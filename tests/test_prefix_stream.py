"""Prefix-sharing paged KV cache + chunked prefill + token streaming
(veles_tpu/serving/pages.py PrefixCache + engine adoption/COW +
GenerationAPI/FleetRouter SSE) — the heavy-traffic request plane.

The contracts under test: pages are refcounted and a shared page
counts ONCE in every gauge; prefix-cache ON answers are bit-identical
to OFF (and to solo decodes) — greedy AND sampled, post-COW
divergence included; a retired writer never mutates a shared page;
injected match corruption degrades to a full prefill (never wrong
tokens); a chunk fault sheds 503 with a resume payload while
co-tenants keep decoding; streamed responses deliver every token
exactly once with a first event strictly before completion; and the
router's streaming proxy resumes token-level across a replica death.
"""
import json
import os
import time
import urllib.error
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import prng
from veles_tpu.nn import sampling
from veles_tpu.serving import ContinuousEngine, PagePool, PrefixCache
from veles_tpu.serving.engine import make_request
from veles_tpu.serving.scheduler import Ticket
from veles_tpu.telemetry.counters import counters

from conftest import import_model


# -- allocator refcounts + prefix index (no jax) ------------------------------

def test_pagepool_refcounts_and_ledger():
    pool = PagePool(6, 4)
    got = pool.alloc(3)
    assert got is not None and pool.in_use() == 3
    assert pool.refcount(got[0]) == 1
    # sharing takes references; free releases one at a time
    assert pool.share(got[0]) == 2
    pool.free([got[0]])
    assert pool.refcount(got[0]) == 1 and pool.in_use() == 3
    pool.free(got)
    assert pool.in_use() == 0 and pool.ledger() == {}
    # a page nobody holds cannot be shared (poisoning guard)
    with pytest.raises(ValueError):
        pool.share(got[0])
    # double free is tolerated like the idempotent slot retire
    pool.free(got)
    assert pool.free_count() == 6


def test_shared_page_counts_once_in_use():
    """Satellite fix: ``in_use`` (and so the fragmentation gauge and
    fleet pages_in_use aggregation) counts a page shared by N holders
    exactly once."""
    pool = PagePool(4, 8)
    page = pool.alloc(1)[0]
    for _ in range(5):
        pool.share(page)
    assert pool.in_use() == 1
    assert pool.refcount(page) == 6


def test_prefix_cache_match_insert_and_divergence():
    pool = PagePool(8, 2)
    cache = PrefixCache(pool, 2)
    pages = pool.alloc(3)
    assert cache.insert([1, 2, 3, 4, 5, 6], pages) == 3
    # full match walks all three blocks, in order, sharing each
    m = cache.match([1, 2, 3, 4, 5, 6, 9])
    assert m == pages
    assert all(pool.refcount(p) == 3 for p in m)   # slot+tree+match
    pool.free(m)
    # divergence in block 2 stops the walk after block 1
    m = cache.match([1, 2, 7, 7, 5, 6])
    assert m == pages[:1]
    pool.free(m)
    # partial trailing block never matches (blocks are page_size)
    assert cache.match([1, 2, 3]) == [pages[0]]
    pool.free([pages[0]])
    # re-inserting the same blocks dedupes (tree keeps its pages)
    other = pool.alloc(2)
    assert cache.insert([1, 2, 3, 4], other) == 0
    pool.free(other)
    pool.free(pages)
    cache.clear()
    assert pool.ledger() == {}


def test_prefix_cache_lru_leaf_eviction_under_pressure():
    """Allocator pressure evicts least-recently-used LEAF blocks via
    the pool's evictor hook before any caller is refused."""
    pool = PagePool(4, 2)
    cache = PrefixCache(pool, 2)
    pool.evictor = cache.evict
    a = pool.alloc(2)
    cache.insert([1, 2, 3, 4], a)
    pool.free(a)                    # only the tree holds both now
    b = pool.alloc(2)
    cache.insert([9, 9, 8, 8], b)
    pool.free(b)
    assert pool.free_count() == 0
    # touch the [1,2] chain so the [9,9] chain is LRU
    pool.free(cache.match([1, 2, 3, 4]))
    ev0 = counters.get("veles_prefix_evictions_total")
    got = pool.alloc(2)             # forces eviction of the LRU chain
    assert got is not None
    assert counters.get("veles_prefix_evictions_total") - ev0 == 2
    assert cache.match([9, 9, 8, 8]) == []          # evicted
    kept = cache.match([1, 2, 3, 4])
    assert len(kept) == 2                           # survivors
    pool.free(kept)
    pool.free(got)
    cache.clear()
    assert pool.ledger() == {}


def test_new_fault_points_registered():
    from veles_tpu.resilience.faults import list_points
    points = list_points()
    assert "serve.prefix_match" in points
    assert "serve.prefill_chunk" in points


# -- engine: id-exactness under sharing ---------------------------------------

@pytest.fixture(scope="module")
def served():
    lm = import_model("char_lm")
    prng.seed_all(1511)
    wf = lm.build_workflow(epochs=1, minibatch_size=64, n_blocks=2,
                           dim=32, n_train=256, n_valid=64)
    wf.initialize(device=vt.XLADevice(mesh_axes={"data": 1}))
    wf.run()
    yield lm, wf


@pytest.fixture(scope="module")
def prefix_engine(served):
    lm, wf = served
    engine = ContinuousEngine(wf, max_slots=3, buckets=(8, 16, 32),
                              max_context=48, page_size=8,
                              prefix_cache=True, prefill_chunk=8,
                              name="prefix_t").start()
    yield engine
    engine.stop()


def _corpus(lm, seed, length):
    return [int(t) for t in
            lm.make_corpus(numpy.random.RandomState(seed), length)]


def test_prefix_on_id_exact_greedy_and_sampled(served, prefix_engine):
    """THE acceptance bar: greedy AND sampled decodes with the prefix
    cache on are bit-identical to prefix-cache off AND to solo
    decodes — cold (miss), warm (adoption) and mixed-tenancy."""
    lm, wf = served
    engine = prefix_engine
    shared = _corpus(lm, 7, 16)              # two full 8-token blocks
    reqs = []
    for i in range(4):
        reqs.append(make_request(
            shared + _corpus(lm, 100 + i, 4), 6,
            temperature=0.8 if i % 2 else 0.0,
            seed=40 + i, mode="sample" if i % 2 else "greedy"))
    solo = [sampling.generate(wf, r["prompt"], r["n_new"],
                              temperature=r["temperature"],
                              seed=r["seed"]) for r in reqs]
    hits0 = counters.get("veles_prefix_hits_total")
    # cold wave: misses, full (chunked) prefills — still id-exact
    assert engine.serve([dict(r) for r in reqs]) == solo
    # warm wave: every admission adopts the shared blocks
    assert engine.serve([dict(r) for r in reqs]) == solo
    assert counters.get("veles_prefix_hits_total") - hits0 >= 4
    assert counters.get("veles_prefix_shared_pages_total") > 0


def test_full_prompt_match_cow_and_post_cow_divergence(served,
                                                      prefix_engine):
    """A FULL-prompt match re-computes only its last position — into a
    copy-on-write duplicate of the last shared page — and a later
    request diverging inside the shared region still answers its own
    solo decode (post-COW divergence, test-locked)."""
    lm, wf = served
    engine = prefix_engine
    prompt = _corpus(lm, 9, 16)           # exactly two full blocks
    solo = sampling.generate(wf, prompt, 5, temperature=0)
    cow0 = counters.get("veles_prefix_cow_copies_total")
    assert engine.serve([make_request(prompt, 5)])[0] == solo
    # second serve fully matches the now-cached prompt -> COW
    assert engine.serve([make_request(prompt, 5)])[0] == solo
    assert counters.get("veles_prefix_cow_copies_total") > cow0
    # divergent second block: matches only block 0, answers its own
    divergent = prompt[:8] + _corpus(lm, 31, 8)
    solo_div = sampling.generate(wf, divergent, 5, temperature=0)
    assert engine.serve([make_request(divergent, 5)])[0] == solo_div
    # sampled full-match rides the same COW path id-exactly
    solo_s = sampling.generate(wf, prompt, 5, temperature=0.7, seed=3)
    assert engine.serve([make_request(prompt, 5, temperature=0.7,
                                      seed=3, mode="sample")]
                        )[0] == solo_s


def test_chunked_prefill_id_exact_without_prefix_cache(served):
    """prefill_chunk alone (no sharing) must be bit-identical to the
    monolithic bucketed prefill."""
    lm, wf = served
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8, 32),
                              max_context=48, page_size=8,
                              prefix_cache=False, prefill_chunk=8,
                              name="chunk_t").start()
    try:
        reqs = [make_request(_corpus(lm, 50 + i, 20), 6,
                             temperature=0.6 if i % 2 else 0.0,
                             seed=60 + i,
                             mode="sample" if i % 2 else "greedy")
                for i in range(3)]
        solo = [sampling.generate(wf, r["prompt"], r["n_new"],
                                  temperature=r["temperature"],
                                  seed=r["seed"]) for r in reqs]
        assert engine.serve(reqs) == solo
        assert engine.chunk_dispatches >= 3
        assert ("pchunk", None) in engine._progs
        assert engine.programs_built <= engine.programs_bound()
    finally:
        engine.stop()


# -- poisoning + ledger -------------------------------------------------------

def test_retired_writer_never_mutates_shared_page(served):
    """THE poisoning regression: after a writer retires, its cached
    (now shared) pages keep their exact bytes through adoption by a
    second slot, that slot's decode writes, its retirement, AND page
    reuse by unrelated traffic — write-after-retire and the COW
    divergence path both covered; the refcount ledger balances to
    zero after the churn."""
    lm, wf = served
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8, 16),
                              max_context=32, page_size=8,
                              prefix_cache=True, prefill_chunk=8,
                              name="poison_t").start()
    try:
        prompt = _corpus(lm, 11, 16)
        engine.serve([make_request(prompt, 4)])
        shared_pages = engine.prefix_cache.cached_pages()
        assert len(shared_pages) == 2
        kp0 = numpy.asarray(engine._caches[0][0])
        before = {p: kp0[p].copy() for p in shared_pages}
        # adoption + decode + retire (a full-prompt match also runs
        # the COW path), then unrelated traffic reusing freed pages
        engine.serve([make_request(prompt, 6)])
        engine.serve([make_request(prompt[:8] + _corpus(lm, 12, 8),
                                   6)])
        engine.serve([make_request(_corpus(lm, 13, 14), 8, seed=5)])
        kp0 = numpy.asarray(engine._caches[0][0])
        for p, content in before.items():
            assert (kp0[p] == content).all(), \
                "shared page %d mutated after its writer retired" % p
        assert engine.scheduler.busy_count() == 0
        # every page now held only by the prefix index
        ledger = engine.page_pool.ledger()
        assert all(rc == 1 for rc in ledger.values())
        cached = set(engine.prefix_cache.cached_pages())
        assert set(ledger) == cached
    finally:
        engine.stop()
    # stop() cleared the index: the ledger balances to zero
    assert engine.page_pool.ledger() == {}
    assert engine.page_pool.in_use() == 0
    assert engine.page_pool.free_count() == engine.pages


def test_stats_truthful_under_sharing(served, prefix_engine):
    """Fragmentation/occupancy stats count a shared page once: the
    occupied estimate can never exceed in_use x page_size (the
    pre-fix per-slot sum did under sharing), and cached blocks report
    as fully occupied."""
    lm, wf = served
    engine = prefix_engine
    prompt = _corpus(lm, 17, 16)
    engine.serve([make_request(prompt, 4)])
    engine.serve([make_request(prompt + _corpus(lm, 18, 4), 4)])
    st = engine.stats()
    assert st["prefix_cache"] == 1
    assert st["prefix_blocks"] >= 2
    assert 0.0 <= st["page_fragmentation"] <= 1.0
    in_use = engine.page_pool.in_use()
    assert in_use >= st["prefix_blocks"]


# -- chaos --------------------------------------------------------------------

def test_prefix_match_fault_degrades_to_full_prefill(served,
                                                     prefix_engine,
                                                     monkeypatch):
    """Injected index loss (raise) AND index rot (corrupt) both
    degrade to a full prefill — identical tokens, never wrong ones."""
    lm, wf = served
    engine = prefix_engine
    prompt = _corpus(lm, 21, 16) + _corpus(lm, 22, 4)
    solo = sampling.generate(wf, prompt, 5, temperature=0)
    assert engine.serve([make_request(prompt, 5)])[0] == solo  # warm
    faults0 = counters.get("veles_faults_injected_total")
    monkeypatch.setenv("VELES_FAULTS", "serve.prefix_match:raise")
    assert engine.serve([make_request(prompt, 5)])[0] == solo
    monkeypatch.setenv("VELES_FAULTS", "serve.prefix_match:corrupt")
    assert engine.serve([make_request(prompt, 5)])[0] == solo
    monkeypatch.setenv("VELES_FAULTS", "")
    assert counters.get("veles_faults_injected_total") - faults0 >= 2
    # and the cache still works after the chaos
    hits0 = counters.get("veles_prefix_hits_total")
    assert engine.serve([make_request(prompt, 5)])[0] == solo
    assert counters.get("veles_prefix_hits_total") - hits0 == 1


def test_prefill_chunk_fault_sheds_503_with_resume_payload(
        served, monkeypatch):
    """An injected chunk fault sheds THAT admission 503 + Retry-After
    with a resume payload while the in-flight co-tenant decodes to
    its exact solo answer."""
    lm, wf = served
    engine = ContinuousEngine(wf, max_slots=2, buckets=(8, 32),
                              max_context=48, page_size=8,
                              prefix_cache=False, prefill_chunk=8,
                              name="chaos_chunk_t").start()
    try:
        cotenant = make_request(_corpus(lm, 25, 6), 16, seed=2)
        solo = sampling.generate(wf, cotenant["prompt"], 16,
                                 temperature=0)
        t_co = Ticket()
        assert engine.submit(cotenant, t_co)
        # wait until the co-tenant is PAST its own prefill chunk (its
        # first token exists) so the armed fault can only hit the
        # long admission's chunks
        deadline = time.time() + 30
        while t_co.first_token is None and time.time() < deadline:
            time.sleep(0.005)
        assert t_co.first_token is not None
        shed0 = counters.get("veles_shed_requests_total")
        monkeypatch.setenv("VELES_FAULTS",
                           "serve.prefill_chunk:raise:times=1")
        t_long = Ticket(mode="greedy")
        assert engine.submit(make_request(_corpus(lm, 26, 20), 4),
                             t_long)
        assert t_long.event.wait(60)
        monkeypatch.setenv("VELES_FAULTS", "")
        assert t_long.code == 503 and t_long.retry_after
        body = t_long.error_payload()
        assert body["resume"] == {"tokens": [], "tokens_done": 0}
        assert counters.get("veles_shed_requests_total") == shed0 + 1
        assert t_co.event.wait(60)
        assert t_co.result["tokens"] == solo
    finally:
        engine.stop()


# -- streaming ----------------------------------------------------------------

def _post_stream(url, payload, timeout=60.0):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    events, t_first = [], None
    t0 = time.time()
    with urllib.request.urlopen(req, timeout=timeout) as r:
        ctype = r.headers.get("Content-Type", "")
        for line in r:
            line = line.strip()
            if not line.startswith(b"data:"):
                continue
            ev = json.loads(line[5:])
            if t_first is None and ev.get("tokens"):
                t_first = time.time() - t0
            events.append(ev)
    return ctype, events, t_first, time.time() - t0


@pytest.fixture(scope="module")
def api_served(served):
    lm, wf = served
    api = vt.GenerationAPI(wf, port=0, engine="continuous",
                           max_slots=2, buckets=(8, 16, 32),
                           max_context=48, prefix_cache=True,
                           prefill_chunk=8, name="stream_api_t")
    api.initialize()
    yield api
    api.stop()


def test_http_stream_sse_id_exact_and_first_event_early(served,
                                                        api_served):
    lm, wf = served
    url = "http://127.0.0.1:%d/generate" % api_served.port
    prompt = _corpus(lm, 33, 6)
    expected = sampling.generate(wf, prompt, 12, temperature=0)
    ctype, events, t_first, t_total = _post_stream(
        url, {"prompt": prompt, "n_new": 12, "stream": True})
    assert "text/event-stream" in ctype
    toks = [t for ev in events if not ev.get("done")
            for t in ev["tokens"]]
    final = events[-1]
    assert toks == expected
    assert final.get("done") and final["tokens"] == expected
    assert "request_id" in final
    assert t_first is not None and t_first < t_total
    # TTFT histogram stamped a real sample for the streamed request
    from veles_tpu.telemetry.counters import histograms
    assert histograms.count("veles_serving_ttft_seconds") > 0
    # a sampled stream is id-exact too
    exp_s = sampling.generate(wf, prompt, 8, temperature=0.7, seed=9)
    _ct, events, _tf, _tt = _post_stream(
        url, {"prompt": prompt, "n_new": 8, "stream": True,
              "mode": "sample", "temperature": 0.7, "seed": 9})
    assert events[-1]["tokens"] == exp_s


def test_stream_knob_off_answers_buffered(served, api_served):
    from veles_tpu.config import root
    lm, wf = served
    url = "http://127.0.0.1:%d/generate" % api_served.port
    prompt = _corpus(lm, 34, 5)
    root.common.serving.stream = False
    try:
        req = urllib.request.Request(
            url, data=json.dumps({"prompt": prompt, "n_new": 4,
                                  "stream": True}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert "application/json" in r.headers.get("Content-Type")
            body = json.loads(r.read())
        assert body["tokens"] == sampling.generate(wf, prompt, 4,
                                                   temperature=0)
    finally:
        root.common.serving.stream = True


def test_router_stream_proxies_and_resumes_across_death(served,
                                                        monkeypatch):
    """THE streaming acceptance drill: a 2-replica fleet streams
    through the router; ``serve.replica_death`` kills the serving
    replica mid-stream; the failover RESUMES from the forwarded
    prefix — the client's wire sees every token exactly once, the
    final event matches the solo decode, and ``resumed_from``
    reports the carried prefix."""
    from veles_tpu.serving.router import FleetRouter
    lm, wf = served
    apis = [vt.GenerationAPI(wf, port=0, engine="continuous",
                             max_slots=2, buckets=(8, 16),
                             max_context=48,
                             name="sdeath_t_%d" % i)
            for i in range(2)]
    for api in apis:
        api.initialize()
    router = FleetRouter(
        ["127.0.0.1:%d" % api.port for api in apis],
        probe_interval=0.2, failure_threshold=1, retry_budget=2,
        attempt_timeout=60.0, name="stream_router_t").start()
    try:
        prompt = _corpus(lm, 35, 5)
        n_new = 12
        expected = sampling.generate(wf, prompt, n_new, temperature=0)
        # warm both replicas outside the armed window
        for api in apis:
            _post_stream("http://127.0.0.1:%d/generate" % api.port,
                         {"prompt": prompt, "n_new": 3,
                          "stream": True})
        ra0 = counters.get("veles_resume_attempts_total")
        monkeypatch.setenv("VELES_FAULTS",
                           "serve.replica_death:raise:after=4,times=1")
        _ct, events, _tf, _tt = _post_stream(
            "http://127.0.0.1:%d/generate" % router.port,
            {"prompt": prompt, "n_new": n_new, "stream": True},
            timeout=90.0)
        monkeypatch.setenv("VELES_FAULTS", "")
        toks = [t for ev in events if not ev.get("done")
                for t in ev["tokens"]]
        final = events[-1]
        assert toks == expected          # exactly once, in order
        assert final.get("done") and final["tokens"] == expected
        assert final.get("resumed_from", 0) >= 1
        assert counters.get("veles_resume_attempts_total") > ra0
    finally:
        router.stop()
        for api in apis:
            api.stop()


# -- registration hygiene ------------------------------------------------------

def test_check_counters_passes_with_prefix_counters():
    """The static registration pass (and its --docs mode) stays green
    with the prefix counters — tier-1-hooked here like the tensormon
    and fleet-tracing suites hook it."""
    import importlib
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    try:
        check_counters = importlib.import_module("check_counters")
        assert check_counters.main([]) == 0
        assert check_counters.main(["--docs"]) == 0
    finally:
        sys.path.pop(0)


def test_prefix_bench_section_and_gate_registration(monkeypatch):
    """The bench doc's prefix section stamps the five counters and
    gate_prefix fails a doc that carries leakage (live proof stubbed
    — it runs inside ``python bench.py gate``, not tier-1)."""
    import bench
    section = bench._prefix_section()
    assert sorted(section) == ["cow_copies", "evictions", "hits",
                               "misses", "shared_pages"]
    from veles_tpu.serving import PREFIX_COUNTERS
    from veles_tpu.telemetry.counters import DESCRIPTIONS
    for name in PREFIX_COUNTERS:
        assert name in DESCRIPTIONS
    monkeypatch.setattr(bench, "_prefix_sharing_proof", lambda: [])
    leaky = {"prefix": {"hits": 3, "misses": 0, "shared_pages": 2,
                        "cow_copies": 0, "evictions": 0},
             "serving": {"serving_bench": False}}
    failures = [f for f in bench.gate_prefix(leaky, None)
                if "leaked" in f]
    assert len(failures) == 2          # hits + shared_pages
    # a serving-mode bench document shares on purpose — not a leak
    serving_doc = dict(leaky, serving={"serving_bench": True})
    assert not [f for f in bench.gate_prefix(serving_doc, None)
                if "leaked" in f]
