"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY.md §4): tests run on a cheap,
always-available backend. Here that is the XLA CPU backend with 8 virtual
devices, so every sharding/collective test exercises a real 8-device mesh
without TPU hardware (the reference used in-process loopback ZeroMQ for the
same purpose, veles/tests/test_network.py).

Must run before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("VELES_TPU_TEST", "1")

# the tunnelled-TPU plugin overrides JAX_PLATFORMS at import time; pin the
# config explicitly — this must happen before any backend is initialized
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running e2e tests, excluded from tier-1 via "
        "-m 'not slow'")


def import_model(name):
    """Import models/<name>.py as a module (models/ is not a package —
    mirrors the reference's import_file machinery, veles/import_file.py).
    Shared by model-zoo CI and feature tests."""
    import importlib.util
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, "models", name + ".py")
    spec = importlib.util.spec_from_file_location("models_" + name, path)
    mod = importlib.util.module_from_spec(spec)
    _sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod
