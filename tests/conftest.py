"""Test harness configuration.

Mirrors the reference's test strategy (SURVEY.md §4): tests run on a cheap,
always-available backend. Here that is the XLA CPU backend with 8 virtual
devices, so every sharding/collective test exercises a real 8-device mesh
without TPU hardware (the reference used in-process loopback ZeroMQ for the
same purpose, veles/tests/test_network.py).

Must run before jax is imported anywhere.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("VELES_TPU_TEST", "1")

# the tunnelled-TPU plugin overrides JAX_PLATFORMS at import time; pin the
# config explicitly — this must happen before any backend is initialized
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
