"""Elastic, preemption-tolerant training (veles_tpu/resilience/
elastic.py): generation lifecycle, host-loss detection, survivor
barrier, manifest cursor, quarantine link repair, the respawn
Supervisor, the falsifiable scaling model, and the bench gate.

Tier-1 scope: unit math, fault/counter plumbing and the in-process
single-host chaos round-trip (injected host loss mid-epoch → new
generation resumes from the newest valid checkpoint → state tree
equals the uninterrupted run). The multi-process kill drill and the
N=4 → N=2/N=8 reshard round-trip spawn real subprocess fleets and ride
the @slow lane (alongside tests/test_multihost.py's coordinator-kill).
"""
import json
import os
import subprocess
import sys
import textwrap
import time

import numpy
import pytest

import veles_tpu as vt
from veles_tpu import nn, prng
from veles_tpu.config import root
from veles_tpu.launcher import Launcher
from veles_tpu.loader import FullBatchLoader
from veles_tpu.resilience import checkpoint_chain, faults
from veles_tpu.resilience import elastic
from veles_tpu.resilience.elastic import (
    ELASTIC_COUNTERS, GENERATION_EXIT_CODE, HostLostError, Supervisor,
    generation_barrier, predict_step_time, psum_bytes_per_step)
from veles_tpu.resilience.health import HeartbeatRegistry, heartbeats
from veles_tpu.telemetry.counters import DESCRIPTIONS, counters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _elastic_state_guard():
    """Every test leaves the elastic knob/gauge state and the host
    heartbeats the way it found them."""
    saved = elastic.state()
    enabled = root.common.resilience.elastic.get("enabled", False)
    yield
    root.common.resilience.elastic.enabled = enabled
    elastic._set_state(**saved)
    for name in list(heartbeats.status()):
        if name.startswith(elastic.HOST_BEAT_PREFIX):
            heartbeats.unregister(name)


# ---------------------------------------------------------------------------
# fault points + counters
# ---------------------------------------------------------------------------

def test_fault_points_registered():
    points = faults.list_points()
    assert "distributed.host_loss" in points
    assert "distributed.generation_barrier" in points


def test_elastic_counters_registered():
    for name in ELASTIC_COUNTERS + (
            "veles_manifest_cursor_defaults_total",):
        assert name in DESCRIPTIONS, name


def test_check_hosts_injected_fault_raises_host_lost(monkeypatch):
    monkeypatch.setenv("VELES_FAULTS",
                       "distributed.host_loss:raise:times=1")
    faults.plane.configure()
    with pytest.raises(HostLostError):
        elastic.check_hosts(registry=HeartbeatRegistry())
    monkeypatch.delenv("VELES_FAULTS")
    faults.plane.configure()
    elastic.check_hosts(registry=HeartbeatRegistry())  # clean: no-op


def test_check_hosts_heartbeat_lapse(monkeypatch):
    monkeypatch.delenv("VELES_FAULTS", raising=False)
    faults.plane.configure()
    reg = HeartbeatRegistry()
    reg.beat("host:7", timeout=0.01)
    reg.beat("not_a_host", timeout=0.01)   # non-host lapses don't trip
    time.sleep(0.03)
    with pytest.raises(HostLostError) as e:
        elastic.check_hosts(registry=reg)
    assert "host:7" in str(e.value)
    # the loss was DECLARED: the lapsed entry is dropped, so the next
    # generation's probe does not instantly re-raise on the same beat
    assert "host:7" not in reg.status()
    reg.unregister("not_a_host")
    elastic.check_hosts(registry=reg)
    reg.beat("host:7", timeout=60.0)       # a returning host re-joins
    elastic.check_hosts(registry=reg)


def test_generation_barrier_failure_counted(monkeypatch):
    monkeypatch.setenv("VELES_FAULTS",
                       "distributed.generation_barrier:raise:times=1")
    faults.plane.configure()
    before = counters.get("veles_elastic_barrier_timeouts_total")
    with pytest.raises(HostLostError):
        generation_barrier(3, timeout=1.0)
    assert counters.get("veles_elastic_barrier_timeouts_total") \
        == before + 1
    monkeypatch.delenv("VELES_FAULTS")
    faults.plane.configure()
    # single process, clean: the barrier agrees with itself
    assert generation_barrier(4) == 4


def test_generation_barrier_timeout_enforced(monkeypatch):
    """A dead peer never arrives at the collective: the barrier's
    watchdog thread abandons the wait after generation_timeout and the
    overrun is counted."""
    monkeypatch.delenv("VELES_FAULTS", raising=False)
    faults.plane.configure()
    from veles_tpu.parallel import distributed
    monkeypatch.setattr(distributed, "survivor_barrier",
                        lambda g: time.sleep(30))
    before = counters.get("veles_elastic_barrier_timeouts_total")
    t0 = time.time()
    with pytest.raises(HostLostError) as e:
        generation_barrier(2, timeout=0.2)
    assert time.time() - t0 < 5
    assert "timed out" in str(e.value)
    assert counters.get("veles_elastic_barrier_timeouts_total") \
        == before + 1


def test_repair_skips_tmp_link_debris(tmp_path):
    """A crash between symlink() and os.replace() leaves a
    *_current.pickle*.tmp — quarantine's link repair must ignore the
    debris instead of minting a second pseudo-current link."""
    paths, link = _fake_chain(tmp_path, n=2)
    tmp_link = str(tmp_path / "wf_current.pickle.tmp")
    os.symlink("nonexistent.pickle", tmp_link)
    checkpoint_chain.quarantine(paths[-1])
    assert os.readlink(link) == os.path.basename(paths[-2])
    # the debris was never "repaired" into a second live current link:
    # it is either consumed as the atomic repoint's scratch name or
    # left as-is — never left pointing at a chain survivor
    assert not os.path.lexists(tmp_link) \
        or os.readlink(tmp_link) == "nonexistent.pickle"


def test_gauges_no_rows_until_enabled():
    elastic._set_state(enabled=False)
    assert elastic.gauges() == {}
    elastic._set_state(enabled=True, generation=2, world_size=3,
                       last_reshard_s=0.25, min_hosts=1)
    g = elastic.gauges()
    assert g["veles_elastic_generation"][0] == 2
    assert g["veles_elastic_world_size"][0] == 3
    assert g["veles_elastic_last_reshard_seconds"][0] == 0.25


# ---------------------------------------------------------------------------
# manifest cursor
# ---------------------------------------------------------------------------

def test_cursor_roundtrip_and_legacy_defaults(tmp_path):
    snap = tmp_path / "wf_x_0001.pickle"
    snap.write_bytes(b"payload")
    checkpoint_chain.write_manifest(
        str(snap), cursor={"epoch": 5, "step": 42, "world_size": 4})
    assert checkpoint_chain.cursor_of(str(snap)) == {
        "epoch": 5, "step": 42, "world_size": 4}

    # legacy manifest (pre-cursor): defaults + counted warning, no crash
    legacy = tmp_path / "wf_y_0001.pickle"
    legacy.write_bytes(b"old")
    checkpoint_chain.write_manifest(str(legacy))
    before = counters.get("veles_manifest_cursor_defaults_total")
    assert checkpoint_chain.cursor_of(str(legacy)) == \
        checkpoint_chain.CURSOR_DEFAULT
    assert counters.get("veles_manifest_cursor_defaults_total") \
        == before + 1

    # partial cursor: present keys kept, missing ones defaulted+counted
    partial = tmp_path / "wf_z_0001.pickle"
    partial.write_bytes(b"p")
    checkpoint_chain.write_manifest(str(partial), cursor={"epoch": 9})
    cur = checkpoint_chain.cursor_of(str(partial))
    assert cur["epoch"] == 9 and cur["world_size"] == 1
    assert counters.get("veles_manifest_cursor_defaults_total") \
        == before + 2

    # no manifest at all: defaults, counted, never a crash
    bare = tmp_path / "wf_w_0001.pickle"
    bare.write_bytes(b"b")
    assert checkpoint_chain.cursor_of(str(bare)) == \
        checkpoint_chain.CURSOR_DEFAULT


def test_latest_cursor_walks_newest_first(tmp_path):
    older = tmp_path / "wf_a_0001.pickle"
    older.write_bytes(b"a")
    checkpoint_chain.write_manifest(
        str(older), cursor={"epoch": 1, "step": 4, "world_size": 2})
    time.sleep(0.02)
    newer = tmp_path / "wf_a_0002.pickle"
    newer.write_bytes(b"b")
    checkpoint_chain.write_manifest(
        str(newer), cursor={"epoch": 2, "step": 8, "world_size": 2})
    path, cur = checkpoint_chain.latest_cursor(str(tmp_path), "wf")
    assert path == str(newer) and cur["epoch"] == 2
    assert checkpoint_chain.latest_cursor(str(tmp_path), "nope") is None


# ---------------------------------------------------------------------------
# quarantine link repair (the __main__ silent-rerun seam)
# ---------------------------------------------------------------------------

def _fake_chain(tmp_path, prefix="wf", n=2):
    """n fake verified snapshots, oldest→newest, plus a _current link
    pointing at the newest (what Snapshotter leaves behind)."""
    paths = []
    for i in range(1, n + 1):
        p = tmp_path / ("%s_t_%04d.pickle" % (prefix, i))
        p.write_bytes(b"state-%d" % i)
        checkpoint_chain.write_manifest(
            str(p), cursor={"epoch": i, "step": i, "world_size": 1})
        os.utime(p, (time.time() - (n - i), time.time() - (n - i)))
        paths.append(str(p))
    link = tmp_path / ("%s_current.pickle" % prefix)
    os.symlink(os.path.basename(paths[-1]), str(link))
    return paths, str(link)


def test_quarantine_repoints_current_link(tmp_path):
    paths, link = _fake_chain(tmp_path)
    # bitrot the newest; the chain walk quarantines it
    with open(paths[-1], "r+b") as f:
        f.write(b"XX")
    found = checkpoint_chain.load_latest(str(tmp_path), "wf")
    # fake payloads don't unpickle: the whole chain quarantines — the
    # point here is the LINK, not the payloads
    assert found is None
    assert os.path.exists(paths[-1] + ".corrupt")
    # the link was repointed at the older entry while it survived,
    # then removed when the chain emptied — never left dangling
    assert not os.path.lexists(link) or os.path.exists(link)


def test_quarantine_link_skips_to_older_valid_entry(tmp_path):
    paths, link = _fake_chain(tmp_path, n=3)
    checkpoint_chain.quarantine(paths[-1])
    # the link now points at the next-newest valid-named snapshot
    assert os.path.islink(link) and os.path.exists(link)
    assert os.readlink(link) == os.path.basename(paths[-2])
    # idempotent: a second quarantine pass (rerun) keeps it valid
    checkpoint_chain.quarantine(paths[-2])
    assert os.readlink(link) == os.path.basename(paths[-3])
    # chain empties -> link removed, not dangling
    checkpoint_chain.quarantine(paths[-3])
    assert not os.path.lexists(link)


# ---------------------------------------------------------------------------
# scaling model
# ---------------------------------------------------------------------------

def test_psum_bytes_model():
    assert psum_bytes_per_step(1000, 1) == 0.0
    assert psum_bytes_per_step(1000, 2) == pytest.approx(1000.0)
    assert psum_bytes_per_step(1000, 4) == pytest.approx(1500.0)
    # monotone toward 2x grad bytes as N grows
    assert psum_bytes_per_step(1000, 64) < 2000.0


def test_predict_step_time_states_inputs():
    pred = predict_step_time(0.08, 1e6, 8, device_kind="TPU v4")
    assert pred["predicted_step_s"] == pytest.approx(
        pred["compute_s"] + pred["comm_s"])
    assert pred["compute_s"] == pytest.approx(0.01)
    ins = pred["inputs"]
    assert ins["t1_step_s"] == 0.08
    assert ins["psum_bytes_per_step"] == pytest.approx(1.75e6)
    assert ins["ici_bw_bytes_per_s"] == pytest.approx(2.4e11)
    # unknown chips fall back to the stated loopback-class assumption
    from veles_tpu.telemetry.cost import DEFAULT_ICI_BW
    pred2 = predict_step_time(0.08, 1e6, 8, device_kind="weird")
    assert pred2["inputs"]["ici_bw_bytes_per_s"] == DEFAULT_ICI_BW


def test_scaling_json_carries_model_stamp():
    with open(os.path.join(REPO, "SCALING.json")) as fin:
        doc = json.load(fin)
    model = doc["scaling_model"]
    assert model["per_width"], model
    for row in model["per_width"]:
        assert "predicted_step_s" in row and "measured_step_s" in row
    ins = model["inputs"]
    # the acceptance criterion: prediction inputs STATED
    assert ins["grad_bytes"] > 0
    assert ins["ici_bw_assumed_bytes_per_s"] > 0
    assert "t1_step_s" in ins


# ---------------------------------------------------------------------------
# bench gate
# ---------------------------------------------------------------------------

def test_bench_elastic_section_and_gate():
    sys.path.insert(0, REPO)
    import bench
    sec = bench._elastic_section()
    for key in ("enabled", "generations", "preemptions",
                "reshard_seconds", "barrier_timeouts"):
        assert key in sec
    # clean docs: no failures
    clean = {"elastic": {"enabled": False, "generations": 0,
                         "preemptions": 0, "reshard_seconds": 0.0,
                         "barrier_timeouts": 0}}
    assert bench.gate_elastic(clean, clean) == []
    # leakage: elastic machinery in a non-elastic run fails the gate
    leaky = {"elastic": dict(clean["elastic"], generations=2,
                             reshard_seconds=1.5)}
    fails = bench.gate_elastic(clean, leaky)
    assert any("generations" in f for f in fails)
    assert any("resharding" in f for f in fails)
    # elastic run inside the reshard budget passes...
    on = {"elastic": {"enabled": True, "generations": 3,
                      "preemptions": 2, "reshard_seconds": 1.0,
                      "barrier_timeouts": 0}}
    assert bench.gate_elastic(clean, on) == []
    # ...and a blown budget fails
    slow = {"elastic": dict(on["elastic"],
                            reshard_seconds=10 ** 6)}
    assert any("budget" in f for f in bench.gate_elastic(clean, slow))


def test_supervisor_classifies_loss_vs_restart(tmp_path):
    """Respawn-plane arithmetic on real (trivial) subprocesses: a
    crashed worker is a lost host (world shrinks), a worker exiting
    GENERATION_EXIT_CODE is a healthy survivor (world holds), and a
    clean generation ends the job."""
    log = []

    def spawn(generation, world):
        # the respawn plane exports the generation so worker
        # controllers (and their gauges) continue the job's numbering
        assert os.environ.get(elastic.GENERATION_ENV) \
            == str(generation)
        log.append((generation, world))
        codes = []
        if generation == 1:
            codes = [42] + [GENERATION_EXIT_CODE] * (world - 1)
        elif generation == 2:
            codes = [GENERATION_EXIT_CODE] * world
        else:
            codes = [0] * world
        return [subprocess.Popen([sys.executable, "-c",
                                  "import sys; sys.exit(%d)" % c])
                for c in codes]

    sup = Supervisor(spawn, world_size=3, min_hosts=1,
                     max_generations=5, poll_interval=0.05,
                     reap_timeout=5.0)
    assert sup.run() == 3
    # gen 1: 3 hosts, one dies -> world 2; gen 2: healthy restarts
    # keep world 2; gen 3 completes
    assert log == [(1, 3), (2, 2), (3, 2)]
    # the supervisor's own environment is restored after the run
    assert elastic.GENERATION_ENV not in os.environ


def test_respawned_worker_continues_generation_numbering(
        tmp_path, monkeypatch):
    """A respawned worker seeds its controller from GENERATION_ENV so
    gauges/cursor logs continue the job's true generation count."""
    monkeypatch.delenv("VELES_FAULTS", raising=False)
    faults.plane.configure()
    assert elastic.base_generation() == 1
    monkeypatch.setenv(elastic.GENERATION_ENV, "5")
    assert elastic.base_generation() == 5
    snapdir = tmp_path / "g"
    snapdir.mkdir()
    root.common.resilience.elastic.enabled = True
    prng.seed_all(11)
    wf = _build(snapdir, "gen")
    launcher = Launcher(backend="cpu", random_seed=11)
    launcher.initialize(wf)
    results = launcher.run_elastic()
    assert results["elastic_generations"] == 5
    assert elastic.state()["generation"] == 5
    monkeypatch.setenv(elastic.GENERATION_ENV, "junk")
    assert elastic.base_generation() == 1


def test_supervisor_generation_deadline_reaps_wedged_fleet():
    """A generation where every process wedges (network-partitioned
    peer: nobody exits) is reaped at generation_deadline and respawned
    instead of blocking the respawn plane forever."""
    log = []

    def spawn(generation, world):
        log.append(generation)
        if generation == 1:
            return [subprocess.Popen(
                [sys.executable, "-c", "import time; time.sleep(60)"])
                for _ in range(world)]
        return [subprocess.Popen([sys.executable, "-c", "pass"])
                for _ in range(world)]

    sup = Supervisor(spawn, world_size=2, min_hosts=1,
                     max_generations=3, poll_interval=0.05,
                     reap_timeout=0.3, generation_deadline=1.0)
    t0 = time.time()
    assert sup.run() == 2
    assert time.time() - t0 < 30
    # wedged fleet was reaped (healthy survivors), world held at 2
    assert log == [1, 2]
    assert sup.world == 2


def test_controller_refuses_start_below_min_hosts(tmp_path,
                                                  monkeypatch):
    """A run whose world is already under the floor refuses BEFORE
    training a generation, with the real cause in the error."""
    monkeypatch.delenv("VELES_FAULTS", raising=False)
    faults.plane.configure()
    root.common.resilience.elastic.enabled = True
    root.common.resilience.elastic.min_hosts = 2
    try:
        snapdir = tmp_path / "floor"
        snapdir.mkdir()
        prng.seed_all(11)
        wf = _build(snapdir, "fl")
        launcher = Launcher(backend="cpu", random_seed=11)
        launcher.initialize(wf)
        with pytest.raises(HostLostError) as e:
            launcher.run_elastic()
        assert "min_hosts" in str(e.value)
        assert not checkpoint_chain.chain(str(snapdir), "fl"), \
            "a generation trained despite the floor"
    finally:
        root.common.resilience.elastic.min_hosts = 1


def test_supervisor_min_hosts_floor():
    def spawn(generation, world):
        return [subprocess.Popen([sys.executable, "-c",
                                  "import sys; sys.exit(42)"])
                for _ in range(world)]

    sup = Supervisor(spawn, world_size=2, min_hosts=2,
                     max_generations=4, poll_interval=0.05,
                     reap_timeout=5.0)
    with pytest.raises(HostLostError):
        sup.run()


# ---------------------------------------------------------------------------
# in-process single-host chaos round-trip (the tier-1 acceptance leg)
# ---------------------------------------------------------------------------

class _Blobs(FullBatchLoader):
    hide_from_registry = True

    def load_data(self):
        rng = numpy.random.RandomState(3)
        centers = rng.randn(3, 8) * 3
        y = rng.randint(0, 3, 120).astype(numpy.int32)
        x = (centers[y] + rng.randn(120, 8)).astype(numpy.float32)
        self.create_originals(x, y)
        self.class_lengths = [0, 24, 96]


def _build(snapdir, prefix):
    snap = vt.Snapshotter(None, prefix=prefix, directory=str(snapdir),
                          interval=1)
    return nn.StandardWorkflow(
        name=prefix,
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=_Blobs(None, minibatch_size=24, name="l"),
        loss_function="softmax",
        decision_config=dict(max_epochs=4, fail_iterations=100),
        snapshotter_unit=snap)


def _assert_trees_equal(a, b, path="root"):
    assert type(a) is type(b), (path, type(a), type(b))
    if isinstance(a, dict):
        assert sorted(a) == sorted(b), (path, sorted(a), sorted(b))
        for k in a:
            _assert_trees_equal(a[k], b[k], "%s.%s" % (path, k))
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_trees_equal(x, y, "%s[%d]" % (path, i))
    elif isinstance(a, numpy.ndarray):
        numpy.testing.assert_array_equal(a, b, err_msg=path)
    elif isinstance(a, float):
        assert a == pytest.approx(b), path
    else:
        assert a == b, path


def test_injected_host_loss_resumes_and_matches_uninterrupted(
        tmp_path, monkeypatch):
    """ISSUE acceptance (single-host leg, tier-1): a host-loss fault
    fired mid-epoch ends generation 1; generation 2 restores the
    newest valid checkpoint (epoch cursor logged from the manifest)
    and the completed run's state tree equals an uninterrupted run's
    bit for bit."""
    monkeypatch.delenv("VELES_FAULTS", raising=False)

    # uninterrupted reference
    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    prng.seed_all(11)
    wf = _build(clean_dir, "el")
    launcher = Launcher(backend="cpu", random_seed=11)
    launcher.initialize(wf)
    launcher.run()

    # elastic run: host lost on the 5th train-step dispatch (the fused
    # step runs ~2 dispatches per epoch -> mid-run, after snapshots
    # for epochs 1-2 are already on the chain)
    chaos_dir = tmp_path / "chaos"
    chaos_dir.mkdir()
    root.common.resilience.elastic.enabled = True
    monkeypatch.setenv(
        "VELES_FAULTS", "distributed.host_loss:raise:after=4,times=1")
    faults.plane.configure()
    gen_before = counters.get("veles_elastic_generations_total")
    pre_before = counters.get("veles_elastic_preemptions_total")
    prng.seed_all(11)
    wf2 = _build(chaos_dir, "el")
    launcher2 = Launcher(backend="cpu", random_seed=11)
    launcher2.initialize(wf2)
    results = launcher2.run_elastic()
    monkeypatch.delenv("VELES_FAULTS")
    faults.plane.configure()

    assert results["elastic_generations"] == 2
    assert counters.get("veles_elastic_generations_total") \
        == gen_before + 2
    assert counters.get("veles_elastic_preemptions_total") \
        == pre_before + 1
    assert counters.get("veles_elastic_reshard_seconds_total") > 0
    # the host beat was unregistered with the run — it must not age
    # into a false /healthz failure on a process that keeps serving
    assert not any(n.startswith(elastic.HOST_BEAT_PREFIX)
                   for n in heartbeats.status())

    # the snapshot manifests carry the elastic cursor
    found = checkpoint_chain.latest_cursor(str(chaos_dir), "el")
    assert found is not None
    _, cur = found
    assert cur["epoch"] >= 1 and cur["world_size"] == 1 \
        and cur["step"] > 0

    # converged state tree equals the uninterrupted run
    clean_state = checkpoint_chain.load_latest(str(clean_dir), "el")[1]
    chaos_state = checkpoint_chain.load_latest(str(chaos_dir), "el")[1]
    _assert_trees_equal(chaos_state["__units__"],
                        clean_state["__units__"])
    _assert_trees_equal(chaos_state["__prng__"],
                        clean_state["__prng__"])


# ---------------------------------------------------------------------------
# @slow: multi-process kill drill + cross-width reshard round-trip
# ---------------------------------------------------------------------------

ELASTIC_CHILD = textwrap.dedent("""
    import os, sys
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("XLA_FLAGS", None)      # exactly 1 device per process
    import jax
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, %(repo)r)
    import numpy
    import veles_tpu as vt
    from veles_tpu import nn, prng
    from veles_tpu.config import root
    from veles_tpu.launcher import Launcher
    from veles_tpu.loader import FullBatchLoader

    class Blobs(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(3)
            centers = rng.randn(3, 8) * 3
            y = rng.randint(0, 3, 120).astype(numpy.int32)
            x = (centers[y] + rng.randn(120, 8)).astype(numpy.float32)
            self.create_originals(x, y)
            self.class_lengths = [0, 24, 96]

    pid = int(sys.argv[1]); port = int(sys.argv[2])
    nproc = int(sys.argv[3]); snapdir = sys.argv[4]
    max_epochs = int(sys.argv[5])
    root.common.resilience.elastic.enabled = True
    launcher = Launcher(
        coordinator="127.0.0.1:%%d" %% port if nproc > 1 else None,
        num_processes=nproc if nproc > 1 else None,
        process_id=pid if nproc > 1 else None,
        mesh={"data": nproc}, random_seed=11)
    snap = vt.Snapshotter(None, prefix="esup", directory=snapdir,
                          interval=1)
    wf = nn.StandardWorkflow(
        name="esup",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=Blobs(None, minibatch_size=24, name="l"),
        loss_function="softmax",
        decision_config=dict(max_epochs=max_epochs,
                             fail_iterations=100),
        snapshotter_unit=snap)
    launcher.initialize(wf)
    results = launcher.run_elastic()
    print("RANK%%d DONE generations=%%s epoch=%%d" %% (
        pid, results.get("elastic_generations"),
        wf.decision.epoch_number), flush=True)
""")


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_chaos_host_kill_mid_epoch_supervisor_reshards(tmp_path):
    """ISSUE acceptance (multi-host leg): a 2-process SPMD job loses a
    host mid-epoch to an injected ``distributed.host_loss:crash``
    fault; the Supervisor reaps the wedged survivor, declares
    generation 2 at world 1, and the respawned job reshards from the
    newest valid checkpoint and converges to the same state tree as an
    uninterrupted run (psum-DP equivalence makes the world-size change
    invisible up to summation order)."""
    snapdir = tmp_path / "esup"
    snapdir.mkdir()
    script = tmp_path / "echild.py"
    script.write_text(ELASTIC_CHILD % {"repo": REPO})
    outs = {}

    def spawn(generation, world):
        port = _free_port()
        procs = []
        for pid in range(world):
            env = dict(os.environ)
            env["PYTHONPATH"] = REPO
            env.pop("VELES_FAULTS", None)
            if generation == 1 and pid == 1:
                # the preemption: rank 1 dies on its 5th armed
                # dispatch (mid-epoch 3; epochs 1-2 are on the chain)
                env["VELES_FAULTS"] = \
                    "distributed.host_loss:crash:after=4,times=1"
            p = subprocess.Popen(
                [sys.executable, str(script), str(pid), str(port),
                 str(world), str(snapdir), "6"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=REPO, env=env)
            procs.append(p)
        outs[generation] = procs
        return procs

    sup = Supervisor(spawn, world_size=2, min_hosts=1,
                     max_generations=4, poll_interval=0.2,
                     reap_timeout=20.0)
    final_generation = sup.run()
    assert final_generation >= 2
    assert sup.world == 1
    last = outs[final_generation][0]
    stdout = last.communicate()[0]
    assert "RANK0 DONE" in stdout, stdout[-2000:]

    # uninterrupted reference at world 1, same seed/config
    clean_dir = tmp_path / "clean"
    clean_dir.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("VELES_FAULTS", None)
    r = subprocess.run(
        [sys.executable, str(script), "0", "0", "1", str(clean_dir),
         "6"], capture_output=True, text=True, cwd=REPO, env=env,
        timeout=300)
    assert r.returncode == 0, r.stdout[-3000:]

    chaos = checkpoint_chain.load_latest(str(snapdir), "esup")[1]
    clean = checkpoint_chain.load_latest(str(clean_dir), "esup")[1]
    cu, xu = clean["__units__"], chaos["__units__"]
    assert sorted(cu) == sorted(xu)
    # weights converge to the uninterrupted trajectory (allclose: the
    # 2-proc epochs psum partial sums in a different order)
    for unit_name, sd in cu.items():
        for key, val in sd.items():
            if isinstance(val, numpy.ndarray) \
                    and val.dtype.kind == "f":
                numpy.testing.assert_allclose(
                    xu[unit_name][key], val, rtol=1e-5, atol=1e-6,
                    err_msg="%s.%s" % (unit_name, key))
    assert xu["l"]["epoch_number"] == cu["l"]["epoch_number"]
    # the manifest cursor of the final snapshot records world 1
    _, cur = checkpoint_chain.latest_cursor(str(snapdir), "esup")
    assert cur["world_size"] == 1 and cur["epoch"] >= 5


RESHARD_CHILD = textwrap.dedent("""
    import os, sys
    import numpy
    sys.path.insert(0, %(repo)r)
    import veles_tpu as vt
    from veles_tpu import nn, prng
    from veles_tpu.loader import FullBatchLoader

    class Blobs(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(3)
            centers = rng.randn(3, 8) * 3
            y = rng.randint(0, 3, 120).astype(numpy.int32)
            x = (centers[y] + rng.randn(120, 8)).astype(numpy.float32)
            self.create_originals(x, y)
            self.class_lengths = [0, 24, 96]

    mode = sys.argv[1]; n = int(sys.argv[2])
    snapdir = sys.argv[3]; out = sys.argv[4]
    prng.seed_all(11)
    snap = vt.Snapshotter(None, prefix="rs", directory=snapdir,
                          interval=1)
    wf = nn.StandardWorkflow(
        name="rs",
        layers=[{"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3}],
        loader_unit=Blobs(None, minibatch_size=24, name="l"),
        loss_function="softmax",
        decision_config=dict(max_epochs=2, fail_iterations=100),
        snapshotter_unit=snap)
    dev = vt.XLADevice(mesh_axes={"data": n})
    wf.initialize(device=dev)
    assert wf.train_step.params["all2all_tanh0"][
        "weights"].sharding.num_devices == n or n == 1
    if mode == "save":
        wf.run()
    else:
        from veles_tpu.parallel.distributed import restore_latest
        assert restore_latest(wf, snapdir, "rs"), "nothing to restore"
    # forward logits on a fixed batch through the restored params —
    # the device-count-agnostic snapshot contract: identical at any N
    fwf = wf.extract_forward_workflow()
    from veles_tpu.memory import Array
    x = wf.loader.original_data.mem[:24]
    wf.forwards[0].input = Array(x, name="x")
    fwf.initialize(device=dev)
    fwf.run()
    logits = numpy.asarray(wf.forwards[-1].output.map_read())
    numpy.savez(out, logits=logits,
                w0=numpy.asarray(wf.forwards[0].weights.map_read()))
    print("RESHARD OK n=%%d" %% n, flush=True)
""")


@pytest.mark.slow
def test_reshard_snapshot_n4_restores_at_n2_and_n8(tmp_path):
    """Device-count-agnostic snapshot layout: a snapshot saved on a
    4-device mesh restores on 2- and 8-device meshes with identical
    forward logits (unsharded logical trees on disk, shard on load)."""
    script = tmp_path / "rchild.py"
    script.write_text(RESHARD_CHILD % {"repo": REPO})
    snapdir = tmp_path / "rs"
    snapdir.mkdir()

    def run(mode, n):
        out = str(tmp_path / ("logits_%s_%d.npz" % (mode, n)))
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        env.pop("VELES_FAULTS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_"
                              "count=%d" % n)
        r = subprocess.run(
            [sys.executable, str(script), mode, str(n), str(snapdir),
             out], capture_output=True, text=True, cwd=REPO, env=env,
            timeout=300)
        assert r.returncode == 0, (mode, n, r.stdout[-2000:],
                                   r.stderr[-2000:])
        return numpy.load(out)

    saved = run("save", 4)
    at4 = run("restore", 4)
    at2 = run("restore", 2)
    at8 = run("restore", 8)
    for tag, doc in (("n4", at4), ("n2", at2), ("n8", at8)):
        numpy.testing.assert_allclose(
            doc["logits"], saved["logits"], rtol=1e-6, atol=1e-7,
            err_msg=tag)
        numpy.testing.assert_array_equal(doc["w0"], saved["w0"],
                                         err_msg=tag)


def test_barrier_failure_ends_generation_not_run(tmp_path, monkeypatch):
    """An injected generation-barrier failure is a preemption like any
    other: generation 1 dies at the barrier, generation 2 proceeds and
    the run completes — the barrier failure never kills the whole
    elastic run (single-process leg; multi-process survivors exit 43
    for the respawn plane)."""
    snapdir = tmp_path / "b"
    snapdir.mkdir()
    root.common.resilience.elastic.enabled = True
    monkeypatch.setenv(
        "VELES_FAULTS", "distributed.generation_barrier:raise:times=1")
    faults.plane.configure()
    bt_before = counters.get("veles_elastic_barrier_timeouts_total")
    pre_before = counters.get("veles_elastic_preemptions_total")
    prng.seed_all(11)
    wf = _build(snapdir, "bar")
    launcher = Launcher(backend="cpu", random_seed=11)
    launcher.initialize(wf)
    results = launcher.run_elastic()
    monkeypatch.delenv("VELES_FAULTS")
    faults.plane.configure()
    assert results["elastic_generations"] == 2
    assert counters.get("veles_elastic_barrier_timeouts_total") \
        == bt_before + 1
    assert counters.get("veles_elastic_preemptions_total") \
        == pre_before + 1


def test_resume_via_quarantined_current_link_falls_back(
        tmp_path, monkeypatch):
    """The __main__ silent-rerun seam: `--snapshot <dir>/el_current...`
    after the previous run's newest entry was quarantined (link
    dangles) must skip straight to the older valid snapshot instead of
    dying — the elastic restart is idempotent."""
    monkeypatch.delenv("VELES_FAULTS", raising=False)
    faults.plane.configure()
    snapdir = tmp_path / "snaps"
    snapdir.mkdir()
    prng.seed_all(7)
    wf = _build(snapdir, "el")
    launcher = Launcher(backend="cpu", random_seed=7)
    launcher.initialize(wf)
    launcher.run()
    chain = checkpoint_chain.chain(str(snapdir), "el")
    assert len(chain) >= 2
    # previous run quarantined the newest entry: link dangles
    newest = chain[0]
    os.replace(newest, newest + ".corrupt")
    link = os.path.join(str(snapdir), "el_current.pickle.gz")
    assert os.path.islink(link) and not os.path.exists(link)

    prng.seed_all(7)
    wf2 = _build(snapdir, "el")    # fresh units, same topology
    launcher2 = Launcher(backend="cpu", random_seed=7)
    launcher2.initialize(wf2)
    launcher2.resume(link)          # must fall back, not raise
    assert wf2.restored_from_snapshot
    assert wf2.decision.epoch_number >= 1
