"""Resilience subsystem (veles_tpu/resilience/): deterministic fault
injection, retry/backoff math, crash-safe checkpoint chain, health
endpoints and 503 load shedding — plus the end-to-end chaos round-trip
the ISSUE's acceptance criterion names (snapshot-write crash +
corrupted file → resume equals an uninterrupted run).

Budget discipline: retry math runs on a fake clock (no real sleeps);
the only real sleeps are <= 0.05s fault delays.
"""
import gzip
import json
import os
import pickle
import subprocess
import sys
import textwrap
import time
import urllib.error
import urllib.request

import numpy
import pytest

import veles_tpu as vt
from veles_tpu.config import root
from veles_tpu.error import VelesError
from veles_tpu.resilience import (checkpoint_chain, faults, health,
                                  retry, RESILIENCE_COUNTERS)
from veles_tpu.telemetry.counters import DESCRIPTIONS, counters

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def time(self):
        return self.t

    def sleep(self, d):
        self.sleeps.append(d)
        self.t += d


# ---------------------------------------------------------------------------
# retry policy math
# ---------------------------------------------------------------------------

def _failing(n, exc=OSError):
    """A callable that fails n times, then returns the attempt count."""
    calls = {"n": 0}

    def fn():
        calls["n"] += 1
        if calls["n"] <= n:
            raise exc("boom %d" % calls["n"])
        return calls["n"]
    return fn


def test_backoff_sequence_and_cap():
    fc = FakeClock()
    policy = retry.RetryPolicy(max_attempts=5, base_delay=0.1,
                               max_delay=0.4, jitter=False,
                               sleep=fc.sleep, clock=fc.time)
    before = counters.get("veles_retries_total")
    assert policy.call(_failing(4)) == 5
    # exponential doubling capped at max_delay
    assert fc.sleeps == pytest.approx([0.1, 0.2, 0.4, 0.4])
    assert counters.get("veles_retries_total") - before == 4


def test_exhaustion_reraises_original():
    fc = FakeClock()
    policy = retry.RetryPolicy(max_attempts=3, base_delay=0.1,
                               jitter=False, sleep=fc.sleep,
                               clock=fc.time)
    with pytest.raises(OSError, match="boom 3"):
        policy.call(_failing(10))
    assert len(fc.sleeps) == 2          # retries = attempts - 1


def test_jitter_bounds():
    fc = FakeClock()
    rolls = iter([0.0, 0.5, 0.999] * 10)
    policy = retry.RetryPolicy(max_attempts=4, base_delay=0.2,
                               max_delay=10.0, jitter=True,
                               sleep=fc.sleep, clock=fc.time,
                               rng=lambda: next(rolls))
    # full jitter: delay = raw * u, u ∈ [0, 1)
    assert policy.backoff(1) == pytest.approx(0.2 * 0.0)
    assert policy.backoff(2) == pytest.approx(0.4 * 0.5)
    assert policy.backoff(3) == pytest.approx(0.8 * 0.999)
    for attempt in range(1, 5):
        raw = min(10.0, 0.2 * 2 ** (attempt - 1))
        d = policy.backoff(attempt)
        assert 0.0 <= d < raw + 1e-12


def test_deadline_cutoff_with_fake_clock():
    fc = FakeClock()
    policy = retry.RetryPolicy(max_attempts=50, base_delay=0.4,
                               max_delay=0.4, deadline=1.0,
                               jitter=False, sleep=fc.sleep,
                               clock=fc.time)
    with pytest.raises(OSError):
        policy.call(_failing(100))
    # 0.4 + 0.4 slept; a third retry would land at 1.2 > deadline 1.0,
    # so the policy re-raises instead of sleeping past the budget
    assert fc.sleeps == pytest.approx([0.4, 0.4])
    assert fc.t <= 1.0


def test_non_retryable_raises_immediately():
    fc = FakeClock()
    policy = retry.RetryPolicy(max_attempts=5, retryable=(OSError,),
                               jitter=False, sleep=fc.sleep,
                               clock=fc.time)
    with pytest.raises(ValueError):
        policy.call(_failing(3, exc=ValueError))
    assert fc.sleeps == []


def test_retry_if_predicate():
    fc = FakeClock()
    policy = retry.RetryPolicy(
        max_attempts=5, base_delay=0.1, jitter=False, sleep=fc.sleep,
        clock=fc.time,
        retry_if=lambda e: "retryable" in str(e))
    with pytest.raises(OSError, match="fatal"):
        policy.call(_failing(3, exc=lambda m: OSError("fatal")))
    assert fc.sleeps == []


def test_decorator_and_attempts_context_manager():
    fc = FakeClock()
    policy = retry.RetryPolicy(max_attempts=4, base_delay=0.1,
                               jitter=False, sleep=fc.sleep,
                               clock=fc.time)

    fn = policy(_failing(2))
    assert fn() == 3

    # context-manager loop form
    state = {"n": 0}
    for attempt in policy.attempts():
        with attempt:
            state["n"] += 1
            if state["n"] <= 2:
                raise OSError("cm boom")
    assert state["n"] == 3


def test_attempts_exhaustion_propagates():
    fc = FakeClock()
    policy = retry.RetryPolicy(max_attempts=2, base_delay=0.1,
                               jitter=False, sleep=fc.sleep,
                               clock=fc.time)
    with pytest.raises(OSError):
        for attempt in policy.attempts():
            with attempt:
                raise OSError("always")


# ---------------------------------------------------------------------------
# fault spec parsing + the injection plane
# ---------------------------------------------------------------------------

def test_parse_spec_fields():
    parsed = faults.parse_spec(
        "snapshot.write:crash:after=1,times=2;download:raise:p=0.5;"
        "dispatch:delay:delay=0.01")
    assert [f.point for f in parsed] == ["snapshot.write", "download",
                                         "dispatch"]
    crash, rais, delay = parsed
    assert (crash.action, crash.after, crash.times) == ("crash", 1, 2)
    assert (rais.action, rais.p) == ("raise", 0.5)
    assert (delay.action, delay.delay) == ("delay", 0.01)
    assert faults.parse_spec("") == []
    assert faults.parse_spec("  ;  ") == []


def test_parse_spec_window_field():
    """``window=T0:T1`` (the loadgen chaos-storm clause) parses as a
    (T0, T1) trigger-count window and survives the clause's own colon
    thanks to the maxsplit grammar."""
    (fault,) = faults.parse_spec("dispatch:raise:window=1:3")
    assert fault.window == (1, 3)
    assert "window=1:3" in repr(fault)
    # composes with the other params in one clause
    (fault,) = faults.parse_spec(
        "serve.page_alloc:raise:window=50:80,p=0.5")
    assert fault.window == (50, 80) and fault.p == 0.5


@pytest.mark.parametrize("bad", [
    "nonsense",                       # no action
    "no.such.point:raise",            # unregistered point
    "dispatch:explode",               # unknown action
    "dispatch:raise:frequency=2",     # unknown param
    "dispatch:raise:p=lots",          # unparseable value
    "dispatch:raise:p=1.5",           # probability out of range
    "dispatch:raise:window=3:1",      # empty window (T1 <= T0)
    "dispatch:raise:window=5",        # not a T0:T1 pair
    "dispatch:raise:window=x:y",      # unparseable bounds
    "dispatch:raise:window=-1:3",     # negative trigger count
])
def test_parse_spec_rejects(bad):
    with pytest.raises(VelesError):
        faults.parse_spec(bad)


def test_fire_env_spec_counts_and_exhausts(monkeypatch):
    monkeypatch.setenv("VELES_FAULTS", "loader.batch:raise:times=1")
    before = counters.get("veles_faults_injected_total")
    with pytest.raises(faults.FaultInjected):
        faults.fire("loader.batch")
    # times=1 exhausted: the second hit passes through
    assert faults.fire("loader.batch") is None
    assert counters.get("veles_faults_injected_total") - before == 1


def test_fire_after_skips_first_hits(monkeypatch):
    monkeypatch.setenv("VELES_FAULTS", "download:raise:after=2")
    assert faults.fire("download") is None
    assert faults.fire("download") is None
    with pytest.raises(faults.FaultInjected):
        faults.fire("download")


def test_fire_window_arms_then_heals(monkeypatch):
    """A ``window=1:3`` clause is a timed storm: the first hit passes,
    hits 2..3 fire, and the point HEALS from hit 4 on — trigger-count
    indexed, so the storm is reproducible run-to-run."""
    monkeypatch.setenv("VELES_FAULTS", "download:raise:window=1:3")
    assert faults.fire("download") is None         # hit 1: pre-storm
    with pytest.raises(faults.FaultInjected):
        faults.fire("download")                    # hit 2: armed
    with pytest.raises(faults.FaultInjected):
        faults.fire("download")                    # hit 3: armed
    assert faults.fire("download") is None         # hit 4: healed
    assert faults.fire("download") is None         # ...and stays so


def test_fire_corrupt_returns_fault(monkeypatch):
    monkeypatch.setenv("VELES_FAULTS", "snapshot.write:corrupt")
    fault = faults.fire("snapshot.write")
    assert fault is not None
    blob = b"hello world"
    damaged = fault.corrupt(blob)
    assert damaged != blob and len(damaged) == len(blob)


def test_clean_process_zero_leakage(monkeypatch):
    """The bench gate's resilience contract: with no spec set, firing
    every registered point is a no-op and the counters are untouched."""
    monkeypatch.delenv("VELES_FAULTS", raising=False)
    for name in RESILIENCE_COUNTERS:
        assert name in DESCRIPTIONS
    before = counters.get("veles_faults_injected_total")
    for point in faults.list_points():
        assert faults.fire(point) is None
    assert counters.get("veles_faults_injected_total") == before


def test_probability_is_seeded_deterministic(monkeypatch):
    from veles_tpu import prng
    monkeypatch.setenv("VELES_FAULTS", "dispatch:raise:p=0.5")
    prng.seed_all(123)
    faults.plane.configure()

    def trace(n=20):
        out = []
        for _ in range(n):
            try:
                faults.fire("dispatch")
                out.append(0)
            except faults.FaultInjected:
                out.append(1)
        return out

    first = trace()
    prng.seed_all(123)
    faults.plane.configure()
    assert trace() == first
    assert 0 < sum(first) < 20      # p=0.5 actually mixes


# ---------------------------------------------------------------------------
# checkpoint chain
# ---------------------------------------------------------------------------

def _write_snap(directory, name, state, mtime=None):
    path = os.path.join(directory, name)
    tmp = path + ".tmp"
    with gzip.open(tmp, "wb") as fout:
        fout.write(pickle.dumps(state))
    checkpoint_chain.commit_file(tmp, path)
    checkpoint_chain.write_manifest(path)
    if mtime is not None:
        os.utime(path, (mtime, mtime))
    return path


def _flip_byte(path, offset=None):
    with open(path, "rb") as fin:
        raw = bytearray(fin.read())
    i = len(raw) // 2 if offset is None else offset
    raw[i] ^= 0xFF
    with open(path, "wb") as fout:
        fout.write(raw)


def test_chain_orders_newest_first(tmp_path):
    t0 = time.time() - 100
    for i in range(3):
        _write_snap(str(tmp_path), "wf_%d.pickle.gz" % i,
                    {"i": i}, mtime=t0 + i)
    paths = checkpoint_chain.chain(str(tmp_path), "wf")
    assert [os.path.basename(p) for p in paths] == [
        "wf_2.pickle.gz", "wf_1.pickle.gz", "wf_0.pickle.gz"]


def test_restore_walks_past_corrupt_files(tmp_path):
    t0 = time.time() - 100
    for i in range(3):
        _write_snap(str(tmp_path), "wf_%d.pickle.gz" % i,
                    {"i": i}, mtime=t0 + i)
    newest = os.path.join(str(tmp_path), "wf_2.pickle.gz")
    _flip_byte(newest)
    before = counters.get("veles_snapshots_quarantined_total")
    path, state = checkpoint_chain.load_latest(str(tmp_path), "wf")
    assert os.path.basename(path) == "wf_1.pickle.gz"
    assert state == {"i": 1}
    assert os.path.exists(newest + ".corrupt")
    assert not os.path.exists(newest)
    assert counters.get("veles_snapshots_quarantined_total") - before == 1
    # quarantined files never rejoin the chain
    assert newest not in checkpoint_chain.chain(str(tmp_path), "wf")


def test_all_corrupt_returns_none(tmp_path):
    p = _write_snap(str(tmp_path), "wf_only.pickle.gz", {"x": 1})
    _flip_byte(p)
    assert checkpoint_chain.load_latest(str(tmp_path), "wf") is None


def test_truncated_snapshot_raises_clear_veles_error(tmp_path):
    """Satellite: load_snapshot on a truncated file raises a VelesError
    naming the file, not a bare pickle/EOF error."""
    path = os.path.join(str(tmp_path), "wf_t.pickle.gz")
    with gzip.open(path, "wb") as fout:
        fout.write(pickle.dumps({"big": list(range(10000))}))
    with open(path, "rb") as fin:
        raw = fin.read()
    with open(path, "wb") as fout:
        fout.write(raw[:len(raw) // 2])
    with pytest.raises(VelesError, match="truncated or corrupt"):
        vt.load_snapshot(path)


def test_verify_states(tmp_path):
    path = _write_snap(str(tmp_path), "wf_v.pickle.gz", {"x": 1})
    assert checkpoint_chain.verify(path) is True
    os.unlink(checkpoint_chain.manifest_path(path))
    assert checkpoint_chain.verify(path) is None    # legacy: loadable
    assert vt.load_snapshot(path) == {"x": 1}


def test_prune_bounded_retention(tmp_path):
    t0 = time.time() - 100
    for i in range(5):
        _write_snap(str(tmp_path), "wf_%d.pickle.gz" % i,
                    {"i": i}, mtime=t0 + i)
    removed = checkpoint_chain.prune(str(tmp_path), "wf", keep_last=2)
    assert len(removed) == 6            # 3 snapshots + 3 manifests
    left = checkpoint_chain.chain(str(tmp_path), "wf")
    assert [os.path.basename(p) for p in left] == [
        "wf_4.pickle.gz", "wf_3.pickle.gz"]
    assert not os.path.exists(
        checkpoint_chain.manifest_path(
            os.path.join(str(tmp_path), "wf_0.pickle.gz")))


def test_snapshotter_writes_manifest_atomic_link_and_prunes(tmp_path):
    wf = vt.Workflow(None, name="w")
    snap = vt.Snapshotter(wf, prefix="s", directory=str(tmp_path),
                          keep_last=2)
    paths = []
    for i in range(3):
        snap._runs = i + 1
        paths.append(snap.export())
        os.utime(paths[-1], (time.time() - 10 + i,) * 2)
    assert checkpoint_chain.verify(paths[-1]) is True
    link = os.path.join(str(tmp_path), "s_current.pickle.gz")
    assert os.path.islink(link)
    assert os.readlink(link) == os.path.basename(paths[-1])
    # keep_last=2 pruned the oldest export (+ its manifest)
    assert not os.path.exists(paths[0])
    assert os.path.exists(paths[1]) and os.path.exists(paths[2])
    # the chain restores through the snapshotter's own artifacts
    assert checkpoint_chain.load_latest(str(tmp_path), "s") is not None


def test_snapshotter_corrupt_injection_falls_back(tmp_path, monkeypatch):
    wf = vt.Workflow(None, name="w")
    snap = vt.Snapshotter(wf, prefix="c", directory=str(tmp_path))
    snap._runs = 1
    good = snap.export()
    os.utime(good, (time.time() - 10,) * 2)
    monkeypatch.setenv("VELES_FAULTS", "snapshot.write:corrupt:times=1")
    snap._runs = 2
    bad = snap.export()
    monkeypatch.delenv("VELES_FAULTS")
    assert checkpoint_chain.verify(bad) is False
    path, _state = checkpoint_chain.load_latest(str(tmp_path), "c")
    assert path == good
    assert os.path.exists(bad + ".corrupt")


# ---------------------------------------------------------------------------
# watchdog telemetry (satellite)
# ---------------------------------------------------------------------------

def test_step_watchdog_trip_is_counted():
    from veles_tpu.parallel.distributed import step_watchdog
    history = [1e-4] * 8
    before = counters.get("veles_watchdog_trips_total")
    with step_watchdog("span_name", history=history):
        time.sleep(0.02)                # far beyond mean+3σ of 0.1ms
    assert counters.get("veles_watchdog_trips_total") - before == 1
    assert len(history) == 9            # mean+3σ history still appended

    # a normal step under the threshold does not trip
    history2 = [0.05] * 8
    before = counters.get("veles_watchdog_trips_total")
    with step_watchdog("span_name", history=history2):
        pass
    assert counters.get("veles_watchdog_trips_total") == before


# ---------------------------------------------------------------------------
# health endpoints + load shedding
# ---------------------------------------------------------------------------

def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def _post(url, payload, timeout=10):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_heartbeat_registry_staleness():
    reg = health.HeartbeatRegistry()
    reg.beat("fast", timeout=1000.0)
    reg.beat("slow", timeout=0.0)       # immediately stale
    status = reg.status()
    assert status["fast"]["healthy"] is True
    assert status["slow"]["healthy"] is False
    assert reg.healthy() is False
    reg.unregister("slow")
    assert reg.healthy() is True


def test_workflow_run_beats_then_unregisters():
    """The scheduler loop reports liveness while running and drops the
    beat on completion — only a truly wedged loop ages out."""
    wf = vt.Workflow(None, name="hb_wf")
    wf.initialize()
    wf.run()
    assert "workflow.hb_wf" not in health.heartbeats.status()


def test_web_status_health_endpoints():
    from veles_tpu.web_status import WebStatusServer
    server = WebStatusServer(port=0).start()
    try:
        base = "http://127.0.0.1:%d" % server.port
        code, _, body = _get(base + "/healthz")
        assert code == 200 and body["status"] == "ok"
        code, _, body = _get(base + "/readyz")
        assert code == 200
        assert "components" in body
    finally:
        server.stop()


def test_readiness_transitions_ready_draining_gone():
    """The drain lifecycle on the readiness plane: ready → draining
    (/readyz 503 with status "draining", the component value naming
    it) → gone (forget(): the mark AND the heartbeat drop, /readyz
    back to 200) — while /healthz stays 200 the whole way, because a
    draining process is alive and finishing in-flight work."""
    name = "svc.drainer"
    health.mark_ready(name)
    health.heartbeats.beat(name)
    try:
        code, body = health.readyz()
        assert code == 200 and body["components"][name] is True
        health.mark_draining(name)
        code, body = health.readyz()
        assert code == 503
        assert body["status"] == "draining"
        assert body["components"][name] == "draining"
        # liveness is NOT readiness: the heartbeat is fresh, the
        # process is alive — /healthz stays green throughout
        code, body = health.healthz()
        assert code == 200 and name in body["heartbeats"]
        # a plainly-unready component alongside a draining one makes
        # the page "not ready" (draining no longer explains the 503)
        health.mark_unready("svc.other")
        code, body = health.readyz()
        assert code == 503 and body["status"] == "not ready"
        health.forget("svc.other")
        # gone: the drain finished — mark and heartbeat both drop
        health.forget(name)
        code, body = health.readyz()
        assert code == 200 and name not in body["components"]
        assert name not in health.heartbeats.status()
        assert name not in health.draining()
    finally:
        health.forget(name)
        health.forget("svc.other")


def test_mark_ready_clears_draining_state():
    """A drained service that comes back (respawn) is plainly ready —
    no stale draining mark survives mark_ready/mark_unready."""
    name = "svc.back"
    try:
        health.mark_draining(name)
        assert name in health.draining()
        health.mark_ready(name)
        assert name not in health.draining()
        code, body = health.readyz()
        assert code == 200 and body["components"][name] is True
        health.mark_draining(name)
        health.mark_unready(name)
        # explicitly unready (not draining): the page says so
        code, body = health.readyz()
        assert code == 503 and body["status"] == "not ready"
        assert body["components"][name] is False
    finally:
        health.forget(name)


def test_shed_body_carries_request_id():
    """The satellite contract: a shed's response body includes the
    request_id so a router retry can correlate the 503 with its
    attempt — here via the bounded-queue shed path."""
    wf = vt.Workflow(None, name="w")
    api = vt.GenerationAPI(wf, port=0, max_queue=0, name="rid_g")
    api.initialize()
    try:
        url = "http://127.0.0.1:%d/generate" % api.port
        code, headers, body = _post(
            url, {"prompt": [1, 2, 3], "n_new": 4,
                  "request_id": "req-router-7"})
        assert code == 503
        assert body["request_id"] == "req-router-7"
        assert int(headers.get("Retry-After")) >= 1
    finally:
        api.stop()


def test_generation_api_queue_bound_sheds_503_retry_after():
    wf = vt.Workflow(None, name="w")
    api = vt.GenerationAPI(wf, port=0, max_queue=0, name="shed_g")
    api.initialize()
    try:
        url = "http://127.0.0.1:%d" % api.port
        before = counters.get("veles_shed_requests_total")
        code, headers, body = _post(url + "/generate",
                                    {"prompt": [1, 2, 3], "n_new": 4})
        assert code == 503
        assert int(headers.get("Retry-After")) >= 1
        assert "queue full" in body["error"]
        assert counters.get("veles_shed_requests_total") - before == 1
        # health endpoints ride the same port
        code, _, body = _get(url + "/healthz")
        assert code == 200
        code, _, body = _get(url + "/readyz")
        assert code == 200 and body["components"]["serve.shed_g"] is True
    finally:
        api.stop()


def test_generation_api_injected_fault_sheds_never_raises(monkeypatch):
    wf = vt.Workflow(None, name="w")
    api = vt.GenerationAPI(wf, port=0, max_queue=0, name="fault_g")
    api.initialize()
    try:
        url = "http://127.0.0.1:%d/generate" % api.port
        monkeypatch.setenv("VELES_FAULTS", "serve.request:raise:times=1")
        shed_before = counters.get("veles_shed_requests_total")
        fault_before = counters.get("veles_faults_injected_total")
        code, headers, body = _post(url, {"prompt": [1], "n_new": 1})
        assert code == 503
        assert headers.get("Retry-After") is not None
        assert "injected fault" in body["error"]
        # matching telemetry deltas: one fault fired, one request shed
        assert counters.get("veles_faults_injected_total") \
            - fault_before == 1
        assert counters.get("veles_shed_requests_total") \
            - shed_before == 1
    finally:
        api.stop()


def test_restful_api_pending_bound_sheds(monkeypatch):
    from veles_tpu.loader.stream import RestfulLoader
    wf = vt.Workflow(None, name="w")
    loader = RestfulLoader(wf, sample_shape=(4,), name="rl")
    api = vt.RESTfulAPI(wf, loader=loader, port=0, max_pending=0,
                        name="shed_r")
    api.initialize()
    try:
        url = "http://127.0.0.1:%d/api" % api.port
        before = counters.get("veles_shed_requests_total")
        code, headers, body = _post(url, {"input": [1, 2, 3, 4]})
        assert code == 503
        assert headers.get("Retry-After") is not None
        assert counters.get("veles_shed_requests_total") - before == 1
    finally:
        api.stop()


# ---------------------------------------------------------------------------
# end-to-end chaos round-trip (acceptance criterion)
# ---------------------------------------------------------------------------

CHAOS_MODEL = textwrap.dedent("""
    import numpy
    import veles_tpu as vt
    from veles_tpu import nn
    from veles_tpu.loader import FullBatchLoader

    class L(FullBatchLoader):
        hide_from_registry = True
        def load_data(self):
            rng = numpy.random.RandomState(3)
            centers = rng.randn(3, 8) * 3
            y = rng.randint(0, 3, 300).astype(numpy.int32)
            x = (centers[y] + rng.randn(300, 8)).astype(numpy.float32)
            self.create_originals(x, y)
            self.class_lengths = [0, 60, 240]

    def build_workflow():
        snap = vt.Snapshotter(None, prefix="chaos")
        return nn.StandardWorkflow(
            name="chaos",
            layers=[
                {"type": "all2all_tanh", "output_sample_shape": 8},
                {"type": "softmax", "output_sample_shape": 3},
            ],
            loader_unit=L(None, minibatch_size=24, name="l"),
            loss_function="softmax",
            decision_config=dict(max_epochs=4, fail_iterations=100),
            snapshotter_unit=snap)
""")


def _run_cli(model, snapdir, *argv, env_extra=None, timeout=300):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("VELES_FAULTS", None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m", "veles_tpu", str(model),
         "--snapshot-dir", str(snapdir), "--backend", "cpu",
         "--random-seed", "11", "-v", *argv],
        cwd=REPO, capture_output=True, text=True, timeout=timeout,
        env=env)


def _state_tree_of(snapdir, prefix="chaos"):
    found = checkpoint_chain.load_latest(str(snapdir), prefix)
    assert found is not None, "no valid snapshot in %s" % snapdir
    return found[1]


def _assert_trees_equal(a, b, path="root"):
    assert type(a) is type(b), (path, type(a), type(b))
    if isinstance(a, dict):
        assert sorted(a) == sorted(b), (path, sorted(a), sorted(b))
        for k in a:
            _assert_trees_equal(a[k], b[k], "%s.%s" % (path, k))
    elif isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _assert_trees_equal(x, y, "%s[%d]" % (path, i))
    elif isinstance(a, numpy.ndarray):
        numpy.testing.assert_array_equal(a, b, err_msg=path)
    elif isinstance(a, float):
        assert a == pytest.approx(b), path
    else:
        assert a == b, path


@pytest.mark.slow
def test_chaos_crash_corrupt_resume_equals_clean_run(tmp_path):
    """ISSUE acceptance: a run that (1) crashes on a snapshot write and
    (2) finds its newest surviving snapshot corrupted must resume from
    the newest VALID snapshot and converge to the SAME state tree as an
    uninterrupted run."""
    model = tmp_path / "chaos_model.py"
    model.write_text(CHAOS_MODEL)
    chaos_dir = tmp_path / "chaos_snaps"
    clean_dir = tmp_path / "clean_snaps"
    chaos_dir.mkdir()
    clean_dir.mkdir()

    # 1. crash injected at the THIRD snapshot write (epochs 1-2 commit,
    # the process dies with the fault exit code mid-epoch-3-export)
    r = _run_cli(model, chaos_dir, env_extra={
        "VELES_FAULTS": "snapshot.write:crash:after=2,times=1"})
    assert r.returncode == 42, (r.returncode, r.stderr[-2000:])
    survivors = checkpoint_chain.chain(str(chaos_dir), "chaos")
    # two valid snapshots must exist, so corrupting the newest still
    # leaves the chain a valid fallback
    assert len(survivors) >= 2, r.stderr[-2000:]

    # 2. bitrot the newest survivor — restore must quarantine it and
    # fall back, not crash or silently load garbage
    _flip_byte(survivors[0])

    # 3. relaunch with no faults: auto-resume, complete the job
    r2 = _run_cli(model, chaos_dir)
    assert r2.returncode == 0, r2.stderr[-2000:]
    assert "auto-resumed" in r2.stderr
    assert os.path.exists(survivors[0] + ".corrupt")

    # 4. uninterrupted reference run, same seed
    r3 = _run_cli(model, clean_dir)
    assert r3.returncode == 0, r3.stderr[-2000:]

    resumed = _state_tree_of(chaos_dir)
    clean = _state_tree_of(clean_dir)
    _assert_trees_equal(resumed["__units__"], clean["__units__"])
    _assert_trees_equal(resumed["__prng__"], clean["__prng__"])


def test_faults_list_cli():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    r = subprocess.run([sys.executable, "-m", "veles_tpu", "faults",
                        "list"], capture_output=True, text=True,
                       timeout=120, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    for point in ("snapshot.write", "loader.batch", "serve.request",
                  "dispatch", "download", "distributed.init",
                  "snapshot.load"):
        assert point in r.stdout
    # the chaos-storm window field is surfaced in the clause grammar
    assert "window=T0:T1" in r.stdout
