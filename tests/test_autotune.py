"""Per-device kernel block DB (ops/autotune.py) — measure → persist →
reuse, proven on CPU with a fake device_kind and an injected measure
function (the reference proved its GEMM equivalent against real GPUs
and shipped the result, veles/backends.py:623-731 +
devices/device_infos.json; the capability under test is the same:
first use sweeps, every later use is a lookup)."""
import json
import os

import pytest

from veles_tpu.config import root
from veles_tpu.ops import autotune


@pytest.fixture()
def tuned_env(tmp_path, monkeypatch):
    """Redirect the user DB into tmp, neutralize the shipped DB, clear
    the memo, and pin a fake device_kind."""
    monkeypatch.setattr(root.common.dirs, "cache", str(tmp_path),
                        raising=False)
    monkeypatch.setattr(autotune, "SHIPPED",
                        str(tmp_path / "shipped.json"))
    monkeypatch.setattr(autotune, "current_device_kind",
                        lambda: "faketpu-v0")
    autotune.clear_memo()
    yield tmp_path
    autotune.clear_memo()


def test_sweep_persists_and_reuses(tuned_env):
    calls = []

    def fake_measure(t, d, causal, blocks):
        calls.append(blocks)
        # (256, 128) is the planted winner
        return 1.0 if blocks != (256, 128) else 0.25

    best = autotune.sweep_flash(2048, 64, True, measure=fake_measure,
                                check_bwd=lambda *a: True)
    assert best == (256, 128)
    assert len(calls) == len(autotune.candidates_for(2048, 64))

    db_path = os.path.join(str(tuned_env), "kernel_tuning.json")
    db = json.load(open(db_path))
    entry = db["faketpu-v0"]["flash_t2048_d64_causal"]
    assert (entry["block_q"], entry["block_k"]) == (256, 128)
    assert "ts" in entry and "sweep_ms" in entry

    # reuse: the lookup path returns the persisted winner without any
    # measuring (flash_blocks never calls a measure fn on a hit)
    assert autotune.flash_blocks(2048, 64, causal=True) == (256, 128)
    # ... even in a "fresh process" (memo cleared → file read)
    autotune.clear_memo()
    assert autotune.flash_blocks(2048, 64, causal=True) == (256, 128)


def test_sweep_rejects_backward_incompatible_winner(tuned_env):
    """The fastest forward whose backward does NOT lower must yield to
    the next candidate (the bwd working set is larger than the fwd's)."""
    def fake_measure(t, d, causal, blocks):
        return 0.25 if blocks == (512, 512) else \
            (0.5 if blocks == (256, 128) else 1.0)

    best = autotune.sweep_flash(
        2048, 64, True, measure=fake_measure,
        check_bwd=lambda t, d, c, blocks: blocks != (512, 512))
    assert best == (256, 128)
    entry = autotune.lookup(autotune.flash_key(2048, 64, True))
    assert entry["sweep_ms"]["512x512"] == "bwd_compile_failed"


def test_default_blocks_skip_bwd_check(tuned_env):
    """(128, 128) is the known-safe production default — the sweep
    must not spend a backward compile validating it."""
    def fake_measure(t, d, causal, blocks):
        return 0.1 if blocks == autotune.DEFAULT_BLOCKS else 1.0

    def boom(*a):
        raise AssertionError("bwd check ran for the default blocks")

    assert autotune.sweep_flash(2048, 64, True, measure=fake_measure,
                                check_bwd=boom) == (128, 128)


def test_multihost_reads_shipped_only(tuned_env, monkeypatch):
    """Multi-host processes must trace identical blocks: only the
    committed shipped layer is consulted, never the per-host user DB,
    and no sweep fires."""
    import jax
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # user layer has a winner — must be IGNORED under multihost
    autotune.record(autotune.flash_key(2048, 64, True),
                    {"block_q": 512, "block_k": 512, "ms": 0.1})
    autotune.clear_memo()
    assert autotune.flash_blocks(2048, 64) == autotune.DEFAULT_BLOCKS
    autotune.clear_memo()
    shipped = {"faketpu-v0": {"flash_t2048_d64_causal":
                              {"block_q": 256, "block_k": 128}}}
    with open(autotune.SHIPPED, "w") as f:
        json.dump(shipped, f)
    assert autotune.flash_blocks(2048, 64) == (256, 128)


def test_miss_off_tpu_returns_defaults(tuned_env):
    # CPU backend, "auto" mode: no entry → defaults, no sweep attempt
    assert autotune.flash_blocks(4096, 64) == autotune.DEFAULT_BLOCKS


def test_nearest_length_fallback(tuned_env):
    """An untuned T inherits the measured winner from the nearest
    tuned length of the same (d, mode) class — the v5e sweep showed
    the block preference transfers across lengths while the 128×128
    default LOSES to fused XLA near the crossover."""
    autotune.record(autotune.flash_key(2048, 64, True),
                    {"block_q": 512, "block_k": 512, "ms": 0.5})
    autotune.record(autotune.flash_key(8192, 64, True),
                    {"block_q": 256, "block_k": 256, "ms": 0.4})
    autotune.clear_memo()
    # 3072 is nearer 2048 → 512×512; 6144 is nearer 8192 → 256×256
    assert autotune.flash_blocks(3072, 64) == (512, 512)
    assert autotune.flash_blocks(6144, 64) == (256, 256)
    # different mode (full) has no entries → defaults
    assert autotune.flash_blocks(3072, 64,
                                 causal=False) == autotune.DEFAULT_BLOCKS


def test_nearest_length_fallback_respects_divisibility(tuned_env):
    # nearest entry's blocks must divide the new T; otherwise defaults
    autotune.record(autotune.flash_key(2048, 64, True),
                    {"block_q": 512, "block_k": 512, "ms": 0.5})
    autotune.clear_memo()
    assert autotune.flash_blocks(1280, 64) == autotune.DEFAULT_BLOCKS


def test_nearest_length_fallback_multihost_shipped_only(tuned_env,
                                                        monkeypatch):
    """Multi-host nearest-length fallback reads ONLY the shipped layer
    (host-identical), never the per-host user DB."""
    import jax
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # user layer nearest entry must be IGNORED under multihost
    autotune.record(autotune.flash_key(2048, 64, True),
                    {"block_q": 512, "block_k": 512, "ms": 0.1})
    autotune.clear_memo()
    assert autotune.flash_blocks(4096, 64) == autotune.DEFAULT_BLOCKS
    autotune.clear_memo()
    shipped = {"faketpu-v0": {"flash_t8192_d64_causal":
                              {"block_q": 256, "block_k": 256}}}
    with open(autotune.SHIPPED, "w") as f:
        json.dump(shipped, f)
    assert autotune.flash_blocks(4096, 64) == (256, 256)


def test_windowed_reuses_causal_entry(tuned_env):
    autotune.record(autotune.flash_key(2048, 64, True),
                    {"block_q": 512, "block_k": 128, "ms": 0.5})
    assert autotune.flash_blocks(2048, 64, causal=True,
                                 window=256) == (512, 128)


def test_user_layer_overrides_shipped(tuned_env):
    shipped = {"faketpu-v0": {"flash_t1024_d64_causal":
                              {"block_q": 128, "block_k": 128}}}
    with open(autotune.SHIPPED, "w") as f:
        json.dump(shipped, f)
    assert autotune.flash_blocks(1024, 64) == (128, 128)
    autotune.clear_memo()
    autotune.record(autotune.flash_key(1024, 64, True),
                    {"block_q": 256, "block_k": 256, "ms": 0.1})
    assert autotune.flash_blocks(1024, 64) == (256, 256)


def test_disabled_mode(tuned_env, monkeypatch):
    monkeypatch.setattr(root.common.engine, "kernel_autotune", False,
                        raising=False)
    autotune.record(autotune.flash_key(2048, 64, True),
                    {"block_q": 512, "block_k": 512, "ms": 0.1})
    assert autotune.flash_blocks(2048, 64) == autotune.DEFAULT_BLOCKS


def test_flash_attention_resolves_db_blocks(tuned_env, monkeypatch):
    """End to end: flash_attention with default (None) blocks must run
    with the DB's winner — proven by planting blocks that only divide T
    for the planted entry, then checking numerics still match (the
    kernel itself asserts divisibility via `supported`)."""
    import numpy
    import jax.numpy as jnp
    from veles_tpu.ops.flash_attention import flash_attention
    from veles_tpu.parallel.ring_attention import attention_reference

    autotune.record(autotune.flash_key(256, 64, True),
                    {"block_q": 256, "block_k": 128, "ms": 0.1})
    seen = {}
    import veles_tpu.ops.flash_attention as fa
    orig = fa._fwd_pallas

    def spy(q, k, v, causal, scale, block_q, block_k, *a, **kw):
        seen["blocks"] = (block_q, block_k)
        return orig(q, k, v, causal, scale, block_q, block_k, *a, **kw)

    monkeypatch.setattr(fa, "_fwd_pallas", spy)
    rng = numpy.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)
               for _ in range(3))
    o = flash_attention(q, k, v, causal=True)
    assert seen["blocks"] == (256, 128)
    ref = attention_reference(q, k, v, causal=True)
    assert float(jnp.max(jnp.abs(o - ref))) < 2e-3


def test_flash_min_t_lookup(tuned_env):
    assert autotune.flash_min_t(64) == 4096      # default until swept
    autotune.record(autotune.min_t_key(64), {"min_t": 2048})
    assert autotune.flash_min_t(64) == 2048


def test_choose_flash_auto_reads_measured_crossover(tuned_env,
                                                    monkeypatch):
    import jax
    from veles_tpu.ops import flash_attention as fa
    monkeypatch.setattr(root.common.engine, "flash_attention", True,
                        raising=False)
    monkeypatch.setattr(root.common.engine, "flash_attention_min_t",
                        "auto", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    autotune.record(autotune.min_t_key(64), {"min_t": 1024})
    assert fa.choose_flash(1024, 64)
    assert not fa.choose_flash(512, 64)
    # an explicit int still pins the gate over the DB
    monkeypatch.setattr(root.common.engine, "flash_attention_min_t",
                        256, raising=False)
    assert fa.choose_flash(512, 64)


def _load_chip_experiments():
    """scripts/ is not a package; the seeding tests import the chip
    batch module by path (one copy of the boilerplate)."""
    import importlib.util
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ce", os.path.join(repo, "scripts", "chip_experiments.py"))
    ce = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(ce)
    return ce


class _TpuDev:
    platform = "tpu"


def test_attn_seed_derives_blocks_and_min_t(tuned_env):
    """The chip attn sweep's seeding: block winners per T (train mode
    preferred) AND the measured flash-vs-fused crossover land in the
    DB so production gates update by measurement."""
    ce = _load_chip_experiments()
    results = [
        # t=2048: tuned flash (2.0) LOSES to fused (1.0) in train mode
        {"t": 2048, "b": 16, "train": True, "variants": {
            "fused_xla": {"ms": 1.0}, "flash_128x128": {"ms": 3.0},
            "flash_256x128": {"ms": 2.0}}},
        # t=8192: tuned flash (7.0) WINS vs fused (10.0)
        {"t": 8192, "b": 1, "train": True, "variants": {
            "fused_xla": {"ms": 10.0}, "flash_512x512": {"ms": 7.0}}},
    ]

    ce._attn_seed(results, _TpuDev())
    assert autotune.flash_blocks(2048, 64) == (256, 128)
    assert autotune.flash_blocks(8192, 64) == (512, 512)
    assert autotune.flash_min_t(64) == 8192
    entry = autotune.lookup(autotune.min_t_key(64))
    assert entry["swept"] == {"2048": False, "8192": True}


def test_flash_min_t_multihost_reads_shipped_only(tuned_env,
                                                  monkeypatch):
    """Same invariant as block lookup: under multi-host every process
    must resolve the same gate, so per-host user caches are ignored."""
    import jax
    autotune.record(autotune.min_t_key(64), {"min_t": 1024})  # user
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    autotune.clear_memo()
    assert autotune.flash_min_t(64) == 4096      # shipped empty
    shipped = {"faketpu-v0": {"flash_min_t_d64": {"min_t": 2048}}}
    with open(autotune.SHIPPED, "w") as f:
        json.dump(shipped, f)
    autotune.clear_memo()
    assert autotune.flash_min_t(64) == 2048


def test_attn_seed_min_t_respects_losses_above_wins(tuned_env):
    """A win at a SMALL T below a measured loss at a larger T must not
    open the `t >= min_t` gate over the loss: min_t only opens above
    the largest losing length."""
    ce = _load_chip_experiments()
    results = [
        {"t": 2048, "b": 16, "train": True, "variants": {
            "fused_xla": {"ms": 3.0}, "flash_128x128": {"ms": 2.0}}},
        {"t": 8192, "b": 1, "train": True, "variants": {
            "fused_xla": {"ms": 5.0}, "flash_128x128": {"ms": 9.0}}},
    ]

    ce._attn_seed(results, _TpuDev())
    assert autotune.flash_min_t(64) == autotune.NEVER


def test_attn_seed_split_sections_merge_crossover(tuned_env):
    """The split attn_2048/attn_8192 chip sections each seed one
    length; the second must REFINE the persisted crossover with the
    first's verdicts, not overwrite them."""
    ce = _load_chip_experiments()
    r2048_loss = [{"t": 2048, "b": 16, "train": True, "variants": {
        "fused_xla": {"ms": 1.0}, "flash_128x128": {"ms": 2.0}}}]
    r8192_win = [{"t": 8192, "b": 1, "train": True, "variants": {
        "fused_xla": {"ms": 10.0}, "flash_512x512": {"ms": 7.0}}}]
    ce._attn_seed(r2048_loss, _TpuDev())
    assert autotune.flash_min_t(64) == autotune.NEVER
    autotune.clear_memo()
    ce._attn_seed(r8192_win, _TpuDev())
    # merged view: loss@2048 + win@8192 -> gate opens at 8192
    assert autotune.flash_min_t(64) == 8192
    entry = autotune.lookup(autotune.min_t_key(64))
    assert entry["swept"] == {"2048": False, "8192": True}


# -- provenance stamps (PR 20): record() stamps, lookup() flags stale --


def test_record_stamps_jax_and_device_kind(tuned_env):
    """Every persisted entry carries the toolchain + chip that measured
    it — the provenance a later build checks before trusting the
    ranking."""
    import jax
    from veles_tpu.telemetry.counters import counters
    c0 = counters.get("veles_autotune_stale_total")
    autotune.record("flash_t2048_d64_causal",
                    {"block_q": 256, "block_k": 128})
    entry = autotune.lookup("flash_t2048_d64_causal")
    assert entry["jax"] == str(jax.__version__)
    assert entry["device_kind"] == "faketpu-v0"
    # a fresh same-toolchain stamp is NOT stale
    assert counters.get("veles_autotune_stale_total") == c0


def test_stale_entry_counts_every_lookup_warns_once(tuned_env, caplog):
    """An entry measured under another jax (or the pre-stamp DB format)
    is still USED, but veles_autotune_stale_total moves on EVERY lookup
    and the log warns ONCE per (kind, key) — the operator signal that a
    re-sweep is due, without a log storm per trace."""
    import logging
    from veles_tpu.telemetry.counters import counters
    db_path = os.path.join(str(tuned_env), "kernel_tuning.json")
    with open(db_path, "w") as fout:
        json.dump({"faketpu-v0": {
            "flash_t2048_d64_causal":            # pre-stamp format
                {"block_q": 512, "block_k": 128},
            "flash_t8192_d64_causal":            # other-toolchain stamp
                {"block_q": 256, "block_k": 256, "jax": "0.0.1"},
        }}, fout)
    c0 = counters.get("veles_autotune_stale_total")
    with caplog.at_level(logging.WARNING,
                         logger="veles_tpu.ops.autotune"):
        assert autotune.lookup("flash_t2048_d64_causal")["block_q"] \
            == 512                               # hit is still served
        autotune.lookup("flash_t2048_d64_causal")
        autotune.lookup("flash_t8192_d64_causal")
    assert counters.get("veles_autotune_stale_total") == c0 + 3
    stale = [r for r in caplog.records if "stale" in r.getMessage()]
    assert len(stale) == 2                       # once per key
    assert "unstamped" in stale[0].getMessage()
    assert "0.0.1" in stale[1].getMessage()
    # clear_memo() resets the warn-once set (fresh-process semantics)
    autotune.clear_memo()
    with caplog.at_level(logging.WARNING,
                         logger="veles_tpu.ops.autotune"):
        autotune.lookup("flash_t2048_d64_causal")
    assert len([r for r in caplog.records
                if "stale" in r.getMessage()]) == 3
