"""LMDB/HDFS gated loaders + bboxer annotation tool."""
import json
import urllib.request

import numpy
import pytest

from veles_tpu.error import VelesError
from veles_tpu.loader.kv_store import (HDFSTextLoader, LMDBLoader,
                                       parse_tsv_line)
from veles_tpu.scripts.bboxer import BBoxerServer


def test_lmdb_loader_gates_without_lmdb():
    loader = LMDBLoader(None, databases=[None, None, "/tmp/nope"],
                        minibatch_size=4)
    with pytest.raises(VelesError) as err:
        loader.load_data()
    assert "lmdb" in str(err.value)
    with pytest.raises(VelesError):
        LMDBLoader(None, databases=["just-one"])


def test_hdfs_parsing_without_cluster():
    loader = HDFSTextLoader(None, namenode="", paths=[None, None, "/x"],
                            minibatch_size=4)
    data, labels = loader.parse_text("1.0\t2.0\t0\n3.0\t4.0\t1\n")
    numpy.testing.assert_allclose(data, [[1, 2], [3, 4]])
    numpy.testing.assert_array_equal(labels, [0, 1])
    sample, label = parse_tsv_line("0.5\t7")
    assert label == 7 and sample.tolist() == [0.5]
    with pytest.raises(VelesError):
        loader.load_data()      # no namenode configured


def make_png(path, w=16, h=12):
    from PIL import Image
    Image.new("RGB", (w, h), (100, 50, 25)).save(path)


def test_bboxer_annotation_roundtrip(tmp_path):
    make_png(tmp_path / "a.png")
    make_png(tmp_path / "b.png")
    server = BBoxerServer(str(tmp_path), port=0).start()
    base = "http://127.0.0.1:%d" % server.port

    def get(path):
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.headers.get_content_type(), r.read()

    ctype, page = get("/")
    assert ctype == "text/html" and b"bboxer" in page
    _, listing = get("/list")
    assert json.loads(listing)["images"] == ["a.png", "b.png"]
    ctype, img = get("/image?name=a.png")
    assert ctype == "image/png" and img[:4] == b"\x89PNG"
    # path escape refused
    with pytest.raises(urllib.error.HTTPError):
        get("/image?name=../secret")

    def post(payload):
        req = urllib.request.Request(base + "/boxes",
                                     data=json.dumps(payload).encode())
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    out = post({"image": "a.png",
                "box": {"x": 1, "y": 2, "w": 5, "h": 4, "label": "cat"}})
    assert out["count"] == 1
    saved = json.loads((tmp_path / "bboxes.json").read_text())
    assert saved["a.png"][0]["label"] == "cat"
    post({"image": "a.png", "clear": True})
    saved = json.loads((tmp_path / "bboxes.json").read_text())
    assert saved["a.png"] == []
    server.stop()
    # persisted annotations reload
    server2 = BBoxerServer(str(tmp_path), port=0)
    assert server2.boxes["a.png"] == []


def test_all_empty_splits_rejected():
    loader = HDFSTextLoader(None, namenode="x", paths=[None, None, None],
                            minibatch_size=4)
    with pytest.raises(VelesError) as err:
        loader.load_data()
    assert "no databases/paths" in str(err.value)


def test_bboxer_save_is_atomic(tmp_path):
    make_png(tmp_path / "a.png")
    server = BBoxerServer(str(tmp_path), port=0)
    server.add_box("a.png", {"x": 0, "y": 0, "w": 3, "h": 3,
                             "label": "z"})
    assert not (tmp_path / "bboxes.json.tmp").exists()
    assert json.loads((tmp_path / "bboxes.json").read_text())["a.png"]
    snap = server.boxes_copy()
    snap["a.png"].append("mutation")     # copies, not aliases
    assert server.count("a.png") == 1


def test_hdfs_loader_against_stub_namenode():
    """The WebHDFS path proven end to end against a local stub namenode
    (in-process-loopback policy, like the forge/confluence stubs): OPEN
    requests serve TSV splits — including through the 307
    namenode→datanode redirect real clusters answer with — and the
    loader builds its three sample classes from them."""
    from http.server import BaseHTTPRequestHandler
    from veles_tpu._http import HTTPService, bytes_reply

    train = "".join("%f\t%f\t%d\n" % (i * 0.1, 1 - i * 0.1, i % 2)
                    for i in range(8))
    valid = "0.5\t0.5\t0\n0.25\t0.75\t1\n"

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.startswith("/webhdfs/v1/data/train.tsv"):
                # real namenodes 307-redirect OPEN to a datanode;
                # urllib must follow it transparently
                self.send_response(307)
                self.send_header(
                    "Location",
                    "http://127.0.0.1:%d/datanode/train" % svc.port)
                self.end_headers()
            elif self.path.startswith("/datanode/train"):
                bytes_reply(self, 200, train.encode(), "text/plain")
            elif self.path.startswith("/webhdfs/v1/data/valid.tsv"):
                bytes_reply(self, 200, valid.encode(), "text/plain")
            else:
                bytes_reply(self, 404, b"nope", "text/plain")

        def log_message(self, *a):
            pass

    svc = HTTPService(Handler, thread_name="stub-namenode")
    svc.start_serving()
    try:
        loader = HDFSTextLoader(
            None, namenode="http://127.0.0.1:%d" % svc.port,
            paths=[None, "/data/valid.tsv", "/data/train.tsv"],
            minibatch_size=4, name="hdfs")
        loader.load_data()
        assert loader.class_lengths == [0, 2, 8]
        assert loader.original_data.shape == (10, 2)
        assert set(numpy.unique(loader.original_labels.mem)) == {0, 1}
    finally:
        svc.stop_serving()
