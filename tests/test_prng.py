"""Mirrors reference veles/tests/test_random.py scope: determinism, keyed
streams, state preservation, reseeding."""
import pickle

import numpy

from veles_tpu import prng


def test_keyed_streams_deterministic():
    a1 = prng.RandomGenerator("k", seed=42).rand(5)
    a2 = prng.RandomGenerator("k", seed=42).rand(5)
    numpy.testing.assert_array_equal(a1, a2)


def test_distinct_keys_distinct_streams():
    assert prng.get("one").initial_seed != prng.get("two").initial_seed


def test_preserve_state():
    g = prng.RandomGenerator("p", seed=1)
    before = g.rand(3)
    with prng.RandomGenerator.preserve_state(g):
        g.rand(100)
        g.jax_key()
    after_scope = g.rand(3)
    g2 = prng.RandomGenerator("p", seed=1)
    g2.rand(3)
    numpy.testing.assert_array_equal(after_scope, g2.rand(3))
    assert not numpy.array_equal(before, after_scope)


def test_jax_keys_never_repeat():
    g = prng.RandomGenerator("j", seed=7)
    import jax
    k1, k2 = g.jax_key(), g.jax_key()
    d1 = jax.random.key_data(k1)
    d2 = jax.random.key_data(k2)
    assert not numpy.array_equal(d1, d2)


def test_jax_keys_reproducible_after_reseed():
    import jax
    g = prng.RandomGenerator("r", seed=3)
    k1 = jax.random.key_data(g.jax_key())
    g.seed(3)
    k2 = jax.random.key_data(g.jax_key())
    numpy.testing.assert_array_equal(k1, k2)


def test_pickle_restores_stream():
    g = prng.RandomGenerator("s", seed=9)
    g.rand(10)
    g2 = pickle.loads(pickle.dumps(g))
    numpy.testing.assert_array_equal(g.rand(4), g2.rand(4))


def test_seed_all_reseeds_existing():
    g = prng.get("reseed-me")
    v1 = g.rand(2)
    prng.seed_all(777)
    v2 = prng.get("reseed-me").rand(2)
    prng.seed_all(777)
    v3 = prng.get("reseed-me").rand(2)
    numpy.testing.assert_array_equal(v2, v3)
