"""Flight recorder: the crash black box.

The reference's failure story ended at a traceback; under the
north-star's traffic a crash, a watchdog trip or a NaN-poisoned model
needs *forensics* — what the process was doing in the seconds before it
died. This module keeps a bounded, thread-safe ring (default 4096
events) subscribed to the observability surfaces that already exist:

- **span closes** (:mod:`~veles_tpu.telemetry.spans` close hook) —
  every completed ``unit.run`` / ``workflow.run`` / decode span;
- **alarm-counter increments** (:mod:`~veles_tpu.telemetry.counters`
  inc hook) — fault injections, watchdog trips, shed requests,
  snapshot quarantines, side-plane task errors, model NaNs — plus any
  single increment over ``root.common.telemetry.recorder.
  counter_threshold`` (byte bursts);
- **logger events** (:mod:`veles_tpu.logger` event hook) — workflow
  begin/end, snapshot commits, launcher transitions;
- **health transitions** and **tensormon samples** — noted explicitly
  by :mod:`~veles_tpu.resilience.health` / :mod:`~veles_tpu.telemetry.
  tensormon`.

On an unhandled ``Workflow.run`` exception, a ``step_watchdog`` trip
or SIGTERM (and always on a NaN-sentinel halt) the ring dumps to
``blackbox-<ts>_<pid>.jsonl`` next to the snapshot directory;
``veles-tpu blackbox dump|inspect`` writes/reads it back. Crash-path
dumps honor ``root.common.telemetry.recorder.autodump`` (default off —
test suites raise through ``Workflow.run`` on purpose all the time).

NOTE on naming: ``veles_tpu.telemetry.recorder`` the *module* (this
file) is distinct from ``veles_tpu.telemetry.recorder`` the *package
attribute*, which stays bound to the span recorder instance for
backward compatibility (``telemetry/__init__.py`` import order).
Always import this module by full path::

    from veles_tpu.telemetry.recorder import flight, FlightRecorder
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..config import root
# direct from-imports, not `from . import counters`: the package
# __init__ rebinds the `counters`/`recorder` package attributes to the
# registry/span-recorder instances, so module-attribute access through
# the package is unreliable during (and after) package init
from .counters import add_inc_hook as _add_inc_hook
from .counters import inc as _counter_inc
from .spans import add_close_hook as _add_close_hook
# the ONE request-correlation predicate (spans.py owns it), re-
# exported here because `blackbox inspect --request` is its flight-
# recorder face: a crashed replica's dump cross-references a merged
# fleet trace by either request_id or trace_id
from .spans import matches_request                    # noqa: F401

#: default ring capacity (events)
DEFAULT_CAPACITY = 4096

#: counters whose EVERY increment is a flight-recorder event — the
#: "something went wrong" set; ordinary accounting counters
#: (dispatches, bytes) only record above ``counter_threshold``
ALARM_COUNTERS = frozenset((
    "veles_faults_injected_total",
    "veles_watchdog_trips_total",
    "veles_shed_requests_total",
    "veles_snapshots_quarantined_total",
    "veles_sideplane_errors_total",
    "veles_model_nan_total",
    "veles_model_health_errors_total",
))



#: cached config NODE (not values): the auto-vivified node object is
#: stable, so caching it turns the per-event attribute traversal into
#: one dict lookup while config writes stay immediately visible —
#: these lookups sit on the span-close and counter-inc hot paths
_cfg_node = None


def _cfg(name: str, default):
    global _cfg_node
    try:
        if _cfg_node is None:
            _cfg_node = root.common.telemetry.recorder
        return _cfg_node.get(name, default)
    except Exception:        # noqa: BLE001 — config not importable
        return default


class FlightRecorder:
    """Bounded, thread-safe ring of observability events + dump."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 follow_config: bool = False) -> None:
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=int(capacity))
        self._recorded = 0
        self._sigterm_installed = False
        #: True only on the process-global instance: tracks the
        #: root.common.telemetry.recorder.capacity knob (explicit
        #: capacities — tests, bench proofs — stay fixed)
        self._follow_config = follow_config

    # -- recording -----------------------------------------------------------
    def enabled(self) -> bool:
        return bool(_cfg("enabled", True))

    def note(self, kind: str, **data: Any) -> None:
        """Append one event to the ring (newest wins once full)."""
        if not self.enabled():
            return
        rec = {"kind": kind, "t": time.time()}
        rec.update(data)
        with self._lock:
            if self._follow_config:
                # honor a changed capacity knob (the global instance
                # is constructed at import, before any config lands)
                want = int(_cfg("capacity", self._ring.maxlen)
                           or self._ring.maxlen)
                if want > 0 and want != self._ring.maxlen:
                    self._ring = collections.deque(self._ring,
                                                   maxlen=want)
            self._ring.append(rec)
            self._recorded += 1

    def records(self, kind: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            recs = list(self._ring)
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return recs

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"recorded": self._recorded,
                    "buffered": len(self._ring),
                    "capacity": self._ring.maxlen}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recorded = 0

    # -- dumping -------------------------------------------------------------
    def dump(self, reason: str, directory: Optional[str] = None,
             path: Optional[str] = None) -> str:
        """Write the ring as ``blackbox-<ts>_<pid>.jsonl`` (header line
        first) into ``directory`` (default: the snapshot dir, so the
        forensics land next to the checkpoints they explain). Atomic
        tmp-write + fsync + rename, like the checkpoint chain."""
        from ..resilience.faults import fire as fire_fault
        # the `recorder.dump` injection point: raise/crash exercise the
        # "black box itself fails" path, corrupt damages the dump bytes
        fault = fire_fault("recorder.dump")
        with self._lock:
            events = list(self._ring)
        if path is None:
            if directory is None:
                directory = str(root.common.dirs.snapshots)
            os.makedirs(directory, exist_ok=True)
            base = os.path.join(directory, "blackbox-%s_%d" % (
                time.strftime("%Y%m%d_%H%M%S"), os.getpid()))
            # 1s timestamp resolution: a second dump in the same
            # second (watchdog trip then crash) must not os.replace
            # the first's forensics away
            path, n = base + ".jsonl", 1
            while os.path.exists(path):
                n += 1
                path = "%s-%d.jsonl" % (base, n)
        else:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        header = {"kind": "blackbox.header", "reason": reason,
                  "t": time.time(), "pid": os.getpid(),
                  "events": len(events)}
        payload = "\n".join(json.dumps(r, default=str)
                            for r in [header] + events) + "\n"
        data = payload.encode()
        if fault is not None:
            data = fault.corrupt(data)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fout:
            fout.write(data)
            fout.flush()
            os.fsync(fout.fileno())
        os.replace(tmp, path)
        _counter_inc("veles_blackbox_dumps_total")
        logging.getLogger("veles_tpu.telemetry").warning(
            "flight recorder black box -> %s (%d events; reason: %s)",
            path, len(events), reason)
        return path

    def autodump_enabled(self) -> bool:
        return bool(_cfg("autodump", False))

    def crash_dump(self, reason: str) -> Optional[str]:
        """The crash-path dump: a no-op unless ``autodump`` is armed,
        and NEVER raises — the black box must not mask the crash it is
        documenting."""
        if not self.autodump_enabled():
            return None
        try:
            return self.dump(reason)
        except Exception as e:        # noqa: BLE001 — see docstring
            logging.getLogger("veles_tpu.telemetry").warning(
                "flight recorder dump failed (%s: %s)",
                type(e).__name__, e)
            return None

    # -- SIGTERM -------------------------------------------------------------
    def install_sigterm(self) -> bool:
        """Chain a SIGTERM handler that crash-dumps before the previous
        disposition runs (preemption forensics). Main thread only;
        returns True when installed."""
        if self._sigterm_installed:
            return True
        import signal
        if threading.current_thread() is not threading.main_thread():
            return False
        prev = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            self.crash_dump("SIGTERM")
            if callable(prev):
                prev(signum, frame)
            elif prev is signal.SIG_IGN:
                return          # keep the previously-ignored fate
            else:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        try:
            signal.signal(signal.SIGTERM, handler)
        except (ValueError, OSError):   # non-main thread / exotic host
            return False
        self._sigterm_installed = True
        return True


#: THE process-global flight recorder (mirrors counters.counters)
flight = FlightRecorder(follow_config=True)


# -- black-box file access ----------------------------------------------------

def read_blackbox(path: str) -> Tuple[Optional[Dict[str, Any]],
                                      List[Dict[str, Any]]]:
    """(header, events) from a black-box dump; malformed lines are
    skipped (a dump written mid-crash may be torn)."""
    header: Optional[Dict[str, Any]] = None
    events: List[Dict[str, Any]] = []
    # errors="replace": a dump torn/corrupted mid-crash may carry
    # invalid UTF-8 — the readable lines must still come back
    with open(path, errors="replace") as fin:
        for line in fin:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if not isinstance(rec, dict):
                continue
            if rec.get("kind") == "blackbox.header" and header is None:
                header = rec
            else:
                events.append(rec)
    return header, events


def inspect(path: str, request: Optional[str] = None
            ) -> Dict[str, Any]:
    """Summary of a black-box dump: reason, event count, per-kind
    counts, covered time range — what ``veles-tpu blackbox inspect``
    prints. ``request`` narrows the view to one request's events
    (request_id or trace_id — ``blackbox inspect --request ID``): the
    crashed replica's last seconds for exactly the request a fleet
    trace says died there."""
    header, events = read_blackbox(path)
    total = len(events)
    if request is not None:
        events = [e for e in events if matches_request(e, request)]
    by_kind: Dict[str, int] = {}
    for rec in events:
        kind = str(rec.get("kind", "?"))
        by_kind[kind] = by_kind.get(kind, 0) + 1
    times = [r["t"] for r in events if isinstance(r.get("t"), (int, float))]
    out = {
        "path": path,
        "reason": (header or {}).get("reason"),
        "dumped_at": (header or {}).get("t"),
        "pid": (header or {}).get("pid"),
        "events": len(events),
        "by_kind": by_kind,
        "span_seconds": (round(max(times) - min(times), 3)
                         if len(times) > 1 else 0.0),
    }
    if request is not None:
        out["request"] = str(request)
        out["events_total"] = total
    return out


# -- subscriptions ------------------------------------------------------------

def _on_counter(name: str, value: float, total: float) -> None:
    if name in ALARM_COUNTERS:
        flight.note("counter", counter=name, delta=value, total=total)
        return
    thr = _cfg("counter_threshold", 0)
    if thr and value >= float(thr):
        flight.note("counter", counter=name, delta=value, total=total)


def _on_span_close(rec: Dict[str, Any]) -> None:
    ev = {"name": rec.get("name"), "dur": rec.get("dur"),
          "tid": rec.get("tid")}
    for key in ("unit", "workflow", "error", "steps", "counters"):
        if key in rec:
            ev[key] = rec[key]
    flight.note("span", **ev)


def _on_event(rec: Dict[str, Any]) -> None:
    flight.note("event", **{k: v for k, v in rec.items() if k != "t"})


_add_inc_hook(_on_counter)
_add_close_hook(_on_span_close)

# logger events: imported lazily-but-once here; veles_tpu.logger is a
# leaf module (no telemetry imports), so this cannot cycle
from ..logger import add_event_hook as _add_event_hook  # noqa: E402

_add_event_hook(_on_event)
