"""In-graph model-health monitoring: tensor taps + NaN sentinel.

The host-side telemetry (counters/spans/cost) sees what the framework
*does*; this module sees what the model *computes* — exploding
gradients, NaN/Inf poisoning, saturated activations — without a single
extra per-step host sync. Following the compiler-first discipline
(PAPERS.md "Compiler-First State Space Duality"): the statistics are
pure jax scalars computed as auxiliary outputs of the EXISTING jitted
train step (``nn/train_step.py`` merges them into the metric
accumulators it already scans on device), and they reach the host by
riding the per-epoch metric drain that happens anyway. With
``root.common.telemetry.tensormon.enabled = False`` (the default) the
step function is bit-identical and the dispatch count unchanged —
locked by ``tests/test_tensormon.py``.

Per drained sample the monitor derives:

- global gradient L2 norm (per-step RMS over the drained window);
- per-layer weight norms and update/weight ratios (the classic
  learning-rate sanity signal);
- NaN/Inf counts over gradients, loss and head activations;
- activation saturation fraction (``|x| >= sat_threshold``).

These stream to the span/trace file (``tensormon.sample`` spans — so
Perfetto timelines carry model health), the flight recorder
(:mod:`~veles_tpu.telemetry.recorder`), and ``web_status`` ``/metrics``
as ``veles_model_*`` gauges; NaN detections increment
``veles_model_nan_total``.

The **NaN sentinel** (``root.common.telemetry.tensormon.nan_policy``)
bridges into the resilience plane on detection:

- ``warn``              — log + count, training continues;
- ``halt``              — mark ``model_health`` unready (/readyz 503),
  dump the flight recorder, raise :class:`ModelHealthError`;
- ``snapshot_and_halt`` — additionally force a Snapshotter commit
  through the crash-safe checkpoint chain first, so the poisoned state
  is on disk for forensics.
"""

from __future__ import annotations

import math
import re
import threading
from typing import Any, Dict, List, Optional

from ..config import root
from ..error import VelesError
from .counters import inc


class ModelHealthError(VelesError):
    """Raised by the NaN sentinel (policy ``halt`` /
    ``snapshot_and_halt``) when non-finite values are detected inside
    the train step."""


#: accepted nan_policy values
POLICIES = ("warn", "halt", "snapshot_and_halt")

#: key prefix of the monitor's auxiliary accumulator entries — the
#: train step creates/merges them only when monitoring is enabled and
#: strips them back out of the drained metrics before the Decision
#: sees them
MON_PREFIX = "mon_"


def enabled() -> bool:
    """THE tensormon on/off switch
    (``root.common.telemetry.tensormon.enabled``, default False)."""
    try:
        return bool(root.common.telemetry.tensormon.get("enabled",
                                                        False))
    except Exception:        # noqa: BLE001 — config not importable
        return False


def settings() -> Dict[str, Any]:
    """Resolved monitoring knobs (validated); raises on a bad policy so
    a typo'd config fails at initialize, not at the first NaN."""
    node = root.common.telemetry.tensormon
    policy = str(node.get("nan_policy", "warn") or "warn")
    if policy not in POLICIES:
        raise VelesError(
            "root.common.telemetry.tensormon.nan_policy %r is not one "
            "of %s" % (policy, "/".join(POLICIES)))
    sat = node.get("sat_threshold", 6.0)
    return {
        "every": max(1, int(node.get("every", 1) or 1)),
        "policy": policy,
        # no `or`-coercion: an explicit 0 threshold (count everything
        # as saturated — a wiring check) must survive
        "sat_threshold": float(6.0 if sat is None else sat),
    }


# -- the pure (traced) side ----------------------------------------------------

def zero_stats(layer_names) -> Dict[str, Any]:
    """Zero accumulator entries matching :func:`step_stats`'s keys —
    what ``TrainStep._make_zero_accum`` merges in when monitoring is
    on. All float32 scalars, all sum-accumulable."""
    import jax.numpy as jnp

    def z():
        return jnp.zeros((), jnp.float32)

    out = {"mon_steps": z(), "mon_nan": z(), "mon_grad_sq": z(),
           "mon_sat": z(), "mon_act_n": z()}
    for name in sorted(layer_names):
        out["mon_wsq/%s" % name] = z()
        out["mon_usq/%s" % name] = z()
    return out


def step_stats(params, new_params, grads, loss, out=None,
               sat_threshold: float = 6.0) -> Dict[str, Any]:
    """Pure jax tensor statistics for ONE optimizer step — auxiliary
    outputs of the fused train step, accumulated on device by the same
    scan that carries the loss metrics. ``out`` is the head activation
    tensor when available (the gradient-accumulation path passes None:
    its chunk outputs live inside the scan; saturation reads 0 there).
    Sums only, so the uniform ``a + m`` accumulator merge applies."""
    import jax
    import jax.numpy as jnp
    f32 = jnp.float32

    def sumsq(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        if not leaves:
            return jnp.zeros((), f32)
        return sum(jnp.sum(jnp.square(leaf.astype(f32)))
                   for leaf in leaves)

    def nonfinite(tree):
        leaves = jax.tree_util.tree_leaves(tree)
        if not leaves:
            return jnp.zeros((), f32)
        return sum(jnp.sum((~jnp.isfinite(leaf.astype(f32))).astype(f32))
                   for leaf in leaves)

    loss32 = jnp.asarray(loss, f32)
    stats = {
        "mon_steps": jnp.ones((), f32),
        "mon_grad_sq": sumsq(grads),
        "mon_nan": nonfinite(grads)
        + (~jnp.isfinite(loss32)).astype(f32),
    }
    if out is not None:
        a = jnp.abs(out.astype(f32))
        stats["mon_sat"] = jnp.sum((a >= sat_threshold).astype(f32))
        stats["mon_act_n"] = jnp.asarray(float(out.size), f32)
        stats["mon_nan"] = stats["mon_nan"] + nonfinite(out)
    else:
        stats["mon_sat"] = jnp.zeros((), f32)
        stats["mon_act_n"] = jnp.zeros((), f32)
    for name in sorted(params):
        stats["mon_wsq/%s" % name] = sumsq(new_params[name])
        upd = jax.tree_util.tree_map(
            lambda new, old: new.astype(f32) - old.astype(f32),
            new_params[name], params[name])
        stats["mon_usq/%s" % name] = sumsq(upd)
    return stats


# -- the host side -------------------------------------------------------------

def _safe(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_]", "_", name)


class TensorMonitor:
    """Host-side consumer of drained monitor accumulators: derives the
    human/Prometheus-facing statistics, runs the NaN sentinel, and
    feeds spans + flight recorder. One process-global instance
    (:data:`monitor`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._samples = 0
        self._last: Dict[str, Any] = {}
        self._layers: Dict[str, Dict[str, float]] = {}

    # -- observation ---------------------------------------------------------
    def observe(self, step_unit, mon: Dict[str, float]) -> None:
        """Process one drained sample (host floats keyed ``mon_*``).
        May raise :class:`ModelHealthError` per the sentinel policy —
        callers sit on the scheduler path, exactly where a crashed
        dispatch would have surfaced."""
        cfg = getattr(step_unit, "_tensormon", None) or {}
        every = max(1, int(cfg.get("every", 1)))
        steps = max(float(mon.get("mon_steps", 0.0)), 1.0)
        nan = float(mon.get("mon_nan", 0.0))
        act_n = float(mon.get("mon_act_n", 0.0))
        summary = {
            "grad_norm": math.sqrt(
                max(float(mon.get("mon_grad_sq", 0.0)), 0.0) / steps),
            "nan": nan,
            "act_saturation": (float(mon.get("mon_sat", 0.0)) / act_n
                               if act_n else 0.0),
            "steps": steps,
        }
        layers: Dict[str, Dict[str, float]] = {}
        for key, val in mon.items():
            if not key.startswith("mon_wsq/"):
                continue
            name = key[len("mon_wsq/"):]
            wnorm = math.sqrt(max(float(val), 0.0) / steps)
            unorm = math.sqrt(
                max(float(mon.get("mon_usq/%s" % name, 0.0)), 0.0)
                / steps)
            layers[name] = {
                "weight_norm": wnorm,
                "update_ratio": (unorm / wnorm) if wnorm else 0.0,
            }
        with self._lock:
            self._samples += 1
            n = self._samples
            self._last = dict(summary)
            self._layers = layers
        inc("veles_tensormon_samples_total")
        if n % every == 0:
            # zero-duration span: the sample lands in the span ring and
            # the --trace-file stream, so Perfetto timelines carry
            # model health next to the dispatch spans
            from .spans import span
            attrs = {k: round(v, 6) if isinstance(v, float) else v
                     for k, v in summary.items()}
            with span("tensormon.sample", **attrs):
                pass
            from .recorder import flight
            flight.note("tensormon", **summary)
        if nan > 0:
            inc("veles_model_nan_total", nan)
            self._sentinel(step_unit, cfg, summary)

    # -- sentinel ------------------------------------------------------------
    def _sentinel(self, step_unit, cfg: Dict[str, Any],
                  summary: Dict[str, Any]) -> None:
        import logging
        policy = str(cfg.get("policy", "warn"))
        log = logging.getLogger("veles_tpu.telemetry")
        from .recorder import flight
        flight.note("tensormon.nan", policy=policy, **summary)
        log.warning(
            "tensormon: %d non-finite value(s) in the train step "
            "(grad_norm=%s, policy=%s)", int(summary["nan"]),
            summary["grad_norm"], policy)
        if policy == "warn":
            return
        # halt policies: the model is poisoned — readiness drops first
        # so load balancers stop routing, then the black box and (for
        # snapshot_and_halt) the forensic checkpoint land on disk,
        # then the typed error unwinds the scheduler
        from ..resilience.health import mark_unready
        mark_unready("model_health")
        inc("veles_model_health_errors_total")
        snap_path: Optional[str] = None
        if policy == "snapshot_and_halt":
            snap = self._find_snapshotter(step_unit)
            if snap is None:
                log.warning("tensormon: snapshot_and_halt but the "
                            "workflow has no Snapshotter unit — "
                            "halting without a forensic checkpoint")
            else:
                try:
                    path = snap.export()
                    # async mode: export() only ENQUEUES the commit —
                    # surface a failed commit instead of pointing the
                    # operator at a file that was never written
                    errors = snap.drain(raise_errors=False)
                    if errors:
                        raise errors[0]
                    snap_path = path
                except Exception as e:    # noqa: BLE001 — still halt
                    log.warning("tensormon: forensic snapshot failed "
                                "(%s: %s)", type(e).__name__, e)
        try:
            dump_path = flight.dump(
                "nan sentinel: %d non-finite value(s), policy=%s"
                % (int(summary["nan"]), policy))
        except Exception:        # noqa: BLE001 — never mask the halt
            dump_path = None
        raise ModelHealthError(
            "non-finite values detected in the train step (%d NaN/Inf; "
            "grad_norm=%s). Model health is unready; %s%s"
            % (int(summary["nan"]), summary["grad_norm"],
               ("forensic snapshot: %s; " % snap_path) if snap_path
               else "",
               ("black box: %s" % dump_path) if dump_path
               else "no black box written"))

    @staticmethod
    def _find_snapshotter(step_unit):
        from ..snapshotter import Snapshotter
        wf = getattr(step_unit, "workflow", None)
        snap = getattr(wf, "snapshotter", None)
        if isinstance(snap, Snapshotter):
            return snap
        for unit in getattr(wf, "units", []) or []:
            if isinstance(unit, Snapshotter):
                return unit
        return None

    # -- export --------------------------------------------------------------
    def gauges(self) -> Dict[str, Any]:
        """``/metrics`` gauge rows (name → (value, help)); empty until
        the first sample so monitoring-off processes render no
        ``veles_model_*`` rows at all."""
        with self._lock:
            last = dict(self._last)
            layers = {k: dict(v) for k, v in self._layers.items()}
        if not last:
            return {}
        out = {
            "veles_model_grad_norm": (
                last["grad_norm"],
                "Global gradient L2 norm (per-step RMS, last sample)"),
            "veles_model_act_saturation": (
                last["act_saturation"],
                "Fraction of head activations at/above sat_threshold"),
        }
        for name, vals in sorted(layers.items()):
            safe = _safe(name)
            out["veles_model_weight_norm_" + safe] = (
                vals["weight_norm"],
                "Weight L2 norm of layer " + name)
            out["veles_model_update_ratio_" + safe] = (
                vals["update_ratio"],
                "Update/weight norm ratio of layer " + name)
        return out

    def last_sample(self) -> Dict[str, Any]:
        with self._lock:
            return dict(self._last)

    def reset(self) -> None:
        """Tests only."""
        with self._lock:
            self._samples = 0
            self._last = {}
            self._layers = {}


#: THE process-global monitor (mirrors counters.counters)
monitor = TensorMonitor()


def extract_mon(entries: List[Dict[int, Dict[str, float]]],
                train_cls: int) -> List[Dict[str, float]]:
    """Pop ``mon_*`` keys out of drained per-epoch metric dicts (in
    place) and return them as one sample per epoch — the Decision must
    never see the monitor's auxiliary accumulators."""
    samples: List[Dict[str, float]] = []
    for entry in entries:
        metrics = entry.get(train_cls)
        if not metrics:
            continue
        mon = {k: metrics.pop(k) for k in list(metrics)
               if k.startswith(MON_PREFIX)}
        if mon:
            samples.append(mon)
    return samples
