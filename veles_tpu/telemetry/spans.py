"""Unit-level spans: nested timing intervals with counter deltas.

A span brackets one piece of framework work — a unit's ``run``, a
workflow's scheduler pass, one fused train-step dispatch — and records,
besides wall time, the *deterministic* accounting for that interval:
how many device programs were dispatched inside it, how many compiles
happened, how many bytes crossed the host↔device boundary (deltas of
:mod:`veles_tpu.telemetry.counters`). Nesting is tracked per thread so
the JSONL stream reconstructs the call tree, and
:mod:`~veles_tpu.telemetry.chrome_trace` converts it to Chrome
``trace_event`` JSON for Perfetto.

Usage::

    with span("unit.run", unit="loader"):
        ...
    @spanned("decode")
    def decode(...): ...

The recorder keeps an in-memory ring (cheap: one deque append per
span) and optionally streams JSONL to a file (``set_sink`` — wired to
``--trace-file`` by the CLI). Span records are plain dicts::

    {"name": ..., "ts": ..., "dur": ..., "depth": ..., "parent": ...,
     "sid": ..., "tid": ..., "counters": {...}, ...attrs}
"""

from __future__ import annotations

import collections
import functools
import itertools
import json
import os
import threading
import time
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple

from .counters import counters

#: counters whose per-span deltas ride in every span record; the rest
#: of the registry is process-global only (a span that moved no bytes
#: carries no counter keys at all)
SPAN_COUNTERS = ("veles_dispatches_total", "veles_compiles_total",
                 "veles_h2d_bytes_total", "veles_d2h_bytes_total")

_ids = itertools.count(1)

#: span-close observers installed by the flight recorder
#: (telemetry/recorder.py): called with the completed record AFTER the
#: ring lock is released; exceptions swallowed.
_close_hooks = []


def add_close_hook(fn) -> None:
    if fn not in _close_hooks:
        _close_hooks.append(fn)


#: cached config NODE (not values): the auto-vivified
#: root.common.trace node is stable, so caching it keeps the per-span
#: knob lookups to one dict get while config writes stay immediately
#: visible (same discipline as telemetry/recorder.py)
_cfg_node = None


def _cfg(name: str, default):
    global _cfg_node
    try:
        if _cfg_node is None:
            from ..config import root
            _cfg_node = root.common.trace
        return _cfg_node.get(name, default)
    except Exception:            # noqa: BLE001 — config not importable
        return default           # (tests importing spans standalone)


def _cfg_int(name: str, default: int) -> int:
    """Integer config knob, malformed values degraded to the default:
    these lookups sit on the span APPEND path, where an operator's
    ``span_ring = "64k"`` must not turn every instrumented ``with
    span(...)`` exit in the tree into a ValueError."""
    value = _cfg(name, default)
    if value is None:
        return default
    try:
        return int(value)            # 0 stays 0 — "disabled" knobs
    except (TypeError, ValueError):
        return default


def _enabled() -> bool:
    """THE span on/off switch (``root.common.trace.spans``), honored
    centrally by the recorder so every instrumented site — Unit.run,
    workflow.run/initialize, the train step, the decoders — obeys one
    knob."""
    return bool(_cfg("spans", True))


class _Frame:
    __slots__ = ("name", "sid", "t0", "before", "attrs", "disabled")

    def __init__(self, name, sid, t0, before, attrs, disabled=False):
        self.name, self.sid, self.t0 = name, sid, t0
        self.before, self.attrs = before, attrs
        self.disabled = disabled


class SpanRecorder:
    """Ring of completed span records + optional JSONL file sink.

    The ring is the span plane's bounded black box (the span twin of
    the flight recorder's 4096-event discipline): long-running
    serving replicas keep their recent spans pullable over
    ``GET /trace/spans?since=CURSOR`` without ever needing a
    ``--trace-file``. Every appended record carries a process-
    monotonic ``seq`` — the pull cursor — and the ring's capacity
    follows ``root.common.trace.span_ring`` (default 65536)."""

    def __init__(self, maxlen: int = 65536,
                 follow_config: bool = False) -> None:
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=maxlen)
        self._file = None
        self._path: Optional[str] = None
        self._tls = threading.local()
        #: process-monotonic append sequence — the /trace/spans cursor
        self._seq = 0
        #: bytes appended to the current sink file (rotation ledger)
        self._sink_bytes = 0
        #: True only on the process-global instance: the ring tracks
        #: the root.common.trace.span_ring capacity knob (explicit
        #: capacities — tests — stay fixed)
        self._follow_config = follow_config

    # -- sink ----------------------------------------------------------------
    def set_sink(self, path: Optional[str]) -> None:
        """Stream completed spans as JSON lines to ``path`` (append);
        None closes the sink. The in-memory ring keeps recording either
        way."""
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
                self._path = None
            if path:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                # LINE buffered: each record reaches the fd whole at
                # its newline, so another handle appending to the same
                # file (the logger's event sink shares --trace-file)
                # can never interleave mid-JSON-line
                self._file = open(path, "a", buffering=1)
                self._path = path
                try:
                    self._sink_bytes = os.path.getsize(path)
                except OSError:
                    self._sink_bytes = 0

    @property
    def sink_path(self) -> Optional[str]:
        return self._path

    # -- span lifecycle ------------------------------------------------------
    def _stack(self) -> List[_Frame]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def begin(self, name: str, **attrs: Any) -> _Frame:
        if not _enabled():
            # disabled: hand back an inert frame (attrs writes land in
            # a discarded dict) — no stack push, no counter snapshot
            return _Frame(name, 0, 0.0, {}, attrs, disabled=True)
        frame = _Frame(name, next(_ids), time.time(),
                       counters.snapshot(), attrs)
        self._stack().append(frame)
        return frame

    def end(self, frame: _Frame) -> Dict[str, Any]:
        if frame.disabled:
            return {}
        stack = self._stack()
        # pop through to our frame: a leaked child (generator never
        # closed, exception path) must not corrupt later nesting
        while stack and stack[-1] is not frame:
            stack.pop()
        if stack:
            stack.pop()
        rec: Dict[str, Any] = {
            "name": frame.name,
            "ts": frame.t0,
            "dur": time.time() - frame.t0,
            "depth": len(stack),
            "parent": stack[-1].sid if stack else None,
            "sid": frame.sid,
            "tid": threading.get_ident(),
        }
        delta = counters.delta(frame.before, SPAN_COUNTERS)
        if delta:
            rec["counters"] = delta
        rec.update(frame.attrs)
        self._append(rec)
        return rec

    def _append(self, rec: Dict[str, Any]) -> None:
        """Shared tail of :meth:`end`/:meth:`emit`: stamp the pull
        cursor, honor the ring-capacity knob, append, stream to the
        sink (rotating past ``root.common.trace.rotate_bytes``), then
        run the close hooks outside the lock."""
        counters.inc("veles_spans_total")
        rotated = False
        with self._lock:
            if self._follow_config:
                # honor a changed span_ring knob (the global instance
                # is built at import, before any config lands)
                want = _cfg_int("span_ring", self._ring.maxlen)
                if want > 0 and want != self._ring.maxlen:
                    self._ring = collections.deque(self._ring,
                                                   maxlen=want)
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
            if self._file is not None:
                line = json.dumps(rec, default=str) + "\n"
                self._file.write(line)
                # BYTE ledger (set_sink/rotation reseed it from
                # getsize): json.dumps ASCII-escapes by default, but
                # default=str stringifies arbitrary attrs — count
                # encoded bytes, not code points
                self._sink_bytes += len(line.encode("utf-8"))
                rotated = self._maybe_rotate_locked()
        if rotated:
            counters.inc("veles_trace_rotations_total")
        for hook in _close_hooks:
            try:
                hook(rec)
            except Exception:       # noqa: BLE001 — observers only
                pass

    def _maybe_rotate_locked(self) -> bool:
        """Rotate the JSONL sink once it grows past
        ``root.common.trace.rotate_bytes`` (default 64 MiB; 0
        disables): the full segment moves to ``<path>.1`` — dropping
        the previous ``.1``, the journal's segment-drop pattern — and
        a fresh file opens at ``<path>``, so a long-running serving
        process's trace file is bounded by ~2x the knob instead of
        growing with traffic history. Counted
        ``veles_trace_rotations_total`` (by the caller, outside the
        lock). A sink another writer still appends to (the logger's
        event handle shares ``--trace-file``) keeps following the
        rotated-out segment until its next reopen — documented in
        docs/observability.md."""
        limit = _cfg_int("rotate_bytes", 64 << 20)
        if limit <= 0 or self._sink_bytes < limit \
                or self._file is None or self._path is None:
            return False
        try:
            self._file.close()
            os.replace(self._path, self._path + ".1")
            self._file = open(self._path, "a", buffering=1)
            self._sink_bytes = 0
            return True
        except OSError:
            # a failed rotation must not kill span recording: reopen
            # the (possibly still-present) sink and keep appending
            try:
                self._file = open(self._path, "a", buffering=1)
                self._sink_bytes = os.path.getsize(self._path)
            except OSError as e:
                # double failure (disk gone, permissions flipped):
                # the sink is DEAD — say so and stop reporting it as
                # active, instead of silently dropping every span
                import logging
                logging.getLogger("veles_tpu.telemetry").warning(
                    "trace sink %s lost during rotation (%s: %s) — "
                    "span file streaming stops; the in-memory ring "
                    "keeps recording", self._path,
                    type(e).__name__, e)
                self._file = None
                self._path = None
            return False

    def emit(self, name: str, ts: float, dur: float,
             **attrs: Any) -> Dict[str, Any]:
        """Record an ALREADY-MEASURED interval as a completed span —
        the retrospective twin of begin/end, for timelines assembled
        from host timestamps after the fact (the per-request lifecycle
        spans the serving plane emits at ticket terminal: queue wait,
        prefill, decode — each tagged ``request_id`` so ``veles-tpu
        trace export --request ID`` renders one request's timeline).
        No nesting (depth 0) and no counter deltas: the interval was
        not bracketed live, so attributing registry deltas to it would
        be a lie. Honors the ``root.common.trace.spans`` switch."""
        if not _enabled():
            return {}
        rec: Dict[str, Any] = {
            "name": name,
            "ts": float(ts),
            "dur": max(float(dur), 0.0),
            "depth": 0,
            "parent": None,
            "sid": next(_ids),
            "tid": threading.get_ident(),
        }
        rec.update(attrs)
        self._append(rec)
        return rec

    # -- introspection -------------------------------------------------------
    def records(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            recs = list(self._ring)
        if name is not None:
            recs = [r for r in recs if r["name"] == name]
        return recs

    def cursor(self) -> int:
        """The current pull cursor (the newest record's seq) without
        copying any records — for callers that only want a position
        to pull *from* later."""
        with self._lock:
            return self._seq

    def records_since(self, cursor: int
                      ) -> Tuple[List[Dict[str, Any]], int]:
        """(records appended after ``cursor``, the new cursor) — the
        incremental read behind ``GET /trace/spans?since=CURSOR``. A
        cursor older than the ring's tail silently skips the evicted
        records (bounded ring, same contract as the flight
        recorder); cursor 0 returns everything still buffered."""
        cursor = int(cursor)
        out: List[Dict[str, Any]] = []
        with self._lock:
            # seq climbs with ring order: walk from the newest end
            # and stop at the cursor, so an incremental pull near
            # the tip never scans the whole 65536-record ring under
            # the lock the append path shares
            for rec in reversed(self._ring):
                if int(rec.get("seq", 0)) <= cursor:
                    break
                out.append(rec)
            nxt = self._seq
        out.reverse()
        return out, nxt

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def to_jsonl(self, path: str) -> int:
        """Dump the ring as JSON lines; returns the record count."""
        recs = self.records()
        with open(path, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec, default=str) + "\n")
        return len(recs)


#: THE process-global recorder (mirrors counters.counters).
recorder = SpanRecorder(follow_config=True)

#: process-unique instance token for the /trace/spans header: pids
#: are per-HOST, so a multi-host fleet can hold two distinct
#: processes with one pid — the fleet assembler groups on this token
#: (falling back to pid for payloads from older builds) so they
#: never merge into one lane or steal each other's clock offset
import uuid as _uuid                                    # noqa: E402

instance_id = _uuid.uuid4().hex[:12]


def pull_payload(since: int = 0, name: str = "") -> str:
    """The ``GET /trace/spans?since=CURSOR`` response body: one JSONL
    header line identifying the process (pid, service name, the new
    cursor, this host's wall clock at render time) followed by one
    line per span record appended after ``since``. JSONL on purpose —
    a response torn mid-record (dead replica, truncated read)
    salvages line by line exactly like :func:`read_jsonl`, instead of
    one torn JSON document losing everything. Served by the router
    and both serving APIs; consumed by ``veles-tpu trace fleet``
    (telemetry/fleet.py). Counted ``veles_trace_span_pulls_total``."""
    recs, cursor = recorder.records_since(since)
    header = {"kind": "spans.header", "pid": os.getpid(),
              "instance": instance_id,
              "name": str(name or ""), "cursor": cursor,
              "wall": time.time(), "spans": len(recs)}
    counters.inc("veles_trace_span_pulls_total")
    return "\n".join(json.dumps(r, default=str)
                     for r in [header] + recs) + "\n"


class span:
    """``with span("name", key=val): ...`` — records one span on the
    global recorder. Re-entrant and thread-safe; exceptions still close
    the span (flagged ``error=True``)."""

    def __init__(self, name: str, **attrs: Any) -> None:
        self._name, self._attrs = name, attrs
        self.record: Optional[Dict[str, Any]] = None

    def __enter__(self) -> "span":
        self._frame = recorder.begin(self._name, **self._attrs)
        return self

    def __exit__(self, exc_type, *exc: Any) -> None:
        if exc_type is not None:
            self._frame.attrs["error"] = True
        self.record = recorder.end(self._frame)


def matches_request(record: Dict[str, Any], request: str) -> bool:
    """Does a span record / flight event belong to one serving
    request? Matches the ``request_id`` OR the fleet ``trace_id`` tag
    — THE one correlation predicate ``trace export --request``,
    ``trace fleet --request`` and ``blackbox inspect --request``
    share, so the three views can never disagree on which records
    tell a request's story."""
    rid = str(request)
    return str(record.get("request_id")) == rid \
        or str(record.get("trace_id")) == rid


def emit(name: str, ts: float, dur: float, **attrs: Any
         ) -> Dict[str, Any]:
    """Module-level :meth:`SpanRecorder.emit` on the global recorder
    (mirrors :class:`span`)."""
    return recorder.emit(name, ts, dur, **attrs)


def spanned(name: Optional[str] = None, **attrs: Any):
    """Decorator form: ``@spanned("phase")`` or bare ``@spanned()``
    (span named after the function)."""
    def deco(fn):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any):
            with span(span_name, **attrs):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Load span records back from a JSONL file (skips lines that are
    not span records, so a file shared with logger events loads too).
    Lines that fail to parse at all — a mid-write-truncated tail, a
    torn append — are skipped with ONE counted warning instead of
    raising: a partially-written trace must still export."""
    out = []
    bad = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict) and "name" in rec and "ts" in rec:
                out.append(rec)
    if bad:
        import logging
        logging.getLogger("veles_tpu.telemetry").warning(
            "skipped %d malformed JSONL line(s) in %s (empty or "
            "mid-write truncated records)", bad, path)
    return out


def tree(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Reconstruct nesting: returns root records with ``children``
    lists attached (records are shallow-copied; input order kept)."""
    by_sid: Dict[Any, Dict[str, Any]] = {}
    roots: List[Dict[str, Any]] = []
    for rec in records:
        node = dict(rec)
        node["children"] = []
        by_sid[node.get("sid")] = node
    for node in by_sid.values():
        parent = by_sid.get(node.get("parent"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots
