"""Unit-level spans: nested timing intervals with counter deltas.

A span brackets one piece of framework work — a unit's ``run``, a
workflow's scheduler pass, one fused train-step dispatch — and records,
besides wall time, the *deterministic* accounting for that interval:
how many device programs were dispatched inside it, how many compiles
happened, how many bytes crossed the host↔device boundary (deltas of
:mod:`veles_tpu.telemetry.counters`). Nesting is tracked per thread so
the JSONL stream reconstructs the call tree, and
:mod:`~veles_tpu.telemetry.chrome_trace` converts it to Chrome
``trace_event`` JSON for Perfetto.

Usage::

    with span("unit.run", unit="loader"):
        ...
    @spanned("decode")
    def decode(...): ...

The recorder keeps an in-memory ring (cheap: one deque append per
span) and optionally streams JSONL to a file (``set_sink`` — wired to
``--trace-file`` by the CLI). Span records are plain dicts::

    {"name": ..., "ts": ..., "dur": ..., "depth": ..., "parent": ...,
     "sid": ..., "tid": ..., "counters": {...}, ...attrs}
"""

from __future__ import annotations

import collections
import functools
import itertools
import json
import os
import threading
import time
from typing import Any, Deque, Dict, Iterable, List, Optional

from .counters import counters

#: counters whose per-span deltas ride in every span record; the rest
#: of the registry is process-global only (a span that moved no bytes
#: carries no counter keys at all)
SPAN_COUNTERS = ("veles_dispatches_total", "veles_compiles_total",
                 "veles_h2d_bytes_total", "veles_d2h_bytes_total")

_ids = itertools.count(1)

#: span-close observers installed by the flight recorder
#: (telemetry/recorder.py): called with the completed record AFTER the
#: ring lock is released; exceptions swallowed.
_close_hooks = []


def add_close_hook(fn) -> None:
    if fn not in _close_hooks:
        _close_hooks.append(fn)


def _enabled() -> bool:
    """THE span on/off switch (``root.common.trace.spans``), honored
    centrally by the recorder so every instrumented site — Unit.run,
    workflow.run/initialize, the train step, the decoders — obeys one
    knob."""
    try:
        from ..config import root
        return bool(root.common.trace.get("spans", True))
    except Exception:            # noqa: BLE001 — config not importable
        return True              # (tests importing spans standalone)


class _Frame:
    __slots__ = ("name", "sid", "t0", "before", "attrs", "disabled")

    def __init__(self, name, sid, t0, before, attrs, disabled=False):
        self.name, self.sid, self.t0 = name, sid, t0
        self.before, self.attrs = before, attrs
        self.disabled = disabled


class SpanRecorder:
    """Ring of completed span records + optional JSONL file sink."""

    def __init__(self, maxlen: int = 65536) -> None:
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=maxlen)
        self._file = None
        self._path: Optional[str] = None
        self._tls = threading.local()

    # -- sink ----------------------------------------------------------------
    def set_sink(self, path: Optional[str]) -> None:
        """Stream completed spans as JSON lines to ``path`` (append);
        None closes the sink. The in-memory ring keeps recording either
        way."""
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
                self._path = None
            if path:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                # LINE buffered: each record reaches the fd whole at
                # its newline, so another handle appending to the same
                # file (the logger's event sink shares --trace-file)
                # can never interleave mid-JSON-line
                self._file = open(path, "a", buffering=1)
                self._path = path

    @property
    def sink_path(self) -> Optional[str]:
        return self._path

    # -- span lifecycle ------------------------------------------------------
    def _stack(self) -> List[_Frame]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def begin(self, name: str, **attrs: Any) -> _Frame:
        if not _enabled():
            # disabled: hand back an inert frame (attrs writes land in
            # a discarded dict) — no stack push, no counter snapshot
            return _Frame(name, 0, 0.0, {}, attrs, disabled=True)
        frame = _Frame(name, next(_ids), time.time(),
                       counters.snapshot(), attrs)
        self._stack().append(frame)
        return frame

    def end(self, frame: _Frame) -> Dict[str, Any]:
        if frame.disabled:
            return {}
        stack = self._stack()
        # pop through to our frame: a leaked child (generator never
        # closed, exception path) must not corrupt later nesting
        while stack and stack[-1] is not frame:
            stack.pop()
        if stack:
            stack.pop()
        rec: Dict[str, Any] = {
            "name": frame.name,
            "ts": frame.t0,
            "dur": time.time() - frame.t0,
            "depth": len(stack),
            "parent": stack[-1].sid if stack else None,
            "sid": frame.sid,
            "tid": threading.get_ident(),
        }
        delta = counters.delta(frame.before, SPAN_COUNTERS)
        if delta:
            rec["counters"] = delta
        rec.update(frame.attrs)
        counters.inc("veles_spans_total")
        with self._lock:
            self._ring.append(rec)
            if self._file is not None:
                self._file.write(json.dumps(rec, default=str) + "\n")
        for hook in _close_hooks:
            try:
                hook(rec)
            except Exception:       # noqa: BLE001 — observers only
                pass
        return rec

    def emit(self, name: str, ts: float, dur: float,
             **attrs: Any) -> Dict[str, Any]:
        """Record an ALREADY-MEASURED interval as a completed span —
        the retrospective twin of begin/end, for timelines assembled
        from host timestamps after the fact (the per-request lifecycle
        spans the serving plane emits at ticket terminal: queue wait,
        prefill, decode — each tagged ``request_id`` so ``veles-tpu
        trace export --request ID`` renders one request's timeline).
        No nesting (depth 0) and no counter deltas: the interval was
        not bracketed live, so attributing registry deltas to it would
        be a lie. Honors the ``root.common.trace.spans`` switch."""
        if not _enabled():
            return {}
        rec: Dict[str, Any] = {
            "name": name,
            "ts": float(ts),
            "dur": max(float(dur), 0.0),
            "depth": 0,
            "parent": None,
            "sid": next(_ids),
            "tid": threading.get_ident(),
        }
        rec.update(attrs)
        counters.inc("veles_spans_total")
        with self._lock:
            self._ring.append(rec)
            if self._file is not None:
                self._file.write(json.dumps(rec, default=str) + "\n")
        for hook in _close_hooks:
            try:
                hook(rec)
            except Exception:       # noqa: BLE001 — observers only
                pass
        return rec

    # -- introspection -------------------------------------------------------
    def records(self, name: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            recs = list(self._ring)
        if name is not None:
            recs = [r for r in recs if r["name"] == name]
        return recs

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def to_jsonl(self, path: str) -> int:
        """Dump the ring as JSON lines; returns the record count."""
        recs = self.records()
        with open(path, "w") as f:
            for rec in recs:
                f.write(json.dumps(rec, default=str) + "\n")
        return len(recs)


#: THE process-global recorder (mirrors counters.counters).
recorder = SpanRecorder()


class span:
    """``with span("name", key=val): ...`` — records one span on the
    global recorder. Re-entrant and thread-safe; exceptions still close
    the span (flagged ``error=True``)."""

    def __init__(self, name: str, **attrs: Any) -> None:
        self._name, self._attrs = name, attrs
        self.record: Optional[Dict[str, Any]] = None

    def __enter__(self) -> "span":
        self._frame = recorder.begin(self._name, **self._attrs)
        return self

    def __exit__(self, exc_type, *exc: Any) -> None:
        if exc_type is not None:
            self._frame.attrs["error"] = True
        self.record = recorder.end(self._frame)


def emit(name: str, ts: float, dur: float, **attrs: Any
         ) -> Dict[str, Any]:
    """Module-level :meth:`SpanRecorder.emit` on the global recorder
    (mirrors :class:`span`)."""
    return recorder.emit(name, ts, dur, **attrs)


def spanned(name: Optional[str] = None, **attrs: Any):
    """Decorator form: ``@spanned("phase")`` or bare ``@spanned()``
    (span named after the function)."""
    def deco(fn):
        span_name = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any):
            with span(span_name, **attrs):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def read_jsonl(path: str) -> List[Dict[str, Any]]:
    """Load span records back from a JSONL file (skips lines that are
    not span records, so a file shared with logger events loads too).
    Lines that fail to parse at all — a mid-write-truncated tail, a
    torn append — are skipped with ONE counted warning instead of
    raising: a partially-written trace must still export."""
    out = []
    bad = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                bad += 1
                continue
            if isinstance(rec, dict) and "name" in rec and "ts" in rec:
                out.append(rec)
    if bad:
        import logging
        logging.getLogger("veles_tpu.telemetry").warning(
            "skipped %d malformed JSONL line(s) in %s (empty or "
            "mid-write truncated records)", bad, path)
    return out


def tree(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Reconstruct nesting: returns root records with ``children``
    lists attached (records are shallow-copied; input order kept)."""
    by_sid: Dict[Any, Dict[str, Any]] = {}
    roots: List[Dict[str, Any]] = []
    for rec in records:
        node = dict(rec)
        node["children"] = []
        by_sid[node.get("sid")] = node
    for node in by_sid.values():
        parent = by_sid.get(node.get("parent"))
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    return roots
