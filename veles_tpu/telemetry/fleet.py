"""Fleet /metrics aggregation: scrape-and-merge over N endpoints.

The ROADMAP-item-1 topology is N engine replicas behind a router; its
observability substrate is ONE fleet-wide /metrics view — "what is
p99 TTFT across the fleet", not per replica. This module scrapes the
Prometheus exposition every veles_tpu HTTP surface renders (the
shared :func:`~veles_tpu.telemetry.counters.metrics_text` path on
web_status, RESTfulAPI and GenerationAPI) and merges:

- **counters** are SUMMED (each is a per-process monotonic total);
- **histogram buckets** are SUMMED per ``le`` bound, ``_sum`` and
  ``_count`` with them — fixed buckets make this lossless, which is
  exactly why the registry uses fixed bounds instead of per-process
  quantile sketches — and the fleet p50/p90/p99 are RECOMPUTED from
  the merged buckets (never averaged from per-endpoint quantiles,
  which is statistically meaningless);
- **gauges** are SUMMED (slots busy, queue depth, pages in use — the
  fleet totals an admission/spill/drain router decides on); the
  per-endpoint quantile gauges the endpoints derive from their own
  buckets are DROPPED (they are recomputed fleet-wide);
- per-endpoint **up/down status** rides along as
  ``veles_fleet_endpoint_up{endpoint="..."}`` rows, so a dead
  replica is visible in the very page that hides its counters.

CLI: ``veles-tpu metrics aggregate URL [URL ...]`` prints the merged
exposition; ``--json`` prints the structured form. Operator guide:
docs/observability.md "Request-plane SLOs".
"""

from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Sequence, Tuple

from .counters import (METRICS_CONTENT_TYPE,          # noqa: F401
                       QUANTILE_GAUGES, describe_counter,
                       describe_histogram, gauge_text,
                       histogram_quantile, inc)

#: quantile-gauge suffixes the endpoints derive locally — dropped on
#: merge and recomputed from the merged buckets
_QUANTILE_SUFFIXES = tuple("_" + label for _q, label in QUANTILE_GAUGES)

#: one exposition sample line: ``name{labels} value`` or ``name value``
_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(\{([^}]*)\})?\s+(\S+)$")

_LE_RE = re.compile(r'le="([^"]+)"')


def parse_metrics_text(text: str) -> Dict[str, Dict]:
    """Prometheus exposition text → ``{"counters": {name: value},
    "gauges": {...}, "histograms": {name: {"buckets": {le: cum},
    "sum": s, "count": n}}}``. ``# TYPE`` lines drive classification;
    untyped samples land in gauges (safe: summing an unknown series
    is no worse than dropping it, and the names stay visible).
    Labeled series other than histogram ``le`` buckets are skipped —
    the veles surfaces emit none, and guessing how to merge foreign
    labels would corrupt the page."""
    types: Dict[str, str] = {}
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict] = {}

    def hist(base: str) -> Dict:
        return hists.setdefault(
            base, {"buckets": {}, "sum": 0.0, "count": 0.0})

    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels, raw = m.group(1), m.group(3), m.group(4)
        try:
            value = float(raw)
        except ValueError:
            continue
        if name.endswith("_bucket") \
                and types.get(name[:-7]) == "histogram":
            le = _LE_RE.search(labels or "")
            if le:
                hist(name[:-7])["buckets"][le.group(1)] = value
            continue
        if name.endswith("_sum") and types.get(name[:-4]) == "histogram":
            hist(name[:-4])["sum"] = value
            continue
        if name.endswith("_count") \
                and types.get(name[:-6]) == "histogram":
            hist(name[:-6])["count"] = value
            continue
        if labels:
            continue
        if types.get(name) == "counter":
            counters[name] = value
        else:
            gauges[name] = value
    return {"counters": counters, "gauges": gauges,
            "histograms": hists}


def _le_value(le: str) -> float:
    return float("inf") if le == "+Inf" else float(le)


def _cum_at(buckets: Dict[str, float], bound: float) -> float:
    """Cumulative count of a histogram at ``bound`` — the largest
    recorded cumulative count at a bound <= ``bound`` (the step
    function a cumulative histogram IS), so endpoints with different
    bucket grids still merge exactly at their common bounds."""
    best = 0.0
    for le, cum in buckets.items():
        if _le_value(le) <= bound:
            best = max(best, cum)
    return best


def merge(parsed: Sequence[Dict[str, Dict]]) -> Dict[str, Dict]:
    """Merge N :func:`parse_metrics_text` results into one fleet
    view: counters and gauges summed, histogram buckets summed per
    bound (union of bounds, each endpoint evaluated as the step
    function its cumulative buckets define), sums/counts summed.
    Per-endpoint quantile gauges are dropped — :func:`quantiles`
    recomputes them from the merged buckets."""
    out: Dict[str, Dict] = {"counters": {}, "gauges": {},
                            "histograms": {}}
    for p in parsed:
        for name, val in p.get("counters", {}).items():
            out["counters"][name] = out["counters"].get(name, 0.0) + val
        for name, val in p.get("gauges", {}).items():
            if name == "veles_serving_tp":
                # mesh-slice width, NOT additive load: a tp=4 replica
                # is ONE endpoint spanning 4 chips — fold the widths
                # into the fleet chip total (solo engines export
                # tp=1) instead of letting the generic sum read as
                # "4 of something" on one replica's row
                out["gauges"]["veles_fleet_chips"] = (
                    out["gauges"].get("veles_fleet_chips", 0.0)
                    + max(1.0, val))
                continue
            out["gauges"][name] = out["gauges"].get(name, 0.0) + val
        for name, h in p.get("histograms", {}).items():
            tgt = out["histograms"].setdefault(
                name, {"buckets": {}, "sum": 0.0, "count": 0.0})
            bounds = {le for le in h["buckets"]} \
                | set(tgt["buckets"])
            merged = {}
            for le in bounds:
                merged[le] = (_cum_at(tgt["buckets"], _le_value(le))
                              + _cum_at(h["buckets"], _le_value(le)))
            tgt["buckets"] = merged
            tgt["sum"] += h["sum"]
            tgt["count"] += h["count"]
    # drop the per-endpoint quantile gauges in one pass over the
    # FINAL histogram name set (they are recomputed fleet-wide)
    for name in list(out["gauges"]):
        if any(name == h + s for h in out["histograms"]
               for s in _QUANTILE_SUFFIXES):
            del out["gauges"][name]
    return out


def hist_to_snapshot(hist: Dict) -> Dict:
    """A merged CUMULATIVE-bucket histogram (the exposition form) →
    the registry-snapshot form (``{"bounds", "counts"
    (non-cumulative, + overflow), "sum", "count"}``) —  exactly what
    :meth:`~veles_tpu.telemetry.timeseries.SeriesStore.ingest`
    stores, so a remote scrape and a local registry sample derive
    windowed quantiles through the same arithmetic."""
    items = sorted(((le, cum) for le, cum in hist["buckets"].items()
                    if le != "+Inf"),
                   key=lambda kv: _le_value(kv[0]))
    bounds = [_le_value(le) for le, _ in items]
    counts: List[float] = []
    prev = 0.0
    for _le, cum in items:
        counts.append(max(0.0, cum - prev))
        prev = max(prev, cum)
    counts.append(max(0.0, float(hist["count"]) - prev))  # +Inf bucket
    return {"bounds": bounds, "counts": counts,
            "sum": float(hist.get("sum", 0.0)),
            "count": float(hist.get("count", 0.0))}


def quantiles(hist: Dict, qs=(0.5, 0.9, 0.99)) -> Dict[float, Optional[float]]:
    """Recompute quantiles from a merged histogram's CUMULATIVE
    buckets (the exposition form) via the shared
    :func:`histogram_quantile` arithmetic."""
    snap = hist_to_snapshot(hist)
    return {q: histogram_quantile(snap["bounds"], snap["counts"], q)
            for q in qs}


def ingest_aggregate(store, agg: Dict, ts: Optional[float] = None
                     ) -> None:
    """Feed one :func:`aggregate` result into a client-side
    :class:`~veles_tpu.telemetry.timeseries.SeriesStore` (built with
    ``count_samples=False`` — a watching CLI must not move the
    watched fleet's, or its own process's, watch counters). The
    endpoint up/down status rides along as fleet gauges so the watch
    loop can display roster health from the same ring."""
    merged = agg["merged"]
    hists = {name: hist_to_snapshot(h)
             for name, h in merged["histograms"].items()}
    gauges = dict(merged["gauges"])
    gauges["veles_fleet_endpoints"] = len(agg["endpoints"])
    gauges["veles_fleet_endpoints_up"] = sum(
        1 for ep in agg["endpoints"] if ep["up"])
    store.ingest(merged["counters"], hists, gauges, ts=ts)


def interval_report(store, window: Optional[float] = None) -> Dict:
    """One watch-interval summary from a client-side store: request/
    token rates and WINDOWED latency quantiles (bucket deltas between
    the window's endpoint samples — the cumulative ``_p99`` gauges on
    the scrape page would bury a brownout under the whole run's
    history), plus the fleet occupancy gauges of the newest sample.
    Values are None until two samples exist."""
    def _r(v, nd=3):
        return None if v is None else round(v, nd)
    return {
        "up": store.gauge("veles_fleet_endpoints_up"),
        "endpoints": store.gauge("veles_fleet_endpoints"),
        "qps": _r(store.rate("veles_serving_retired_total", window)),
        "tok_s": _r(store.rate("veles_serving_tokens_total", window)),
        "shed_s": _r(store.rate("veles_shed_requests_total", window)),
        "ttft_p50": _r(store.quantile(
            "veles_serving_ttft_seconds", 0.5, window), 4),
        "ttft_p99": _r(store.quantile(
            "veles_serving_ttft_seconds", 0.99, window), 4),
        "tpot_p50": _r(store.quantile(
            "veles_serving_tpot_seconds", 0.5, window), 4),
        "tpot_p99": _r(store.quantile(
            "veles_serving_tpot_seconds", 0.99, window), 4),
        "e2e_p99": _r(store.quantile(
            "veles_serving_e2e_seconds", 0.99, window), 4),
        "slots_busy": store.gauge("veles_serving_slots_busy"),
        "slots": store.gauge("veles_serving_slots"),
        "queue_depth": store.gauge("veles_serving_queue_depth"),
        "brownout": store.gauge("veles_qos_brownout_level"),
        "admit_rate": store.gauge("veles_qos_admit_rate"),
    }


def format_interval(rep: Dict) -> str:
    """One terminal line per watch interval (``veles-tpu metrics
    aggregate --watch``)."""
    def fmt(v, unit=""):
        return "-" if v is None else ("%g%s" % (v, unit))
    parts = ["up %s/%s" % (fmt(rep["up"]), fmt(rep["endpoints"])),
             "qps %s" % fmt(rep["qps"]),
             "tok/s %s" % fmt(rep["tok_s"]),
             "ttft p50/p99 %s/%s" % (fmt(rep["ttft_p50"], "s"),
                                     fmt(rep["ttft_p99"], "s")),
             "e2e p99 %s" % fmt(rep["e2e_p99"], "s"),
             "busy %s/%s" % (fmt(rep["slots_busy"]),
                             fmt(rep["slots"])),
             "queue %s" % fmt(rep["queue_depth"])]
    if rep.get("shed_s"):
        parts.append("shed/s %s" % fmt(rep["shed_s"]))
    if rep.get("brownout"):
        parts.append("brownout L%s" % fmt(rep["brownout"]))
    return "  ".join(parts)


def read_endpoints(path: str) -> List[str]:
    """Replica roster from a file — the ONE roster format fleet
    scraping (``veles-tpu metrics aggregate --endpoints-file``) and
    routing (``veles-tpu route --endpoints-file``) share. Two forms:

    - plain text: one endpoint per line, ``#`` comments and blank
      lines ignored;
    - JSON: a bare list of URLs, or an object with an ``"endpoints"``
      list whose items are URLs or ``{"url": ...}`` dicts — exactly
      what the router's ``GET /roster`` page is, so discovery output
      saved to disk feeds both consumers unchanged.

    Raises ValueError on malformed JSON/entries; an empty roster is
    the caller's error to report."""
    with open(path) as fin:
        text = fin.read()
    stripped = text.lstrip()
    if stripped.startswith("{") or stripped.startswith("["):
        doc = json.loads(text)
        items = doc.get("endpoints", []) if isinstance(doc, dict) \
            else doc
        out: List[str] = []
        for item in items:
            if isinstance(item, dict):
                url = item.get("url")
                if not isinstance(url, str) or not url:
                    raise ValueError(
                        "roster entry %r carries no \"url\"" % (item,))
                out.append(url)
            elif isinstance(item, str):
                out.append(item)
            else:
                raise ValueError("roster entry %r is neither a URL "
                                 "string nor a dict" % (item,))
        return out
    return [line for raw in text.splitlines()
            for line in [raw.split("#", 1)[0].strip()] if line]


def scrape(url: str, timeout: float = 5.0
           ) -> Tuple[Optional[str], Optional[str]]:
    """(body, error) for one /metrics endpoint — exactly one of the
    two is None. Bare host:port inputs get ``http://`` and
    ``/metrics`` filled in."""
    import urllib.error
    import urllib.request
    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/metrics"):
        url = url.rstrip("/") + "/metrics"
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.read().decode("utf-8", "replace"), None
    except Exception as e:      # noqa: BLE001 — a down replica is data
        return None, "%s: %s" % (type(e).__name__, e)


def aggregate(urls: Sequence[str], timeout: float = 5.0) -> Dict:
    """Scrape every endpoint and merge the live ones. Returns
    ``{"endpoints": [{"url", "up", "error"}...], "merged": {...}}`` —
    a down endpoint contributes its up=0 row and nothing else."""
    statuses = []
    parsed = []
    for url in urls:
        body, error = scrape(url, timeout=timeout)
        statuses.append({"url": url, "up": body is not None,
                         "error": error})
        if body is not None:
            parsed.append(parse_metrics_text(body))
    return {"endpoints": statuses, "merged": merge(parsed)}


def render(agg: Dict) -> str:
    """One fleet-wide exposition page from an :func:`aggregate`
    result: endpoint status rows, summed counters, merged histograms
    with RECOMPUTED p50/p90/p99 gauges, summed gauges."""
    lines = [
        "# HELP veles_fleet_endpoint_up 1 = endpoint scraped "
        "successfully, 0 = down",
        "# TYPE veles_fleet_endpoint_up gauge",
    ]
    for ep in agg["endpoints"]:
        lines.append('veles_fleet_endpoint_up{endpoint="%s"} %d'
                     % (ep["url"], 1 if ep["up"] else 0))
    text = "\n".join(lines) + "\n"
    text += gauge_text("veles_fleet_endpoints", len(agg["endpoints"]),
                       "Endpoints this aggregation covers")
    text += gauge_text("veles_fleet_endpoints_up",
                       sum(1 for ep in agg["endpoints"] if ep["up"]),
                       "Endpoints that answered the scrape")
    merged = agg["merged"]
    for name in sorted(merged["counters"]):
        val = merged["counters"][name]
        text += "# HELP %s %s\n# TYPE %s counter\n%s %s\n" % (
            name, describe_counter(name), name, name,
            int(val) if float(val).is_integer() else val)
    for name in sorted(merged["histograms"]):
        h = merged["histograms"][name]
        text += "# HELP %s %s\n# TYPE %s histogram\n" % (
            name, describe_histogram(name), name)
        for le, cum in sorted(h["buckets"].items(),
                              key=lambda kv: _le_value(kv[0])):
            text += '%s_bucket{le="%s"} %d\n' % (name, le, cum)
        if "+Inf" not in h["buckets"]:
            text += '%s_bucket{le="+Inf"} %d\n' % (name, h["count"])
        text += "%s_sum %s\n%s_count %d\n" % (
            name, round(float(h["sum"]), 9), name, h["count"])
        if h["count"]:
            qs = quantiles(h)
            for q, label in QUANTILE_GAUGES:
                if qs.get(q) is not None:
                    text += gauge_text(
                        "%s_%s" % (name, label), round(qs[q], 9),
                        "Fleet-recomputed %s of %s" % (label, name))
    for name in sorted(merged["gauges"]):
        val = merged["gauges"][name]
        text += gauge_text(name, val)
    return text


# -- fleet-wide distributed tracing (span pulls + timeline assembly) ----------
#
# The trace twin of the /metrics aggregation above: every request-
# plane HTTP surface serves its bounded span ring at GET
# /trace/spans?since=CURSOR (telemetry/spans.pull_payload — JSONL, a
# header line + one line per span), and `veles-tpu trace fleet` pulls
# the router's + every replica's rings, estimates per-process clock
# offsets by BRACKETING alignment — each router route.attempt span
# must contain, in true time, the replica `request` span carrying the
# same (trace_id, attempt) — and merges everything into ONE Chrome
# trace with one lane per process. The offset technique is
# devtime.attribute_spans' window alignment reapplied host-to-host;
# like there, it is an approximation: the estimate is only as tight
# as the attempt-minus-request slack (network + HTTP framing time),
# stated in docs/observability.md "Fleet tracing".

def _base_url(url: str) -> str:
    url = str(url).strip()
    if "://" not in url:
        url = "http://" + url
    url = url.rstrip("/")
    if url.endswith("/metrics"):
        url = url[:-len("/metrics")]
    return url


def scrape_spans(url: str, since: int = 0, timeout: float = 5.0
                 ) -> Tuple[Optional[str], Optional[str]]:
    """(body, error) for one ``/trace/spans`` endpoint — exactly one
    of the two is None (the :func:`scrape` contract, for span
    rings)."""
    import urllib.request
    full = "%s/trace/spans?since=%d" % (_base_url(url), int(since))
    try:
        with urllib.request.urlopen(full, timeout=timeout) as r:
            return r.read().decode("utf-8", "replace"), None
    except Exception as e:      # noqa: BLE001 — a down replica is data
        return None, "%s: %s" % (type(e).__name__, e)


def parse_span_payload(text: str) -> Dict:
    """One ``/trace/spans`` JSONL body → ``{"header": {...} | None,
    "spans": [...], "bad": n}``. Torn lines — a response truncated
    mid-record by a dying replica or a cut connection — are skipped
    with ONE counted warning (the ``spans.read_jsonl`` salvage rule):
    the complete prefix still assembles."""
    import logging
    header: Optional[Dict] = None
    spans: List[Dict] = []
    bad = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            bad += 1
            continue
        if not isinstance(rec, dict):
            bad += 1
            continue
        if rec.get("kind") == "spans.header":
            if header is None:
                header = rec
            continue
        # sanitize HERE, the one remote-data entry point: every
        # consumer downstream (grouping sort, bracketing pairs, lane
        # conversion) does float arithmetic on ts/dur, and a corrupt
        # record from a damaged ring must quarantine like a torn
        # line, never crash the assembler with a TypeError
        ts = rec.get("ts")
        dur = rec.get("dur", 0.0)
        if "name" not in rec \
                or not isinstance(ts, (int, float)) \
                or isinstance(ts, bool):
            bad += 1
            continue
        if not isinstance(dur, (int, float)) or isinstance(dur, bool):
            rec = dict(rec, dur=0.0)
        try:
            tid = int(rec.get("tid", 0))
        except (TypeError, ValueError):
            tid = 0
        if rec.get("tid", 0) != tid:
            rec = dict(rec, tid=tid)
        spans.append(rec)
    if bad:
        logging.getLogger("veles_tpu.telemetry").warning(
            "skipped %d torn/malformed line(s) in a /trace/spans "
            "payload (truncated mid-record; the complete prefix "
            "still assembles)", bad)
    return {"header": header, "spans": spans, "bad": bad}


def _group_processes(payloads: Sequence[Dict]) -> Dict:
    """Payloads (``{"url", "header", "spans"}``) → per-PROCESS span
    sets keyed by the header's ``instance`` token (falling back to
    the bare pid for payloads from builds without one — pids are
    per-host, so two hosts CAN hold distinct processes with one
    pid): ``{key: {"pid", "names", "spans"}}``, deduplicated within
    a process by the records' pull cursor — an in-process fleet
    (N replicas + router sharing one python process, the test/bench
    topology) pulls the SAME process-global ring through every
    endpoint, and triple-counting it would triple every lane."""
    procs: Dict = {}
    for payload in payloads:
        header = payload.get("header")
        if header is None:
            # a payload whose header line was torn away still merges
            # — keyed by its URL so two headerless SOURCES never
            # coalesce into one lane (their seq counters both start
            # at 1 and would cross-dedup each other's spans)
            header = {}
            key = "headerless:%s" % payload.get("url")
            pid = 0
        else:
            try:
                pid = int(header.get("pid", 0) or 0)
            except (TypeError, ValueError):
                # a damaged header quarantines like a torn record —
                # it must not crash the merge of healthy endpoints
                pid = 0
            key = header.get("instance") or pid
        entry = procs.setdefault(key, {"pid": pid, "names": [],
                                       "seen": {}, "spans": []})
        name = str(header.get("name") or payload.get("url") or "")
        if name and name not in entry["names"]:
            entry["names"].append(name)
        for rec in payload.get("spans", ()):
            dedup = (rec.get("seq"), rec.get("sid"), rec.get("ts"))
            if dedup in entry["seen"]:
                continue
            entry["seen"][dedup] = True
            entry["spans"].append(rec)
    for entry in procs.values():
        entry.pop("seen")
        entry["spans"].sort(key=lambda r: float(r.get("ts", 0.0)))
    return procs


def _bracket_pairs(attempts: Sequence[Dict], requests: Sequence[Dict]
                   ) -> List[Tuple[float, float]]:
    """Offset-bound intervals ``[lo, hi]`` (replica_clock −
    router_clock, seconds) from (route.attempt, request) span pairs
    sharing (trace_id, attempt): in true time the attempt brackets
    the replica's request span, so ``R_end − A_end ≤ offset ≤
    R_start − A_start``."""
    by_key = {}
    for a in attempts:
        key = (a.get("trace_id"), a.get("attempt"))
        if None not in key:
            by_key.setdefault(key, a)
    out: List[Tuple[float, float]] = []
    for r in requests:
        a = by_key.get((r.get("trace_id"), r.get("attempt")))
        if a is None:
            continue
        a0, a1 = float(a["ts"]), float(a["ts"]) + float(
            a.get("dur", 0.0))
        r0, r1 = float(r["ts"]), float(r["ts"]) + float(
            r.get("dur", 0.0))
        lo, hi = r1 - a1, r0 - a0
        if lo <= hi:
            out.append((lo, hi))
    return out


def estimate_offsets(procs: Dict) -> Dict:
    """Per-process clock offsets onto the ROUTER's clock, keyed like
    ``procs``: ``{key: {"pid", "offset": seconds, "pairs": n,
    "bound": slack}}``. The reference process is the one emitting
    ``route.attempt`` spans (offset 0 by definition); every other
    process's offset is the midpoint of the intersected bracketing
    intervals (median of midpoints when noise empties the
    intersection), ``bound`` the final interval's width — the stated
    uncertainty of the estimate. A process with no bracketing pair
    keeps offset 0 with ``pairs: 0`` (assembled on its own clock,
    flagged in the CLI summary)."""
    ref_key = None
    for key, entry in sorted(procs.items(), key=lambda kv: str(kv[0])):
        if any(r.get("name") == "route.attempt"
               for r in entry["spans"]):
            ref_key = key
            break
    if ref_key is None and procs:
        ref_key = sorted(procs, key=str)[0]
    out: Dict = {}
    attempts = [r for r in procs.get(ref_key, {}).get("spans", ())
                if r.get("name") == "route.attempt"] \
        if ref_key is not None else []
    for key, entry in procs.items():
        pid = entry.get("pid", 0)
        if key == ref_key:
            out[key] = {"pid": pid, "offset": 0.0, "pairs": 0,
                        "bound": 0.0, "reference": True}
            continue
        requests = [r for r in entry["spans"]
                    if r.get("name") == "request"]
        pairs = _bracket_pairs(attempts, requests)
        if not pairs:
            out[key] = {"pid": pid, "offset": 0.0, "pairs": 0,
                        "bound": None}
            continue
        lo = max(p[0] for p in pairs)
        hi = min(p[1] for p in pairs)
        if lo <= hi:
            offset, bound = (lo + hi) / 2.0, hi - lo
        else:
            # noisy pairs emptied the intersection: fall back to the
            # median of per-pair midpoints
            mids = sorted((a + b) / 2.0 for a, b in pairs)
            offset = mids[len(mids) // 2]
            bound = max(b - a for a, b in pairs)
        out[key] = {"pid": pid, "offset": offset,
                    "pairs": len(pairs), "bound": bound}
    return out


def assemble_fleet_trace(payloads: Sequence[Dict],
                         request: Optional[str] = None
                         ) -> Tuple[Dict, Dict]:
    """Merge N parsed ``/trace/spans`` payloads into ONE Chrome trace
    document: spans deduplicated per process, each process's clock
    shifted onto the router's by the bracketing estimate, one
    Perfetto lane per process. ``request`` keeps only one request's
    story — every span whose ``trace_id`` matches (resolving a
    request_id to its trace first), so the timeline reads: queue at
    the router, attempt 1, replica death, backoff, attempt 2 with
    resume, first token, terminal. Returns ``(trace document,
    summary)``; raises ValueError when nothing survives (an empty
    Perfetto page helps nobody). Counted
    ``veles_trace_fleet_merges_total``."""
    from . import chrome_trace
    procs = _group_processes(payloads)
    offsets = estimate_offsets(procs)
    if request is not None:
        from .spans import matches_request
        tids = {str(r.get("trace_id"))
                for entry in procs.values() for r in entry["spans"]
                if matches_request(r, request)
                and r.get("trace_id") is not None}
        if not tids:
            raise ValueError(
                "no span tagged request_id/trace_id %s in any pulled "
                "ring" % request)
    processes = []
    total = 0
    for key in sorted(procs,
                      key=lambda p: (not offsets[p].get("reference"),
                                     str(p))):
        entry = procs[key]
        off = offsets[key]["offset"]
        recs = []
        for rec in entry["spans"]:
            if request is not None \
                    and str(rec.get("trace_id")) not in tids \
                    and str(rec.get("request_id")) != str(request):
                continue
            out = dict(rec, ts=float(rec["ts"]) - off)
            if off:
                out["clock_offset_s"] = round(off, 6)
            recs.append(out)
        if not recs:
            # a process the --request filter emptied renders no lane
            # — and must not inflate the summary's lane count either
            continue
        total += len(recs)
        processes.append({
            "name": "%s (pid %d)" % ("+".join(entry["names"])
                                     or "process", entry["pid"]),
            "records": recs,
        })
    if not total:
        raise ValueError("no spans to assemble (empty rings%s)"
                         % (", or nothing tagged %s" % request
                            if request else ""))
    doc = {"traceEvents": chrome_trace.fleet_trace_events(processes),
           "displayTimeUnit": "ms"}
    errors = chrome_trace.validate(doc)
    if errors:        # assembler bug, not user input — fail loudly
        raise ValueError("invalid fleet trace produced: %s"
                         % errors[:3])
    inc("veles_trace_fleet_merges_total")
    summary = {
        "processes": len(processes),
        "spans": total,
        "offsets": {key: dict(offsets[key],
                              offset=round(offsets[key]["offset"], 6))
                    for key in offsets},
    }
    if request is not None:
        summary["trace_ids"] = sorted(tids)
    return doc, summary


def trace_fleet(urls: Sequence[str], request: Optional[str] = None,
                since: int = 0, timeout: float = 5.0
                ) -> Tuple[Dict, Dict]:
    """Pull every endpoint's span ring and assemble the fleet trace
    (``veles-tpu trace fleet`` driver). Down endpoints degrade to
    up=0 rows in the summary — the merge runs over whoever answered;
    raises ValueError when NOBODY did."""
    payloads = []
    statuses = []
    for url in urls:
        body, error = scrape_spans(url, since=since, timeout=timeout)
        statuses.append({"url": url, "up": body is not None,
                         "error": error})
        if body is None:
            continue
        parsed = parse_span_payload(body)
        parsed["url"] = url
        payloads.append(parsed)
    if not payloads:
        raise ValueError(
            "no /trace/spans endpoint answered (%s)"
            % "; ".join("%s: %s" % (s["url"], s["error"])
                        for s in statuses))
    doc, summary = assemble_fleet_trace(payloads, request=request)
    summary["endpoints"] = statuses
    return doc, summary


def main(argv) -> int:
    """``veles-tpu metrics aggregate URL [URL ...]`` driver (wired in
    veles_tpu/__main__.py). Exit 0 while at least one endpoint
    answered; 2 when the whole fleet is down (the merged page would
    be empty — an alert, not a report)."""
    import argparse
    parser = argparse.ArgumentParser(
        prog="veles_tpu metrics",
        description="fleet /metrics tools (telemetry/fleet.py)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    ag = sub.add_parser(
        "aggregate",
        help="scrape N /metrics endpoints, print the merged "
             "exposition (counters/buckets summed, quantiles "
             "recomputed, per-endpoint up/down rows)")
    ag.add_argument("urls", nargs="*", metavar="URL",
                    help="endpoint (http://host:port[/metrics]; bare "
                         "host:port accepted)")
    ag.add_argument("--endpoints-file", default=None, metavar="FILE",
                    help="replica roster file shared with the fleet "
                         "router: one endpoint per line (# comments), "
                         "or JSON — a bare URL list or the router's "
                         "GET /roster output saved to disk")
    ag.add_argument("--timeout", type=float, default=5.0,
                    help="per-endpoint scrape timeout, seconds")
    ag.add_argument("--json", action="store_true",
                    help="print the structured aggregation instead "
                         "of exposition text")
    ag.add_argument("--watch", type=float, default=None, metavar="SEC",
                    help="interval mode: re-scrape every SEC seconds "
                         "and print one summary line per interval "
                         "(windowed rates/quantiles from sample "
                         "deltas via the watchtower SeriesStore) "
                         "instead of one exposition page")
    ag.add_argument("--iterations", type=int, default=0, metavar="N",
                    help="with --watch: stop after N intervals "
                         "(0 = run until interrupted)")
    args = parser.parse_args(argv)
    urls = list(args.urls)
    if args.endpoints_file:
        try:
            urls += read_endpoints(args.endpoints_file)
        except (OSError, ValueError) as e:
            parser.error("bad --endpoints-file: %s" % e)
    if not urls:
        parser.error("no endpoints (positional URLs and/or "
                     "--endpoints-file)")
    if args.watch is not None:
        if args.watch <= 0:
            parser.error("--watch period must be > 0")
        return watch_aggregate(urls, period=args.watch,
                               iterations=args.iterations,
                               timeout=args.timeout,
                               as_json=args.json)
    agg = aggregate(urls, timeout=args.timeout)
    if args.json:
        print(json.dumps(agg, indent=2, sort_keys=True))
    else:
        print(render(agg), end="")
    return 0 if any(ep["up"] for ep in agg["endpoints"]) else 2


def watch_aggregate(urls: Sequence[str], period: float,
                    iterations: int = 0, timeout: float = 5.0,
                    as_json: bool = False, out=print) -> int:
    """``veles-tpu metrics aggregate --watch SEC`` driver: a scrape +
    merge + :func:`ingest_aggregate` loop over a client-side
    :class:`~veles_tpu.telemetry.timeseries.SeriesStore`
    (``count_samples=False``), one summary line per interval —
    windowed rates and quantiles computed EXACTLY like a replica's
    own watchtower computes them. Exit 0 while the last interval saw
    at least one endpoint up; 2 otherwise."""
    import time as _time
    from .timeseries import SeriesStore
    store = SeriesStore(period=period,
                        retention=max(600.0, period * 600),
                        count_samples=False)
    n = 0
    last_up = 0
    try:
        while True:
            agg = aggregate(urls, timeout=timeout)
            ingest_aggregate(store, agg)
            last_up = sum(1 for ep in agg["endpoints"] if ep["up"])
            rep = interval_report(store, window=period * 1.5)
            if as_json:
                out(json.dumps(rep, sort_keys=True))
            else:
                out(format_interval(rep))
            n += 1
            if iterations and n >= iterations:
                break
            _time.sleep(period)
    except KeyboardInterrupt:
        pass
    return 0 if last_up else 2
