"""Telemetry: deterministic performance accounting for every run.

The reference shipped live observability as a first-class layer (ZeroMQ
graphics server + tornado web status, veles/graphics_server.py:73 +
veles/web_status.py:113); this build has the endpoints but, until this
subsystem, no *deterministic* accounting behind them — every perf gate
keyed off wall-clock medians that the shared TPU relay swings up to
7.6× between measurement windows (docs/perf.md "Relay weather"), and
MFU claims were hand-derived in docs rather than measured by the
framework. This package closes that gap with four pieces, none of which
depend on wall-clock:

- :mod:`counters` — process-global, thread-safe counter registry
  (dispatches, compiles, cache hits, bytes moved) with a
  Prometheus-style text rendering served at ``/metrics`` by
  ``web_status.py`` and ``restful_api.py``;
- :mod:`spans` — context-manager/decorator span API wired into
  ``Unit.run`` dispatch and the fused train step, recording nesting
  and counter deltas (device dispatches, transfer bytes) per span,
  emitted as JSONL;
- :mod:`cost` — a :class:`~veles_tpu.telemetry.cost.CostModel`
  extracting FLOPs / bytes-accessed / peak-memory from lowered XLA
  computations (``jax.stages.Compiled.cost_analysis()``) with an
  analytic fallback table for the Pallas kernels (which report
  nothing), so measured MFU comes from the framework, not from docs;
- :mod:`chrome_trace` — span-JSONL → Chrome ``trace_event`` export
  (``veles-tpu trace export run.jsonl trace.json``) for Perfetto.

Counter-based perf gates live in :func:`gate_counters`: bench.py
records ``{flops, bytes, dispatches, compiles}`` alongside wall-clock
and the gate fails on counter regressions (extra dispatches per token,
unexpected recompiles) — meaningful CI even when the relay is noisy.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .counters import (counters, describe_counter, inc,          # noqa: F401
                       prometheus_text, snapshot)
# the flight-recorder MODULE must import before the span-recorder
# INSTANCE below: loading a submodule binds the package attribute
# ``recorder`` to the module; the next line deliberately rebinds it to
# the SpanRecorder instance (the long-standing export). Import the
# flight recorder by full path: veles_tpu.telemetry.recorder
from .recorder import FlightRecorder, flight                      # noqa: F401
from .spans import span, spanned, SpanRecorder, recorder          # noqa: F401
from .cost import Cost, CostModel, peak_bf16_flops                # noqa: F401
from .tensormon import (ModelHealthError, TensorMonitor,          # noqa: F401
                        monitor)

#: every counter the model-health plane increments — registered with
#: HELP strings in counters.DESCRIPTIONS and asserted zero in
#: monitoring-off runs by ``python bench.py gate``'s tensormon section
TENSORMON_COUNTERS = (
    "veles_tensormon_samples_total",
    "veles_model_nan_total",
    "veles_model_health_errors_total",
    "veles_blackbox_dumps_total",
)

#: every counter the watchtower plane increments (SeriesStore
#: samples, /metrics/history pulls, alert-rule sweeps/transitions,
#: critical-unready hooks) — registered with HELP strings in
#: counters.DESCRIPTIONS and asserted zero in watch-off runs by
#: ``python bench.py gate``'s watch section
WATCH_COUNTERS = (
    "veles_watch_samples_total",
    "veles_watch_pulls_total",
    "veles_alert_evals_total",
    "veles_alert_transitions_total",
    "veles_alert_critical_unready_total",
)

#: every counter the fleet-tracing plane increments (span-ring pulls,
#: trace-file rotations, cross-process merges) — registered with HELP
#: strings in counters.DESCRIPTIONS and asserted zero in non-fleet
#: runs by ``python bench.py gate``'s tracing section
TRACE_COUNTERS = (
    "veles_trace_rotations_total",
    "veles_trace_span_pulls_total",
    "veles_trace_fleet_merges_total",
)

#: default gate rules: counter key → max allowed current/baseline
#: ratio; 1.0 means "may not grow at all". Only WINDOW-INDEPENDENT
#: quantities are gated: bench windows are time-boxed, so raw deltas
#: (total dispatches, total flops) scale with how many epochs fit the
#: window — exactly the relay-weather noise this gate exists to
#: escape. Per-epoch / per-dispatch rates and steady-state compile
#: counts are invariants of the program, not of the wall clock.
GATE_RULES = {
    "dispatches_per_epoch": 1.0,
    "compiles": 1.0,
    "flops_per_dispatch": 1.05,
    "bytes_per_dispatch": 1.05,
    # baseline-relative: a decode that degenerates from one program
    # per generate (1/n_new per token) to one per token shows as an
    # n_new× ratio here — the absolute <= 1 ceiling alone would pass
    # the batch=1 degenerate case at exactly 1.0
    "dispatches_per_token": 1.0,
}


def gate_counters(current: Dict[str, Any],
                  baseline: Dict[str, Any],
                  rules: Optional[Dict[str, float]] = None,
                  max_dispatches_per_token: Optional[float] = None,
                  ) -> List[str]:
    """Compare a benchmark's counter record against a baseline record;
    return a list of human-readable failure strings (empty = pass).

    Unlike the wall-clock gates, these comparisons are exact: a decode
    that suddenly dispatches twice per token, or a step that recompiles
    where it used to hit the jit cache, fails deterministically no
    matter what the relay weather does to the timings. The default
    rules gate only normalized quantities (see GATE_RULES) — raw
    window totals scale with wall clock and are recorded for
    information, not gated.

    ``max_dispatches_per_token`` additionally enforces an absolute
    ceiling on ``current["dispatches_per_token"]`` (the round-5
    speculative finding was ultimately this number) independent of any
    baseline.
    """
    failures: List[str] = []
    for key, max_ratio in (rules or GATE_RULES).items():
        cur, base = current.get(key), baseline.get(key)
        if cur is None or base is None:
            continue
        if base == 0:
            if cur > 0:
                failures.append("%s regressed: 0 -> %s" % (key, cur))
            continue
        ratio = float(cur) / float(base)
        if ratio > max_ratio + 1e-9:
            failures.append(
                "%s regressed: %s -> %s (%.3fx > %.2fx allowed)"
                % (key, base, cur, ratio, max_ratio))
    if max_dispatches_per_token is not None:
        dpt = current.get("dispatches_per_token")
        if dpt is not None and float(dpt) > max_dispatches_per_token:
            failures.append(
                "dispatches_per_token %.3f exceeds ceiling %.3f"
                % (float(dpt), max_dispatches_per_token))
    return failures
