"""Watchtower time-series: a fixed-ring sampler over the registries.

Everything the telemetry plane exposed before this module is a
*snapshot*: ``/metrics`` renders the counter/histogram registries at
scrape time, loadgen folds its verdict at end-of-run, the flight
recorder speaks at crash time. The watchtower adds the time dimension
— a :class:`SeriesStore` samples the counter registry, the histogram
registry and a set of registered gauge providers on a fixed period
into a bounded ring, and derives the *operational* signals from
sample-to-sample deltas:

- :meth:`SeriesStore.rate` / :meth:`SeriesStore.delta` — counter
  growth over a trailing window (qps, tokens/sec, shed/sec);
- :meth:`SeriesStore.quantile` — **windowed** histogram quantiles
  from bucket deltas between two samples. The ``_p50/_p90/_p99``
  gauges on ``/metrics`` are cumulative-since-start (they go stale on
  long runs: an hour of good traffic buries a five-minute brownout);
  the windowed estimate sees only the window.

The ring is cursor-pullable over ``GET /metrics/history?since=N`` on
every request-plane HTTP surface (router, GenerationAPI, RESTfulAPI,
web status) — the ``/trace/spans?since=`` pattern: a JSONL body
(header line + one record per line) so a torn read salvages per line.
Alert transitions (telemetry/alerts.py) ride the same ring as
``watch.alert`` records, so history pulls see firing/resolved edges
in order with the samples that caused them.

Default **OFF** and bit-identical off (the tensormon discipline,
locked by tests/test_watchtower.py): with
``root.common.telemetry.watch.enabled`` false no sampler thread
starts, no ``veles_watch_*``/``veles_alert_*`` counter ever moves and
the serving plane runs the exact pre-watchtower path. Knobs::

    root.common.telemetry.watch.enabled     # False
    root.common.telemetry.watch.period      # 1.0 s between samples
    root.common.telemetry.watch.retention   # 300.0 s of ring history

The store is also the client-side engine behind ``veles-tpu watch``
and ``veles-tpu metrics aggregate --watch N``: :meth:`ingest` accepts
parsed ``/metrics`` scrapes from *another* process, so the CLI
computes the same windowed rates/quantiles from remote registries
that a replica computes locally (``count_samples=False`` keeps a
client-side store from moving this process's watch counters).
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import (Any, Callable, Deque, Dict, List, Optional,
                    Tuple)

from .counters import counters, histogram_quantile, histograms

#: every gauge provider registered for the process-global sampler:
#: name -> callable returning {gauge_name: value | (value, help)}.
#: Registration is always safe (a dict put) — providers only run
#: while the watch sampler is on, so the feature-off path never
#: calls them.
_gauge_providers: Dict[str, Callable[[], Dict[str, Any]]] = {}


def add_gauge_provider(name: str,
                       fn: Callable[[], Dict[str, Any]]) -> None:
    _gauge_providers[name] = fn


def remove_gauge_provider(name: str) -> None:
    _gauge_providers.pop(name, None)


def watch_config() -> Dict[str, Any]:
    """The watch knob block (missing config → shipped defaults)."""
    try:
        from ..config import root
        node = root.common.telemetry.watch
        return {
            "enabled": bool(node.get("enabled", False)),
            "period": float(node.get("period", 1.0) or 1.0),
            "retention": float(node.get("retention", 300.0) or 300.0),
        }
    except Exception:        # noqa: BLE001 — config not importable
        return {"enabled": False, "period": 1.0, "retention": 300.0}


def enabled() -> bool:
    return watch_config()["enabled"]


class SeriesStore:
    """Fixed-ring metric time-series with windowed derivations.

    Capacity is ``retention / period`` samples (+1 so a full
    retention window always has both endpoints buffered). Every
    record carries a process-monotonic ``seq`` — the
    ``/metrics/history`` pull cursor, exactly the span-ring
    contract: a cursor older than the ring's tail silently skips
    evicted records. ``clock`` is injectable so tests drive ring
    wrap/window math deterministically."""

    def __init__(self, period: float = 1.0, retention: float = 300.0,
                 clock: Callable[[], float] = time.time,
                 count_samples: bool = True) -> None:
        self.period = max(1e-3, float(period))
        self.retention = max(self.period, float(retention))
        self.clock = clock
        self._count_samples = count_samples
        capacity = max(2, int(round(self.retention / self.period)) + 1)
        self._lock = threading.Lock()
        self._ring: Deque[Dict[str, Any]] = collections.deque(
            maxlen=capacity)
        self._seq = 0

    # -- append paths --------------------------------------------------------
    def sample(self) -> Dict[str, Any]:
        """One sample of THIS process's registries + gauge providers
        (the sampler-thread tick). Counted
        ``veles_watch_samples_total``."""
        gauges: Dict[str, float] = {}
        for provider in list(_gauge_providers.values()):
            try:
                for name, val in (provider() or {}).items():
                    if isinstance(val, tuple):
                        val = val[0]
                    try:
                        gauges[name] = float(val)
                    except (TypeError, ValueError):
                        continue
            except Exception:    # noqa: BLE001 — observers only
                continue
        return self.ingest(counters.snapshot(), histograms.snapshot(),
                           gauges)

    def ingest(self, counter_values: Dict[str, float],
               hist_snap: Dict[str, Dict[str, Any]],
               gauges: Dict[str, float],
               ts: Optional[float] = None) -> Dict[str, Any]:
        """Append one sample — local registries or a parsed remote
        ``/metrics`` scrape (the ``veles-tpu watch`` client path)."""
        rec = {
            "kind": "watch.sample",
            "ts": float(self.clock() if ts is None else ts),
            "counters": dict(counter_values),
            "hist": {name: {"bounds": list(h["bounds"]),
                            "counts": list(h["counts"]),
                            "sum": h["sum"], "count": h["count"]}
                     for name, h in hist_snap.items()},
            "gauges": dict(gauges),
        }
        self._append(rec)
        if self._count_samples:
            counters.inc("veles_watch_samples_total")
        return rec

    def note_event(self, kind: str, **data: Any) -> Dict[str, Any]:
        """Append a non-sample record (alert transitions) into the
        same ring, so cursor pulls see edges in order with the
        samples that caused them."""
        rec = dict(data, kind=kind, ts=float(self.clock()))
        self._append(rec)
        return rec

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)

    # -- reads ---------------------------------------------------------------
    def records(self, kind: Optional[str] = None
                ) -> List[Dict[str, Any]]:
        with self._lock:
            recs = list(self._ring)
        if kind is not None:
            recs = [r for r in recs if r.get("kind") == kind]
        return recs

    def cursor(self) -> int:
        with self._lock:
            return self._seq

    def records_since(self, cursor: int
                      ) -> Tuple[List[Dict[str, Any]], int]:
        """(records appended after ``cursor``, the new cursor) —
        the span-ring pull contract."""
        cursor = int(cursor)
        out: List[Dict[str, Any]] = []
        with self._lock:
            for rec in reversed(self._ring):
                if int(rec.get("seq", 0)) <= cursor:
                    break
                out.append(rec)
            nxt = self._seq
        out.reverse()
        return out, nxt

    def samples(self) -> List[Dict[str, Any]]:
        return self.records("watch.sample")

    def _window_pair(self, window: Optional[float]
                     ) -> Optional[Tuple[Dict[str, Any],
                                         Dict[str, Any]]]:
        """(older, newest) samples spanning ~``window`` seconds:
        the newest sample, and the newest sample at least ``window``
        older (the whole ring when the window outruns retention).
        None until two samples exist."""
        recs = self.samples()
        if len(recs) < 2:
            return None
        newest = recs[-1]
        if window is None:
            return recs[-2], newest
        target = newest["ts"] - float(window)
        older = recs[0]
        for rec in recs[:-1]:
            if rec["ts"] <= target:
                older = rec
            else:
                break
        return older, newest

    def delta(self, name: str, window: Optional[float] = None
              ) -> Optional[float]:
        """Counter growth over the trailing window (None until two
        samples exist). Negative deltas (a restarted remote process)
        clamp to the newest absolute value — a restart is growth from
        zero, not negative traffic."""
        pair = self._window_pair(window)
        if pair is None:
            return None
        older, newest = pair
        d = newest["counters"].get(name, 0.0) \
            - older["counters"].get(name, 0.0)
        if d < 0:
            d = newest["counters"].get(name, 0.0)
        return d

    def rate(self, name: str, window: Optional[float] = None
             ) -> Optional[float]:
        """Per-second counter rate over the trailing window."""
        pair = self._window_pair(window)
        if pair is None:
            return None
        older, newest = pair
        dt = newest["ts"] - older["ts"]
        if dt <= 0:
            return None
        d = self.delta(name, window)
        return None if d is None else d / dt

    def gauge(self, name: str) -> Optional[float]:
        recs = self.samples()
        if not recs:
            return None
        return recs[-1]["gauges"].get(name)

    def hist_delta(self, name: str, window: Optional[float] = None
                   ) -> Optional[Dict[str, Any]]:
        """{bounds, counts, count} of the bucket DELTAS between the
        window's endpoint samples — the windowed-quantile numerator.
        A histogram absent from the older sample (it appeared
        mid-window) deltas against zeros; a bounds mismatch (remote
        restart with different registration) falls back to the
        newest absolute counts."""
        pair = self._window_pair(window)
        if pair is None:
            return None
        older, newest = pair
        new_h = newest["hist"].get(name)
        if new_h is None:
            return None
        old_h = older["hist"].get(name)
        bounds = list(new_h["bounds"])
        counts = list(new_h["counts"])
        if old_h is not None \
                and list(old_h["bounds"]) == bounds \
                and len(old_h["counts"]) == len(counts):
            counts = [max(0, int(c) - int(o))
                      for c, o in zip(counts, old_h["counts"])]
        return {"bounds": bounds, "counts": counts,
                "count": sum(counts)}

    def quantile(self, name: str, q: float,
                 window: Optional[float] = None) -> Optional[float]:
        """WINDOWED histogram quantile: bucket deltas between the
        window's endpoint samples fed to the shared
        :func:`histogram_quantile` interpolation — the operational
        twin of the cumulative-since-start ``_p99`` gauges. None
        when the window saw no samples."""
        h = self.hist_delta(name, window)
        if h is None or not h["count"]:
            return None
        return histogram_quantile(tuple(h["bounds"]),
                                  tuple(h["counts"]), q)

    def error_fraction(self, name: str, slo_seconds: float,
                       window: Optional[float] = None
                       ) -> Optional[float]:
        """Fraction of the window's observations ABOVE the SLO
        target — the burn-rate numerator (telemetry/alerts.py).
        Bucket-resolution: observations are 'good' when their whole
        bucket's upper bound is <= the target, so a target between
        bounds errs toward alerting. None when the window saw no
        samples."""
        h = self.hist_delta(name, window)
        if h is None or not h["count"]:
            return None
        good = sum(cnt for bound, cnt in zip(h["bounds"], h["counts"])
                   if float(bound) <= float(slo_seconds))
        return max(0.0, (h["count"] - good) / float(h["count"]))


# -- the process-global sampler ----------------------------------------------

_lock = threading.Lock()
_store: Optional[SeriesStore] = None
_engine = None                       # telemetry.alerts.AlertEngine
_thread: Optional[threading.Thread] = None
_stop = threading.Event()


def store() -> Optional[SeriesStore]:
    """The live process-global store, or None while the watchtower
    is off."""
    return _store


def alert_engine():
    return _engine


def maybe_start() -> Optional[SeriesStore]:
    """Start the process-global sampler thread once, iff
    ``root.common.telemetry.watch.enabled`` — called by every HTTP
    surface at its own start, so enabling the knob before ANY
    service brings the watchtower up with it. Feature-off this is a
    config read and nothing else (the bit-identical-off contract)."""
    global _store, _engine, _thread
    cfg = watch_config()
    if not cfg["enabled"]:
        return None
    with _lock:
        if _store is None:
            _store = SeriesStore(period=cfg["period"],
                                 retention=cfg["retention"])
            from . import alerts
            _engine = alerts.AlertEngine(_store,
                                         alerts.rules_from_config())
        if _thread is None or not _thread.is_alive():
            _stop.clear()
            _thread = threading.Thread(target=_sampler_loop,
                                       daemon=True,
                                       name="veles.watch")
            _thread.start()
    return _store


def stop_watch() -> None:
    """Stop the sampler and drop the store — tests and process
    teardown only."""
    global _store, _engine, _thread
    _stop.set()
    thread = _thread
    if thread is not None:
        thread.join(timeout=5)
    with _lock:
        _store = None
        _engine = None
        _thread = None


def _sampler_loop() -> None:
    while not _stop.is_set():
        store_, engine = _store, _engine
        if store_ is None:
            return
        try:
            store_.sample()
            if engine is not None:
                engine.evaluate()
        except Exception:        # noqa: BLE001 — observability only
            pass
        # period re-read each tick: the knob stays live, and a
        # stop() mid-sleep returns promptly
        if _stop.wait(watch_config()["period"]):
            return


def pull_payload(since: int = 0, name: str = "") -> str:
    """The ``GET /metrics/history?since=CURSOR`` response body: one
    JSONL header line (enabled flag, new cursor, period, the current
    alert states) + one line per ring record appended after
    ``since``. Disabled → the header alone, with ``enabled: false``
    and NO counter movement (the off path stays frozen). Counted
    ``veles_watch_pulls_total`` when live."""
    import os
    store_, engine = _store, _engine
    header: Dict[str, Any] = {"kind": "watch.header",
                              "pid": os.getpid(),
                              "name": str(name or ""),
                              "enabled": store_ is not None}
    if store_ is None:
        header.update(cursor=0, records=0)
        return json.dumps(header) + "\n"
    recs, cursor = store_.records_since(since)
    header.update(cursor=cursor, records=len(recs),
                  wall=time.time(), period=store_.period,
                  retention=store_.retention,
                  alerts=engine.status() if engine is not None else [])
    counters.inc("veles_watch_pulls_total")
    return "\n".join(json.dumps(r, default=str)
                     for r in [header] + recs) + "\n"


def alerts_payload() -> Dict[str, Any]:
    """The ``GET /alerts`` JSON body: rule states when the
    watchtower is live, ``enabled: false`` otherwise (no counter
    movement either way — listing rules is a read)."""
    engine = _engine
    if engine is None:
        return {"enabled": False, "rules": []}
    return {"enabled": True, "rules": engine.status(),
            "firing": engine.firing()}


def parse_history(text: str) -> Tuple[Optional[Dict[str, Any]],
                                      List[Dict[str, Any]]]:
    """Parse a ``/metrics/history`` JSONL body → (header, records).
    Torn lines (a response truncated mid-record) are skipped — the
    salvage-per-line contract the JSONL framing exists for."""
    header = None
    records: List[Dict[str, Any]] = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if not isinstance(rec, dict):
            continue
        if rec.get("kind") == "watch.header":
            header = rec
        else:
            records.append(rec)
    return header, records
