"""Device self-time: the measurement plane behind the perf gates.

Every perf claim before this module keyed off wall-clock medians that
the shared TPU relay swings up to 7.6× between measurement windows
(docs/perf.md "Relay weather"). Device *self-time* — the seconds the
compute stream actually spent executing programs — is immune to relay
weather, host scheduling and queue depth, so ``bench.py`` stamps it
per section and ``bench.py gate`` compares IT, with wall-clock only as
a counted legacy fallback. Two sources, in preference order:

1. **Profiler capture** (``jax.profiler.start_trace``/``stop_trace``):
   the profiler writes a Chrome trace-event stream
   (``plugins/profile/<run>/<host>.trace.json.gz``) whose *processes*
   include one per device (``/device:TPU:0`` …) with per-stream
   threads ("XLA Ops"). :func:`device_self_time` interval-unions those
   device-stream events — nested/overlapping events never double
   count — and :func:`attribute_spans` maps the device intervals onto
   the telemetry span records (:mod:`~veles_tpu.telemetry.spans`) by
   time overlap, so the operator view (``veles-tpu trace self-time``)
   and the gate read the same numbers.
2. **Host-sync fallback**: on backends where the capture yields no
   device streams (the CPU CI backend traces only ``/host:CPU``), or
   where the profiler is unavailable, the fallback times the caller's
   ``lax``-loop harness (the fused epoch/decode programs — one
   dispatch each) bracketed by the scalar-fetch sync that
   ``bench.py host_sync`` uses, because ``jax.block_until_ready`` is a
   no-op through the tunnelled-TPU transport. Sync-to-sync wall time
   of a single-dispatch program is device time plus one host round
   trip — an upper bound, stamped ``source="host_sync"`` and counted
   (``veles_devtime_fallbacks_total``) so a gate reading fallback
   numbers knows it.

The comparison arithmetic (:func:`compare_sections`) lives here too so
the gate's tolerance math is a pure, testable function: device-time
medians may grow ``DEVTIME_TOLERANCE`` (noise), legacy wall-clock
sections (pre-devtime ``BENCH_*.json``) are compared at
``LEGACY_TOLERANCE`` (the documented relay swing) with a counted
``veles_bench_legacy_sections_total`` warning instead of a crash.
"""

from __future__ import annotations

import gzip
import json
import logging
import os
import shutil
import tempfile
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .counters import inc

log = logging.getLogger("veles_tpu.telemetry")

#: the measurement plane's counters — registered with HELP strings in
#: counters.DESCRIPTIONS; capture/fallback counts surface on both
#: /metrics surfaces through the shared registry renderer
DEVTIME_COUNTERS = (
    "veles_devtime_captures_total",
    "veles_devtime_fallbacks_total",
    "veles_bench_legacy_sections_total",
)

#: max allowed growth of device_time_per_epoch between two bench
#: documents — the stated noise tolerance of the device-time gate.
#: Device self-time is relay-immune but not jitter-free (compiler
#: autotuning, HBM refresh alignment); measured drift on repeated
#: chip sections sits well under 10 %, so 25 % headroom never flaps
#: while a real regression (a lost fusion, an extra pass) is a ≥2×
#: move.
DEVTIME_TOLERANCE = 1.25

#: wall-clock fallback tolerance for LEGACY sections (documents
#: stamped before the device-time format): the relay swings wall
#: clock up to 7.6× between windows (docs/perf.md), so anything
#: tighter would flap — this bound only catches collapse, and every
#: legacy comparison is counted so the format migration is visible.
LEGACY_TOLERANCE = 8.0


# -- trace-event stream parsing ---------------------------------------------

def load_trace_events(path: str) -> List[Dict[str, Any]]:
    """Load a Chrome trace-event file (``.json`` or ``.json.gz``;
    either a ``{"traceEvents": [...]}`` document or a bare event
    list). A torn/truncated file — a capture killed mid-write — is
    salvaged event by event with ONE counted warning instead of
    raising, mirroring ``spans.read_jsonl``'s hardening: a partial
    trace must still summarize."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        raw = f.read().decode("utf-8", errors="replace")
    try:
        doc = json.loads(raw)
    except ValueError:
        return _salvage_events(raw, path)
    if isinstance(doc, list):
        return [e for e in doc if isinstance(e, dict)]
    if isinstance(doc, dict):
        evs = doc.get("traceEvents", [])
        return [e for e in evs if isinstance(e, dict)]
    raise ValueError("not a trace-event document: %s" % path)


def _salvage_events(raw: str, path: str) -> List[Dict[str, Any]]:
    """Recover the complete event prefix of a truncated trace: scan
    the ``traceEvents`` array (or a bare list) object by object with
    an incremental decoder; stop at the first undecodable tail."""
    start = raw.find("[", max(0, raw.find('"traceEvents"')))
    if start < 0:
        raise ValueError("no traceEvents array found in %s" % path)
    decoder = json.JSONDecoder()
    out: List[Dict[str, Any]] = []
    i = start + 1
    n = len(raw)
    while i < n:
        while i < n and raw[i] in " \t\r\n,":
            i += 1
        if i >= n or raw[i] == "]":
            break
        try:
            obj, end = decoder.raw_decode(raw, i)
        except ValueError:
            break
        if isinstance(obj, dict):
            out.append(obj)
        i = end
    log.warning(
        "salvaged %d complete trace event(s) from torn trace %s "
        "(mid-write truncated tail skipped)", len(out), path)
    return out


def load_profile_dir(logdir: str) -> List[Dict[str, Any]]:
    """Events of the newest trace under a ``jax.profiler`` log
    directory (``plugins/profile/<run>/*.trace.json[.gz]``)."""
    import glob as _glob
    pats = [os.path.join(logdir, "plugins", "profile", "*",
                         "*.trace.json.gz"),
            os.path.join(logdir, "plugins", "profile", "*",
                         "*.trace.json")]
    paths = [p for pat in pats for p in _glob.glob(pat)]
    if not paths:
        raise ValueError("no *.trace.json[.gz] under %s" % logdir)
    return load_trace_events(max(paths, key=os.path.getmtime))


def _metadata(events: Iterable[Dict[str, Any]]
              ) -> Tuple[Dict[Any, str], Dict[Tuple[Any, Any], str]]:
    """(process names by pid, thread names by (pid, tid)) from the
    ``ph == "M"`` metadata events (which may trail the data events)."""
    procs: Dict[Any, str] = {}
    threads: Dict[Tuple[Any, Any], str] = {}
    for ev in events:
        if ev.get("ph") != "M":
            continue
        name = ev.get("name")
        args = ev.get("args") or {}
        if name == "process_name":
            procs[ev.get("pid")] = str(args.get("name", ""))
        elif name == "thread_name":
            threads[(ev.get("pid"), ev.get("tid"))] = \
                str(args.get("name", ""))
    return procs, threads


def _is_device_process(name: str) -> bool:
    """XLA's trace names one process per accelerator
    (``/device:TPU:0``, ``/device:GPU:0 …``); the host shows as
    ``/host:CPU`` plus python/runtime processes. Only the former are
    compute streams."""
    n = name.lower()
    return "/device:" in n and "cpu" not in n


def _interval_union_us(intervals: List[Tuple[float, float]]) -> float:
    """Total covered microseconds of possibly nested/overlapping
    ``(start, end)`` intervals — THE self-time primitive: an op event
    nested inside a fusion event (or two overlapping sub-streams of
    one stream) must count its covered time once, not twice."""
    total = 0.0
    end_prev = None
    start_prev = None
    for start, end in sorted(intervals):
        if end_prev is None or start > end_prev:
            if end_prev is not None:
                total += end_prev - start_prev
            start_prev, end_prev = start, end
        elif end > end_prev:
            end_prev = end
    if end_prev is not None:
        total += end_prev - start_prev
    return total


def device_events(events: Iterable[Dict[str, Any]]
                  ) -> List[Dict[str, Any]]:
    """The complete (``ph == "X"``) events that ran on device-stream
    threads. Within a device process, when any thread is named
    "XLA Ops" only those threads count — the other lanes ("XLA
    Modules", "Steps") are ENVELOPES around the same ops and would
    double the self-time."""
    events = list(events)
    procs, threads = _metadata(events)
    dev_pids = {pid for pid, name in procs.items()
                if _is_device_process(name)}
    ops_tids = {key for key, name in threads.items()
                if key[0] in dev_pids and "xla ops" in name.lower()}
    ops_pids = {pid for pid, _tid in ops_tids}
    out = []
    for ev in events:
        if ev.get("ph") != "X" or ev.get("pid") not in dev_pids:
            continue
        if ev.get("pid") in ops_pids \
                and (ev.get("pid"), ev.get("tid")) not in ops_tids:
            continue
        out.append(ev)
    return out


def device_self_time(events: Iterable[Dict[str, Any]]
                     ) -> Dict[str, Any]:
    """Per-stream and total device self-time of a trace-event stream:
    ``{"device_time_s", "by_stream": {label: seconds}, "n_events"}``.
    Streams are (device process, thread) pairs; each stream's
    self-time is the interval union of its events, so nesting inside
    one stream never double counts (concurrent streams DO sum — two
    busy cores are two cores' worth of self-time)."""
    events = list(events)
    procs, threads = _metadata(events)
    per: Dict[Tuple[Any, Any], List[Tuple[float, float]]] = {}
    n = 0
    for ev in device_events(events):
        ts = float(ev.get("ts", 0.0))
        dur = float(ev.get("dur", 0.0))
        per.setdefault((ev.get("pid"), ev.get("tid")), []).append(
            (ts, ts + dur))
        n += 1
    by_stream = {}
    total = 0.0
    for (pid, tid), ivals in sorted(per.items(), key=lambda kv: str(kv[0])):
        us = _interval_union_us(ivals)
        label = "%s/%s" % (procs.get(pid, "pid%s" % pid),
                           threads.get((pid, tid), "tid%s" % tid))
        by_stream[label] = by_stream.get(label, 0.0) + us / 1e6
        total += us
    return {"device_time_s": total / 1e6, "by_stream": by_stream,
            "n_events": n}


def attribute_spans(events: Iterable[Dict[str, Any]],
                    span_records: Iterable[Dict[str, Any]],
                    offset_us: Optional[float] = None
                    ) -> Dict[str, Dict[str, float]]:
    """Device self-time per telemetry span NAME: for every span record
    (``{"name", "ts" (epoch s), "dur" (s)}`` — the
    :mod:`~veles_tpu.telemetry.spans` schema), the interval union of
    device-stream events overlapping the span's window, clipped to it.

    The two clocks differ: spans carry host epoch seconds, profiler
    events carry trace-clock microseconds. ``offset_us`` is
    ``device_ts − host_ts·1e6`` for one common instant; when None it
    is estimated by aligning the earliest device event to the
    earliest span start — exact enough when the capture brackets the
    spans (how :func:`measure` uses it), stated here because it IS an
    approximation. Same-name spans aggregate; a parent span's window
    includes its children's (self-time here is *device* self-time per
    span window, not host-tree-exclusive time)."""
    span_records = [r for r in span_records
                    if "name" in r and "ts" in r]
    devs = [(float(e.get("ts", 0.0)),
             float(e.get("ts", 0.0)) + float(e.get("dur", 0.0)))
            for e in device_events(events)]
    if offset_us is None:
        if not devs or not span_records:
            return {}
        offset_us = (min(s for s, _ in devs)
                     - min(float(r["ts"]) for r in span_records) * 1e6)
    out: Dict[str, Dict[str, float]] = {}
    for rec in span_records:
        s0 = float(rec["ts"]) * 1e6 + offset_us
        s1 = s0 + float(rec.get("dur", 0.0)) * 1e6
        clipped = [(max(a, s0), min(b, s1)) for a, b in devs
                   if b > s0 and a < s1]
        row = out.setdefault(rec["name"],
                             {"device_time_s": 0.0, "spans": 0,
                              "events": 0})
        row["device_time_s"] += _interval_union_us(clipped) / 1e6
        row["spans"] += 1
        row["events"] += len(clipped)
    return out


# -- capture ------------------------------------------------------------------

#: process-wide profiler state: "auto" probes once and remembers — a
#: backend whose captures carry no device streams (CPU CI) or whose
#: profiler errors must not pay capture overhead on every window.
_prof_state = {"disabled": False, "reason": None}


def _profiler_mode() -> str:
    """``root.common.telemetry.devtime.profiler``: "auto" (default —
    try once, remember failure), "on" (always try), "off"."""
    try:
        from ..config import root
        mode = root.common.telemetry.devtime.get("profiler", "auto")
        return str(mode) if mode else "auto"
    except Exception:            # noqa: BLE001 — config not importable
        return "auto"


def _disable_profiler(reason: str) -> None:
    if not _prof_state["disabled"]:
        _prof_state.update(disabled=True, reason=reason)
        log.info("devtime: profiler capture disabled for this process "
                 "(%s) — falling back to host-sync timing", reason)


def profiler_usable() -> bool:
    mode = _profiler_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return not _prof_state["disabled"]


def measure(fn: Callable[[], Any], sync: Callable[[], Any],
            calls: int = 1,
            span_records: Optional[List[Dict[str, Any]]] = None
            ) -> Dict[str, Any]:
    """ONE device-time measurement: run ``fn`` ``calls`` times between
    scalar-fetch syncs. Returns::

        {"device_time_s", "wall_time_s", "calls",
         "device_time_per_call", "source": "profiler" | "host_sync"
         [, "by_stream"] [, "spans"]}

    Profiler path (when usable): the run is captured with
    ``jax.profiler``, the trace-event stream parsed for device-stream
    self-time (``veles_devtime_captures_total``) and attributed onto
    the telemetry spans that closed inside the window
    (``span_records``; default: the global span recorder's records
    from the capture window) under ``out["spans"]``. A capture with no
    device streams disables the profiler for the process and falls
    back. Fallback: the synced wall time IS the device-time estimate
    (upper bound by one host round trip per call —
    ``fn`` is expected to be a ``lax``-loop harness dispatching one
    fused program per call), counted
    ``veles_devtime_fallbacks_total``."""
    sync()
    t0_epoch = time.time()
    started = False
    tmpdir = None
    if profiler_usable():
        import jax
        tmpdir = tempfile.mkdtemp(prefix="veles_devtime_")
        try:
            jax.profiler.start_trace(tmpdir)
            started = True
        except Exception as e:           # noqa: BLE001 — any profiler
            _disable_profiler("start_trace failed: %s" % e)
            shutil.rmtree(tmpdir, ignore_errors=True)
            tmpdir = None
    t0 = time.time()
    try:
        for _ in range(max(1, int(calls))):
            fn()
        sync()
    finally:
        wall = time.time() - t0
        parsed = None
        if started:
            import jax
            try:
                jax.profiler.stop_trace()
                events = load_profile_dir(tmpdir)
                parsed = device_self_time(events)
            except Exception as e:       # noqa: BLE001
                _disable_profiler("capture parse failed: %s" % e)
                events = None
            if tmpdir:
                shutil.rmtree(tmpdir, ignore_errors=True)
    calls = max(1, int(calls))
    if parsed is not None and parsed["device_time_s"] > 0:
        inc("veles_devtime_captures_total")
        out = {"device_time_s": parsed["device_time_s"],
               "wall_time_s": wall, "calls": calls,
               "device_time_per_call": parsed["device_time_s"] / calls,
               "source": "profiler",
               "by_stream": parsed["by_stream"]}
        if span_records is None:
            # attribute onto the telemetry spans that closed inside
            # THIS window — the existing span names are the section
            # vocabulary the gate and `trace self-time` share
            from .spans import recorder as _span_recorder
            span_records = [r for r in _span_recorder.records()
                            if r.get("ts", 0) >= t0_epoch]
        if span_records:
            out["spans"] = attribute_spans(events, span_records)
        return out
    if started:
        _disable_profiler("capture carried no device-stream events "
                          "(host-only backend)")
    inc("veles_devtime_fallbacks_total")
    return {"device_time_s": wall, "wall_time_s": wall, "calls": calls,
            "device_time_per_call": wall / calls,
            "source": "host_sync"}


# -- gate arithmetic ----------------------------------------------------------

def section_invariants(name: str, sec: Dict[str, Any]) -> List[str]:
    """Harness invariants every devtime section record must satisfy —
    what the gate proves on CPU CI, where timing ratios are
    meaningless: fields present, positive device time, wall ≥ device
    (minus float slack), a known source."""
    failures = []
    for key in ("device_time_s", "wall_time_s", "source",
                "device_time_per_epoch"):
        if key not in sec:
            failures.append("%s: devtime record lacks %s" % (name, key))
    if failures:
        return failures
    if not sec["device_time_s"] > 0:
        failures.append("%s: device_time_s = %r (must be > 0)"
                        % (name, sec["device_time_s"]))
    if sec["wall_time_s"] < sec["device_time_s"] * 0.999:
        failures.append(
            "%s: wall_time_s %.6f < device_time_s %.6f — device "
            "self-time cannot exceed the synced wall window"
            % (name, sec["wall_time_s"], sec["device_time_s"]))
    if sec["source"] not in ("profiler", "host_sync"):
        failures.append("%s: unknown devtime source %r"
                        % (name, sec["source"]))
    return failures


def compare_sections(name: str, base: Optional[Dict[str, Any]],
                     cur: Optional[Dict[str, Any]],
                     base_rate: Optional[float] = None,
                     cur_rate: Optional[float] = None,
                     timing: bool = True,
                     tolerance: float = DEVTIME_TOLERANCE) -> List[str]:
    """The device-time gate for one section pair; returns failure
    strings (empty = pass).

    - both carry devtime records → harness invariants always; the
      ``device_time_per_epoch`` ratio may not exceed ``tolerance``
      when ``timing`` (False on CPU/smoke documents, where the gate
      proves invariants only);
    - the CURRENT doc lost the record while the baseline has it →
      fail (format regression);
    - a LEGACY side (pre-devtime ``BENCH_*.json``) → counted
      ``veles_bench_legacy_sections_total`` warning and a wall-clock
      rate comparison at :data:`LEGACY_TOLERANCE` (throughput may not
      collapse below baseline/tolerance), so old baselines neither
      crash the gate nor silently stop gating."""
    failures: List[str] = []
    if cur is not None:
        failures += section_invariants(name, cur)
    if base is None or cur is None:
        if base is not None and cur is None:
            failures.append(
                "%s: current document lost its devtime record while "
                "the baseline has one — the device-time format must "
                "not regress" % name)
            return failures
        # legacy pairing: count + wall-clock fallback
        inc("veles_bench_legacy_sections_total")
        log.warning(
            "devtime gate: section %s compared on wall-clock only "
            "(legacy document without device_time_s)", name)
        if base_rate and cur_rate is not None \
                and cur_rate < base_rate / tolerance_legacy():
            failures.append(
                "%s: legacy wall-clock rate collapsed %.1f -> %.1f "
                "(> %.1fx, beyond even relay weather)"
                % (name, base_rate, cur_rate, tolerance_legacy()))
        return failures
    if failures or not timing:
        return failures
    b = base.get("device_time_per_epoch")
    c = cur.get("device_time_per_epoch")
    if not b or c is None:
        return failures
    ratio = float(c) / float(b)
    if ratio > tolerance + 1e-9:
        failures.append(
            "%s: device_time_per_epoch regressed %.6fs -> %.6fs "
            "(%.3fx > %.2fx tolerance)" % (name, b, c, ratio, tolerance))
    return failures


def tolerance_legacy() -> float:
    return LEGACY_TOLERANCE
