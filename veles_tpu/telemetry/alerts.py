"""Watchtower alerting: threshold + SLO burn-rate rules over a
:class:`~veles_tpu.telemetry.timeseries.SeriesStore`.

The rule engine is the operator-facing half of the watchtower plane
(timeseries.py is the data half): the sampler thread calls
:meth:`AlertEngine.evaluate` after every sample, each rule derives
one observed value from the store's windowed rates/quantiles/bucket
deltas, and state transitions follow the brownout ladder's hysteresis
idiom — ``fire_for`` consecutive breached evaluations to go firing,
``resolve_for`` consecutive clean ones to resolve, so a flapping
signal cannot strobe the pager. Two rule kinds:

- :class:`ThresholdRule` — a bound on a service gauge
  (``veles_serving_queue_depth > 64``) or on a counter's windowed
  rate (``rate(veles_shed_requests_total) > 5/s``);
- :class:`BurnRateRule` — multi-window SLO error-budget burn. The
  SLO is "``objective`` of requests complete under ``slo_seconds``"
  (error budget = 1 - objective); the burn rate over a window is
  ``observed_error_fraction / error_budget`` (1.0 = exactly spending
  the budget). The rule breaches only when BOTH the fast and the
  slow window burn above ``factor`` — the standard fast+slow pair:
  the fast window gives minutes-scale detection, the slow window
  keeps a single bad scrape from paging.

Transitions are *observable everywhere the incident will be
debugged*: noted into the flight recorder (``blackbox inspect``
shows them), appended to the SeriesStore ring (``/metrics/history``
pulls see them in order with the samples), counted
(``veles_alert_transitions_total``) and rendered as
``veles_alert_firing{rule="..."}`` gauges on ``/metrics``. A
``critical`` rule firing additionally marks the process unready
(``health.mark_unready`` — the router's probe loop routes around it)
and dumps the flight-recorder black box; resolving marks it ready
again.

Rule validation is FAIL-CLOSED: a rule referencing a series name
that is not a registered counter (counters.DESCRIPTIONS), histogram
(counters.HISTOGRAMS) or known service gauge (KNOWN_GAUGES) refuses
at parse time with a ValueError — a typo'd rule that silently never
fires is worse than no rule. scripts/check_counters.py re-runs the
same validation over the shipped defaults in CI.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .counters import DESCRIPTIONS, HISTOGRAMS, counters

#: service gauges a ThresholdRule may reference — the names the
#: request-plane HTTP surfaces export on /metrics (restful_api.py,
#: serving/router.py) and the watch sampler's gauge providers feed
#: into SeriesStore samples. Gauges are not registry-backed, so this
#: tuple IS their registration for the fail-closed rule validation.
KNOWN_GAUGES = (
    "veles_serving_slots",
    "veles_serving_slots_busy",
    "veles_serving_queue_depth",
    "veles_serving_prefill_stall_seconds",
    "veles_router_replicas",
    "veles_router_replicas_ready",
    "veles_router_breakers_open",
    "veles_router_inflight",
    "veles_qos_admit_rate",
    "veles_qos_brownout_level",
    "veles_qos_retry_tokens",
    "veles_fleet_slots",
    "veles_fleet_slots_busy",
    "veles_fleet_queue_depth",
)

SEVERITIES = ("info", "warn", "critical")


def _validate_series(rule_name: str, series: str,
                     kinds: Sequence[str]) -> None:
    """FAIL-CLOSED series check: ``series`` must be registered as one
    of the allowed ``kinds`` ('counter', 'histogram', 'gauge')."""
    ok = (("counter" in kinds and series in DESCRIPTIONS)
          or ("histogram" in kinds and series in HISTOGRAMS)
          or ("gauge" in kinds and series in KNOWN_GAUGES))
    if not ok:
        raise ValueError(
            "alert rule %r references unregistered series %r (must "
            "be a registered %s — counters.DESCRIPTIONS / "
            "counters.HISTOGRAMS / alerts.KNOWN_GAUGES)"
            % (rule_name, series, "/".join(kinds)))


class Rule:
    """Shared rule state machine: hysteresis streaks + severity."""

    def __init__(self, name: str, severity: str = "warn",
                 fire_for: int = 2, resolve_for: int = 3) -> None:
        if severity not in SEVERITIES:
            raise ValueError("alert rule %r: unknown severity %r "
                             "(one of %s)"
                             % (name, severity, "/".join(SEVERITIES)))
        self.name = str(name)
        self.severity = severity
        self.fire_for = max(1, int(fire_for))
        self.resolve_for = max(1, int(resolve_for))
        self.state = "ok"
        self.value: Optional[float] = None
        self.since: Optional[float] = None
        self._streak = 0

    def observe(self, store) -> Optional[bool]:
        """One evaluation: returns the breach verdict (None = not
        enough data yet; streaks hold still)."""
        raise NotImplementedError

    def describe(self) -> Dict[str, Any]:
        raise NotImplementedError

    def step(self, store, now: float) -> Optional[str]:
        """Advance the hysteresis machine one evaluation; returns
        'firing' / 'resolved' on a transition, else None."""
        breached = self.observe(store)
        if breached is None:
            return None
        if self.state == "ok":
            self._streak = self._streak + 1 if breached else 0
            if self._streak >= self.fire_for:
                self.state, self.since, self._streak = "firing", now, 0
                return "firing"
        else:
            self._streak = self._streak + 1 if not breached else 0
            if self._streak >= self.resolve_for:
                self.state, self.since, self._streak = "ok", now, 0
                return "resolved"
        return None

    def status(self) -> Dict[str, Any]:
        out = {"rule": self.name, "severity": self.severity,
               "state": self.state,
               "value": None if self.value is None
               else round(float(self.value), 6),
               "since": self.since}
        out.update(self.describe())
        return out


class ThresholdRule(Rule):
    """``gauge(series) OP threshold`` or
    ``rate(series, window) OP threshold``. ``source`` picks the
    read: 'gauge' (latest sampled service gauge) or 'rate'
    (windowed per-second counter rate)."""

    def __init__(self, name: str, series: str, threshold: float,
                 op: str = ">", source: str = "gauge",
                 window: Optional[float] = None, **kwargs: Any
                 ) -> None:
        super().__init__(name, **kwargs)
        if op not in (">", "<", ">=", "<="):
            raise ValueError("alert rule %r: unknown op %r"
                             % (name, op))
        if source not in ("gauge", "rate"):
            raise ValueError("alert rule %r: unknown source %r "
                             "(gauge or rate)" % (name, source))
        _validate_series(name, series,
                         ("gauge",) if source == "gauge"
                         else ("counter",))
        self.series = series
        self.threshold = float(threshold)
        self.op = op
        self.source = source
        self.window = None if window is None else float(window)

    def observe(self, store) -> Optional[bool]:
        if self.source == "gauge":
            value = store.gauge(self.series)
        else:
            value = store.rate(self.series, self.window)
        if value is None:
            return None
        self.value = float(value)
        if self.op == ">":
            return self.value > self.threshold
        if self.op == "<":
            return self.value < self.threshold
        if self.op == ">=":
            return self.value >= self.threshold
        return self.value <= self.threshold

    def describe(self) -> Dict[str, Any]:
        return {"type": "threshold", "series": self.series,
                "op": self.op, "threshold": self.threshold,
                "source": self.source, "window": self.window}


class BurnRateRule(Rule):
    """Multi-window SLO error-budget burn on a latency histogram.

    ``objective`` of requests must complete under ``slo_seconds``;
    the windowed error fraction comes from SeriesStore bucket deltas
    (a request is an 'error' when its bucket's upper bound exceeds
    the target — bucket resolution errs toward alerting). Breaches
    when burn > ``factor`` in BOTH windows; ``value`` reports the
    fast-window burn."""

    def __init__(self, name: str, series: str, slo_seconds: float,
                 objective: float = 0.99, fast_window: float = 30.0,
                 slow_window: float = 120.0, factor: float = 6.0,
                 **kwargs: Any) -> None:
        super().__init__(name, **kwargs)
        _validate_series(name, series, ("histogram",))
        if not 0.0 < float(objective) < 1.0:
            raise ValueError("alert rule %r: objective %r must be in "
                             "(0, 1)" % (name, objective))
        if float(slow_window) < float(fast_window):
            raise ValueError("alert rule %r: slow_window %.3f < "
                             "fast_window %.3f"
                             % (name, slow_window, fast_window))
        self.series = series
        self.slo_seconds = float(slo_seconds)
        self.objective = float(objective)
        self.fast_window = float(fast_window)
        self.slow_window = float(slow_window)
        self.factor = float(factor)

    def burn(self, store, window: float) -> Optional[float]:
        frac = store.error_fraction(self.series, self.slo_seconds,
                                    window)
        if frac is None:
            return None
        return frac / (1.0 - self.objective)

    def observe(self, store) -> Optional[bool]:
        fast = self.burn(store, self.fast_window)
        slow = self.burn(store, self.slow_window)
        if fast is None or slow is None:
            return None
        self.value = fast
        return fast > self.factor and slow > self.factor

    def describe(self) -> Dict[str, Any]:
        return {"type": "burn_rate", "series": self.series,
                "slo_seconds": self.slo_seconds,
                "objective": self.objective,
                "fast_window": self.fast_window,
                "slow_window": self.slow_window,
                "factor": self.factor}


RULE_TYPES = {"threshold": ThresholdRule, "burn_rate": BurnRateRule}


def parse_rule(spec: Dict[str, Any]) -> Rule:
    """One rule from a config dict — FAIL-CLOSED: unknown type,
    unknown series, malformed field all raise at parse."""
    spec = dict(spec)
    kind = spec.pop("type", "threshold")
    cls = RULE_TYPES.get(kind)
    if cls is None:
        raise ValueError("alert rule %r: unknown type %r (one of %s)"
                         % (spec.get("name"), kind,
                            "/".join(sorted(RULE_TYPES))))
    try:
        return cls(**spec)
    except TypeError as e:
        raise ValueError("alert rule %r: %s" % (spec.get("name"), e))


def default_rules() -> List[Rule]:
    """The shipped rule set. Window/target knobs ride
    ``root.common.telemetry.watch.*`` so drills and small fleets can
    shrink them without redefining the rules:
    ``slo_ttft_ms`` (500), ``slo_e2e_ms`` (5000), ``objective``
    (0.99), ``fast_window`` (30 s), ``slow_window`` (120 s),
    ``burn_factor`` (6), ``queue_depth_limit`` (64),
    ``shed_rate_limit`` (5/s)."""
    try:
        from ..config import root
        node = root.common.telemetry.watch
        get = node.get
    except Exception:        # noqa: BLE001 — config not importable
        get = lambda name, default=None: default      # noqa: E731
    fast = float(get("fast_window", 30.0) or 30.0)
    slow = float(get("slow_window", 120.0) or 120.0)
    factor = float(get("burn_factor", 6.0) or 6.0)
    objective = float(get("objective", 0.99) or 0.99)
    return [
        BurnRateRule(
            "slo_ttft_burn", "veles_serving_ttft_seconds",
            slo_seconds=float(get("slo_ttft_ms", 500.0) or 500.0)
            / 1000.0,
            objective=objective, fast_window=fast, slow_window=slow,
            factor=factor, severity="warn"),
        BurnRateRule(
            "slo_e2e_burn", "veles_serving_e2e_seconds",
            slo_seconds=float(get("slo_e2e_ms", 5000.0) or 5000.0)
            / 1000.0,
            objective=objective, fast_window=fast, slow_window=slow,
            factor=factor, severity="warn"),
        ThresholdRule(
            "queue_depth_high", "veles_serving_queue_depth",
            threshold=float(get("queue_depth_limit", 64) or 64),
            op=">", source="gauge", severity="warn"),
        ThresholdRule(
            "shed_rate_high", "veles_shed_requests_total",
            threshold=float(get("shed_rate_limit", 5.0) or 5.0),
            op=">", source="rate", window=fast, severity="warn"),
        # the brownout<->alert cross-link (docs/services.md): ladder
        # level >= 2 means speculative decoding is stripped and batch
        # shedding is next — the replica is past graceful degradation,
        # so the critical hook routes traffic around it until the
        # ladder climbs back down
        ThresholdRule(
            "brownout_shedding", "veles_qos_brownout_level",
            threshold=2.0, op=">=", source="gauge",
            severity="critical", fire_for=3, resolve_for=3),
    ]


def rules_from_config() -> List[Rule]:
    """Shipped defaults + operator rules from
    ``root.common.telemetry.watch.rules`` (a list of rule dicts —
    JSON config or ``--watch-rules FILE``). Duplicate names: the
    operator's rule replaces the default."""
    rules = {r.name: r for r in default_rules()}
    try:
        from ..config import root
        extra = root.common.telemetry.watch.get("rules", None) or ()
    except Exception:        # noqa: BLE001 — config not importable
        extra = ()
    for spec in extra:
        rule = parse_rule(dict(spec))
        rules[rule.name] = rule
    return list(rules.values())


class AlertEngine:
    """Evaluate a rule set against a SeriesStore; own the transition
    side effects (flight recorder, ring events, counters, the
    critical health hook)."""

    def __init__(self, store, rules: Sequence[Rule],
                 clock: Callable[[], float] = time.time,
                 health_name: str = "watch",
                 dump_on_critical: bool = True) -> None:
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError("duplicate alert rule names: %s" % names)
        self.store = store
        self.rules = list(rules)
        self.clock = clock
        self.health_name = health_name
        self.dump_on_critical = dump_on_critical

    def evaluate(self) -> List[Dict[str, Any]]:
        """One sweep over every rule (the sampler-thread tick after
        each sample); returns the transitions that happened."""
        counters.inc("veles_alert_evals_total")
        now = float(self.clock())
        transitions = []
        for rule in self.rules:
            try:
                edge = rule.step(self.store, now)
            except Exception:    # noqa: BLE001 — one bad rule must
                continue         # not take the sweep down
            if edge is not None:
                self._transition(rule, edge, now)
                transitions.append({"rule": rule.name, "state": edge,
                                    "value": rule.value})
        return transitions

    def _transition(self, rule: Rule, edge: str, now: float) -> None:
        counters.inc("veles_alert_transitions_total")
        value = None if rule.value is None else float(rule.value)
        self.store.note_event("watch.alert", rule=rule.name,
                              state=edge, value=value,
                              severity=rule.severity)
        try:
            from .recorder import flight
            flight.note("alert", rule=rule.name, state=edge,
                        value=value, severity=rule.severity)
        except Exception:        # noqa: BLE001 — observability only
            flight = None
        if rule.severity != "critical":
            return
        # the critical hook: a firing page-severity rule flips this
        # process unready (the router probe loop routes around it)
        # and preserves the forensics; resolve restores admission
        try:
            from ..resilience import health
            token = "alert.%s.%s" % (self.health_name, rule.name)
            if edge == "firing":
                health.mark_unready(token)
                counters.inc("veles_alert_critical_unready_total")
            else:
                health.mark_ready(token)
        except Exception:        # noqa: BLE001 — observability only
            pass
        if edge == "firing" and self.dump_on_critical \
                and flight is not None:
            try:
                flight.dump("alert:%s" % rule.name)
            except Exception:    # noqa: BLE001 — the black box must
                pass             # not take the alert path down

    def status(self) -> List[Dict[str, Any]]:
        return [rule.status() for rule in self.rules]

    def firing(self) -> List[str]:
        return [rule.name for rule in self.rules
                if rule.state == "firing"]

    def render_firing(self) -> str:
        """``veles_alert_firing{rule="..."}`` exposition rows (the
        labeled-gauge style of fleet.render's endpoint_up) — appended
        after metrics_text by every surface serving a live
        watchtower."""
        lines = [
            "# HELP veles_alert_firing 1 = alert rule currently "
            "firing (watchtower rule engine, telemetry/alerts.py)",
            "# TYPE veles_alert_firing gauge",
        ]
        for rule in self.rules:
            lines.append('veles_alert_firing{rule="%s"} %d'
                         % (rule.name,
                            1 if rule.state == "firing" else 0))
        return "\n".join(lines) + "\n"


def render_firing() -> str:
    """Module-level :meth:`AlertEngine.render_firing` on the live
    engine — empty string while the watchtower is off, so /metrics
    renders byte-identical to the pre-watchtower page."""
    from . import timeseries
    engine = timeseries.alert_engine()
    return "" if engine is None else engine.render_firing()
