"""Cost accounting: FLOPs / bytes / peak memory from the compiler.

Roofline-style accounting is how the TPU linear-algebra literature
reports utilization; this module makes the framework itself the source
of those numbers instead of hand-derivations in docs/perf.md. Primary
source: ``jax.stages.Compiled.cost_analysis()`` on the lowered
computation — exact for everything XLA compiles. Pallas kernels report
nothing through that interface (the custom-call is opaque to the HLO
cost model), so the ops that own kernels publish an ``analytic_cost``
(ops/flash_attention.py, ops/fused_fc.py) and the
:class:`CostModel` merges both sources into one per-unit ledger.

MFU here is the standard quotient: analytic/compiler model FLOPs per
second over the chip's nominal dense bf16 peak — the same numerator
convention bench.py has always used (2·spatial·weights per conv
position, ×3 for training), now computed and reported by the framework.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

#: nominal dense bf16 peak FLOP/s per chip by device kind (public
#: numbers; substring-matched against jax device_kind, first hit wins).
#: THE one copy — bench.py imports it from here.
PEAK_BF16 = [
    ("v6", 918e12), ("v5p", 459e12), ("v5", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
]
DEFAULT_PEAK = 275e12

#: nominal dense f32 peak FLOP/s per chip. The MXU computes bf16
#: products with f32 accumulation; full-f32 matmul throughput is the
#: STATED assumption peak_bf16/2 (a bf16x3-style decomposition costs
#: at least that), written down as its own table so an f32 workload's
#: MFU is graded against an f32 roofline instead of being understated
#: 2× against the bf16 peak. Same substring matching as PEAK_BF16.
PEAK_F32 = [
    ("v6", 459e12), ("v5p", 229.5e12), ("v5", 98.5e12),
    ("v4", 137.5e12), ("v3", 61.5e12), ("v2", 22.5e12),
]
DEFAULT_PEAK_F32 = 137.5e12


#: assumed aggregate ICI bandwidth per chip, bytes/s (public nominal
#: numbers, substring-matched like PEAK_BF16; first hit wins). This is
#: the STATED input of the elastic scaling model
#: (resilience/elastic.py predict_step_time → SCALING.json): change a
#: value here and every prediction re-anchors — the point is that the
#: assumption is written down where one measurement can refute it.
ICI_BW_BYTES = [
    ("v6", 3.584e11), ("v5p", 4.8e11), ("v5", 1.6e11),
    ("v4", 2.4e11), ("v3", 1.4e11), ("v2", 6.4e10),
]
#: hosts without a known interconnect (CPU meshes, unknown chips):
#: loopback-class assumption, stamped as such in the prediction record
DEFAULT_ICI_BW = 1.0e11


def ici_bandwidth_entry(device_kind: Optional[str] = None):
    """(source label, assumed per-chip ICI bytes/s) for
    ``device_kind`` — the label names the EXACT assumption used
    (``ICI_BW_BYTES[<key>]`` on a table hit, ``DEFAULT_ICI_BW``
    otherwise), so the scaling model's falsifiability record can never
    misattribute its own input."""
    if device_kind is None:
        import jax
        try:
            device_kind = str(getattr(jax.devices()[0], "device_kind",
                                      "unknown"))
        except Exception:            # noqa: BLE001 — backend init failure
            device_kind = "unknown"
    kind = str(device_kind).lower()
    for key, bw in ICI_BW_BYTES:
        if key in kind:
            return "telemetry.cost.ICI_BW_BYTES[%s]" % key, bw
    return ("telemetry.cost.DEFAULT_ICI_BW (loopback-class "
            "assumption: %g)" % DEFAULT_ICI_BW), DEFAULT_ICI_BW


def ici_bandwidth(device_kind: Optional[str] = None) -> float:
    """Assumed per-chip ICI bytes/s for ``device_kind`` (default: the
    first visible jax device) — the scaling model's comm denominator."""
    return ici_bandwidth_entry(device_kind)[1]


def peak_bf16_flops(device_kind: Optional[str] = None) -> float:
    """Nominal dense bf16 peak FLOP/s for ``device_kind`` (default: the
    first visible jax device)."""
    if device_kind is None:
        import jax
        try:
            device_kind = str(getattr(jax.devices()[0], "device_kind",
                                      "unknown"))
        except Exception:            # noqa: BLE001 — backend init failure
            device_kind = "unknown"
    kind = str(device_kind).lower()
    return next((p for key, p in PEAK_BF16 if key in kind), DEFAULT_PEAK)


def peak_flops_entry(dtype=None, device_kind: Optional[str] = None):
    """(source label, nominal dense peak FLOP/s) keyed on the
    COMPUTATION dtype: f32 (and f64, which has no MXU path at all —
    priced at the f32 table as the optimistic bound) resolves through
    PEAK_F32, everything else (bf16/f16/int8-ish mixed precision)
    through PEAK_BF16. The label names the exact table entry used so
    bench sections can stamp the peak they were graded against."""
    if dtype is None:
        name = "bfloat16"
    else:
        try:            # accepts "float32", numpy.float32, dtype objects
            import numpy
            name = numpy.dtype(dtype).name
        except TypeError:       # e.g. "bf16" shorthand, jax weak types
            name = str(getattr(dtype, "name", dtype))
    name = name.lower()
    f32_class = name in ("float32", "f32", "float64", "f64")
    table, default, tname = (
        (PEAK_F32, DEFAULT_PEAK_F32, "PEAK_F32") if f32_class
        else (PEAK_BF16, DEFAULT_PEAK, "PEAK_BF16"))
    if device_kind is None:
        import jax
        try:
            device_kind = str(getattr(jax.devices()[0], "device_kind",
                                      "unknown"))
        except Exception:            # noqa: BLE001 — backend init failure
            device_kind = "unknown"
    kind = str(device_kind).lower()
    for key, p in table:
        if key in kind:
            return "telemetry.cost.%s[%s]" % (tname, key), p
    return "telemetry.cost.DEFAULT_%s" % ("PEAK_F32" if f32_class
                                          else "PEAK"), default


def peak_flops(dtype=None, device_kind: Optional[str] = None) -> float:
    """Nominal dense peak FLOP/s for ``dtype`` on ``device_kind``
    (default: the first visible jax device) — the dtype-aware MFU
    denominator. ``peak_flops("float32") == peak_bf16_flops()/2``."""
    return peak_flops_entry(dtype, device_kind)[1]


class Cost:
    """One computation's cost: model FLOPs, bytes accessed (HBM traffic
    as the compiler models it), peak live memory."""

    __slots__ = ("flops", "bytes_accessed", "peak_memory", "source")

    def __init__(self, flops: float = 0.0, bytes_accessed: float = 0.0,
                 peak_memory: float = 0.0, source: str = "analytic"):
        self.flops = float(flops)
        self.bytes_accessed = float(bytes_accessed)
        self.peak_memory = float(peak_memory)
        #: "xla" (compiler-reported) | "analytic" (fallback table)
        self.source = source

    def __add__(self, other: "Cost") -> "Cost":
        return Cost(self.flops + other.flops,
                    self.bytes_accessed + other.bytes_accessed,
                    max(self.peak_memory, other.peak_memory),
                    self.source if self.source == other.source
                    else "mixed")

    def scaled(self, n: float) -> "Cost":
        """Cost of running this computation ``n`` times (peak memory is
        per-execution and does not scale)."""
        return Cost(self.flops * n, self.bytes_accessed * n,
                    self.peak_memory, self.source)

    @property
    def arithmetic_intensity(self) -> float:
        """FLOPs per byte accessed — the roofline x-axis."""
        return self.flops / self.bytes_accessed if self.bytes_accessed \
            else 0.0

    def mfu(self, seconds: float, peak_flops: Optional[float] = None,
            n_chips: int = 1) -> float:
        """Model FLOP utilization of executing this cost in
        ``seconds`` on ``n_chips`` chips of ``peak_flops`` each."""
        if seconds <= 0:
            return 0.0
        peak = peak_flops if peak_flops is not None else peak_bf16_flops()
        return self.flops / seconds / (peak * n_chips)

    def as_dict(self) -> Dict[str, float]:
        return {"flops": self.flops, "bytes": self.bytes_accessed,
                "peak_memory": self.peak_memory, "source": self.source}

    def __repr__(self) -> str:
        return ("Cost(flops=%.3e, bytes=%.3e, peak=%.3e, %s)"
                % (self.flops, self.bytes_accessed, self.peak_memory,
                   self.source))


def _sum_cost_analysis(ca: Any) -> Dict[str, float]:
    """cost_analysis() returns a dict (new jax) or list of per-
    computation dicts (older); flatten to summed keys."""
    if ca is None:
        return {}
    if isinstance(ca, dict):
        dicts = [ca]
    else:
        dicts = [d for d in ca if isinstance(d, dict)]
    out: Dict[str, float] = {}
    for d in dicts:
        for k, v in d.items():
            if isinstance(v, (int, float)):
                out[k] = out.get(k, 0.0) + float(v)
    return out


#: thread-local collector for Pallas kernel costs noted at TRACE time.
#: XLA's HLO cost model counts a scan/while body ONCE (verified: a
#: 10-step scanned matmul reports one matmul's flops), and a kernel's
#: Python builder also runs once per call site per trace — so costs
#: noted here share the compiler's body-once convention and can be
#: summed with cost_analysis() numbers without double counting.
_trace_notes = threading.local()


class collecting_kernel_costs:
    """``with collecting_kernel_costs() as notes:`` — while tracing
    inside the block, kernels that call :func:`note_kernel_cost`
    (ops/flash_attention.py) append their analytic costs to
    ``notes``."""

    def __enter__(self):
        self._prev = getattr(_trace_notes, "acc", None)
        _trace_notes.acc = []
        return _trace_notes.acc

    def __exit__(self, *exc: Any) -> None:
        _trace_notes.acc = self._prev


def note_kernel_cost(cost: Cost) -> None:
    """Called by Pallas kernel entry points at trace time: registers
    the kernel's analytic cost with whatever
    :class:`collecting_kernel_costs` block is active (no-op outside
    one — normal jit tracing pays nothing)."""
    acc = getattr(_trace_notes, "acc", None)
    if acc is not None:
        acc.append(cost)


def cost_of_compiled(compiled: Any) -> Cost:
    """Extract a :class:`Cost` from a ``jax.stages.Compiled``."""
    summed = {}
    try:
        summed = _sum_cost_analysis(compiled.cost_analysis())
    except Exception:                # noqa: BLE001 — backend-optional API
        pass
    peak = 0.0
    try:
        mem = compiled.memory_analysis()
        peak = float(mem.argument_size_in_bytes
                     + mem.output_size_in_bytes
                     + mem.temp_size_in_bytes)
    except Exception:                # noqa: BLE001
        pass
    return Cost(summed.get("flops", 0.0),
                summed.get("bytes accessed", 0.0), peak, source="xla")


def cost_of_fn(fn: Callable, *args: Any, **kwargs: Any) -> Cost:
    """Lower + compile ``fn`` on the given abstract/concrete args and
    read its cost. Compilation hits jax's persistent cache, so calling
    this on an already-used jitted function is cheap."""
    import jax
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    return cost_of_compiled(jitted.lower(*args, **kwargs).compile())


class CostModel:
    """Per-unit cost ledger: the framework's own measured-MFU source.

    Units (or bench sections) record the cost of their compiled
    programs under a name; :meth:`report` divides accumulated FLOPs by
    measured seconds and the chip's nominal peak — MFU as a framework
    output, not a hand calculation. Thread-safe (serving counters and
    training record concurrently).
    """

    def __init__(self, peak_flops: Optional[float] = None) -> None:
        self._lock = threading.Lock()
        self._ledger: Dict[str, Cost] = {}
        self._execs: Dict[str, int] = {}
        self._peak = peak_flops

    @property
    def peak_flops(self) -> float:
        if self._peak is None:
            self._peak = peak_bf16_flops()
        return self._peak

    def record(self, name: str, cost: Cost, executions: float = 1) -> None:
        """Accumulate ``cost`` × ``executions`` under ``name``."""
        with self._lock:
            add = cost.scaled(executions)
            cur = self._ledger.get(name)
            self._ledger[name] = add if cur is None else cur + add
            self._execs[name] = self._execs.get(name, 0) + int(executions)

    def record_compiled(self, name: str, compiled: Any,
                        executions: float = 1) -> Cost:
        cost = cost_of_compiled(compiled)
        self.record(name, cost, executions)
        return cost

    def get(self, name: str) -> Optional[Cost]:
        with self._lock:
            return self._ledger.get(name)

    def total(self) -> Cost:
        with self._lock:
            total = Cost()
            for c in self._ledger.values():
                total = total + c
            return total

    def mfu(self, name: str, seconds: float, n_chips: int = 1) -> float:
        cost = self.get(name)
        if cost is None:
            return 0.0
        return cost.mfu(seconds, self.peak_flops, n_chips)

    def report(self, seconds_by_name: Optional[Dict[str, float]] = None,
               n_chips: int = 1) -> Dict[str, Dict[str, float]]:
        """Structured per-name summary; entries with measured seconds
        carry ``tflops_per_sec`` and ``mfu``."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            items = list(self._ledger.items())
            execs = dict(self._execs)
        for name, cost in items:
            row = cost.as_dict()
            row["executions"] = execs.get(name, 0)
            row["arithmetic_intensity"] = cost.arithmetic_intensity
            secs = (seconds_by_name or {}).get(name)
            if secs:
                row["seconds"] = secs
                row["tflops_per_sec"] = cost.flops / secs / 1e12
                row["mfu"] = cost.mfu(secs, self.peak_flops, n_chips)
            out[name] = row
        return out

    def clear(self) -> None:
        with self._lock:
            self._ledger.clear()
            self._execs.clear()


#: process-global ledger instrumented units record into (mirrors
#: counters.counters / spans.recorder).
model = CostModel()
