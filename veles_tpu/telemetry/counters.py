"""Process-global, thread-safe performance counter registry.

The deterministic backbone of the telemetry subsystem: counters count
*events the framework itself causes* — device dispatches, XLA
compiles, jit-cache hits, host↔device bytes, serving retries — so a
perf gate on them is exact regardless of relay weather (wall-clock
through the shared TPU tunnel swings 7.6× between windows,
docs/perf.md). The HTTP services render :func:`prometheus_text` at
``/metrics`` (web_status.py, restful_api.py).

Naming follows the Prometheus convention: ``veles_<what>_total`` for
monotonic counters, snake_case, unit suffix where applicable
(``_bytes_total``). The registry is flat name → float; callers use the
module-level :func:`inc` / :func:`snapshot` / :func:`delta` helpers on
the singleton :data:`counters`.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

#: canonical counter names with HELP strings (also the /metrics HELP
#: lines). Ad-hoc names are allowed, but instrumented code sticks to
#: these so dashboards and gates agree on spelling.
DESCRIPTIONS = {
    "veles_dispatches_total":
        "Jitted device program executions (one per jitted call)",
    "veles_compiles_total":
        "XLA compilations observed (jit cache misses at call time)",
    "veles_jit_cache_hits_total":
        "Unit-level jit lookups served from the per-unit cache",
    "veles_h2d_bytes_total":
        "Bytes explicitly transferred host to device",
    "veles_d2h_bytes_total":
        "Bytes explicitly fetched device to host",
    "veles_unit_runs_total":
        "Unit.run invocations through the workflow scheduler",
    "veles_decode_tokens_total":
        "Tokens emitted by the generation stack",
    "veles_decode_dispatches_total":
        "Device dispatches spent producing those tokens",
    "veles_flash_attention_traces_total":
        "Programs (re)built containing the flash-attention kernel",
    "veles_spans_total":
        "Telemetry spans recorded",
    # resilience subsystem (veles_tpu/resilience/): these exist so
    # chaos runs are countable; bench.py's gate asserts they read 0 in
    # clean (no fault spec) runs
    "veles_faults_injected_total":
        "Faults fired by the deterministic injection plane",
    "veles_retries_total":
        "Operations retried by a RetryPolicy (backoff performed)",
    "veles_shed_requests_total":
        "Serving requests shed with 503 + Retry-After",
    "veles_watchdog_trips_total":
        "step_watchdog threshold trips (possible hangs)",
    "veles_snapshots_quarantined_total":
        "Corrupt snapshots renamed *.corrupt during chain restore",
    # elastic training plane (veles_tpu/resilience/elastic.py):
    # bench.py's gate asserts the generation counters read 0 in
    # non-elastic runs and bounds the per-handoff reshard time
    "veles_elastic_generations_total":
        "Elastic training generations started (first generation "
        "included)",
    "veles_elastic_preemptions_total":
        "Host-loss events that ended a generation (heartbeat lapse, "
        "join failure, or an injected distributed.host_loss fault)",
    "veles_elastic_reshard_seconds_total":
        "Seconds spent restoring + resharding state at elastic "
        "generation handoffs",
    "veles_elastic_barrier_timeouts_total":
        "Elastic survivor barriers that failed or timed out",
    "veles_manifest_cursor_defaults_total":
        "Snapshot manifests read without an {epoch, step, world_size} "
        "cursor (pre-elastic manifests; defaulted, never a crash)",
    # overlap subsystem (veles_tpu/overlap/): bench.py's gate asserts
    # the side-plane/prefetch counters read 0 in overlap-off runs
    "veles_sideplane_tasks_total":
        "Tasks executed by side-plane lane workers",
    "veles_sideplane_errors_total":
        "Side-plane tasks that raised (routed to drain + health)",
    "veles_sideplane_stall_seconds_total":
        "Seconds the main thread blocked on side-plane backpressure "
        "or drain barriers",
    "veles_prefetch_batches_total":
        "Batches staged ahead by the data-plane prefetcher",
    "veles_prefetch_hits_total":
        "Prefetcher gets served without waiting (batch was ready)",
    "veles_prefetch_misses_total":
        "Prefetcher gets that had to wait for the producer",
    "veles_prefetch_stall_seconds_total":
        "Seconds consumers waited on the prefetch queue",
    # continuous-batching serving engine (veles_tpu/serving/):
    # bench.py's gate asserts these read 0 in non-serving runs
    "veles_serving_admitted_total":
        "Requests admitted into continuous-batching KV-cache slots",
    "veles_serving_retired_total":
        "Slot rows retired (eos_id emitted or own n_new reached)",
    "veles_serving_prefill_dispatches_total":
        "Bucketed prefill programs dispatched by the serving engine",
    "veles_serving_decode_dispatches_total":
        "Pooled fixed-shape decode steps dispatched by the serving "
        "engine",
    "veles_serving_tokens_total":
        "Tokens emitted by the continuous-batching engine",
    "veles_serving_queue_wait_seconds_total":
        "Seconds requests waited in the serving queue before a slot",
    "veles_serving_expired_total":
        "Queued generation requests answered 503 past their deadline",
    "veles_serving_pages_alloc_total":
        "KV-cache pages allocated from the paged serving pool "
        "(admission prefills + decode-time growth)",
    "veles_serving_pages_free_total":
        "KV-cache pages returned to the paged serving pool at row "
        "retirement",
    "veles_serving_pages_exhausted_total":
        "Page allocations refused by an exhausted pool (admission "
        "waits; decode-time growth sheds 503 + Retry-After)",
    "veles_serving_spec_rounds_total":
        "On-device draft/verify speculation rounds run over slot-pool "
        "rows",
    "veles_serving_beam_steps_total":
        "Fixed-shape beam top-k steps run over slot-pool hypothesis "
        "groups",
    "veles_serving_compile_seconds_total":
        "Seconds the serving engine spent jit-tracing/compiling its "
        "live decode/prefill programs (0 in AOT-artifact mode)",
    # quantization subsystem (veles_tpu/quant/): bench.py's gate
    # asserts the quant/artifact counters read 0 in quant-off,
    # artifact-off runs
    "veles_quant_params_total":
        "Parameter tensors quantized to int8 (per-channel symmetric)",
    "veles_quant_bytes_saved_total":
        "Bytes saved by int8 weight quantization (float minus "
        "int8+scale storage)",
    "veles_quant_calibrations_total":
        "Weight-quantization calibration passes (amax scale scans)",
    "veles_artifact_loads_total":
        "AOT serve-artifacts loaded by the serving engine",
    "veles_artifact_load_failures_total":
        "AOT serve-artifact loads that failed and fell back to "
        "live jit (corrupt/mismatched/injected)",
    # device-time measurement plane (telemetry/devtime.py): how each
    # bench section's device_time_s was obtained — profiler capture
    # vs the counted host-sync fallback — and how many gate sections
    # had to fall back to wall-clock (legacy pre-devtime documents)
    "veles_devtime_captures_total":
        "Profiler trace captures that yielded device-stream "
        "self-time",
    "veles_devtime_fallbacks_total":
        "Device-time measurements served by the host-sync wall-clock "
        "fallback (profiler unavailable or no device streams)",
    "veles_bench_legacy_sections_total":
        "Gate sections compared on wall-clock because a legacy bench "
        "document carries no device_time_s fields",
    # model-health observability (telemetry/tensormon.py +
    # telemetry/recorder.py): bench.py's gate asserts the sample/NaN
    # counters read 0 in tensormon-off runs
    "veles_tensormon_samples_total":
        "Tensor-statistics samples drained from the jitted train step",
    "veles_model_nan_total":
        "Non-finite (NaN/Inf) values detected in gradients, loss or "
        "activations by the tensormon taps",
    "veles_model_health_errors_total":
        "ModelHealthError raised by the NaN sentinel (halt policies)",
    "veles_blackbox_dumps_total":
        "Flight-recorder black-box dumps written",
}


def describe_counter(name: str) -> str:
    return DESCRIPTIONS.get(name, "veles_tpu counter")


#: increment observers installed by the flight recorder
#: (telemetry/recorder.py): called as ``hook(name, value, new_total)``
#: AFTER the registry lock is released, exceptions swallowed — an
#: observer can never deadlock or take an instrumented call site down.
_inc_hooks = []


def add_inc_hook(fn) -> None:
    if fn not in _inc_hooks:
        _inc_hooks.append(fn)


class CounterRegistry:
    """Flat, thread-safe name → value map of monotonic counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {}

    def inc(self, name: str, value: float = 1) -> float:
        """Add ``value`` (default 1) to ``name``; returns the new total."""
        with self._lock:
            new = self._values.get(name, 0) + value
            self._values[name] = new
        for hook in _inc_hooks:
            try:
                hook(name, value, new)
            except Exception:       # noqa: BLE001 — observers only
                pass
        return new

    def get(self, name: str) -> float:
        with self._lock:
            return self._values.get(name, 0)

    def snapshot(self) -> Dict[str, float]:
        """Point-in-time copy of every counter."""
        with self._lock:
            return dict(self._values)

    def delta(self, before: Dict[str, float],
              names: Optional[tuple] = None) -> Dict[str, float]:
        """Per-counter growth since a :meth:`snapshot`; zero-growth
        counters are omitted so span records stay small."""
        now = self.snapshot()
        keys = names if names is not None else now.keys()
        out = {}
        for k in keys:
            d = now.get(k, 0) - before.get(k, 0)
            if d:
                out[k] = d
        return out

    def reset(self) -> None:
        """Zero everything — tests and bench section boundaries only
        (production counters are monotonic for the life of the
        process, as Prometheus scraping expects)."""
        with self._lock:
            self._values.clear()

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain; version 0.0.4).
        One snapshot renders the whole page — names and values from
        the same instant."""
        lines = []
        for name, val in sorted(self.snapshot().items()):
            lines.append("# HELP %s %s" % (name, describe_counter(name)))
            lines.append("# TYPE %s counter" % name)
            # integral counters print without a trailing .0 (scrapers
            # accept both; humans diff these files)
            lines.append("%s %s" % (
                name, int(val) if float(val).is_integer() else val))
        return "\n".join(lines) + "\n"


#: THE process-global registry every instrumented call site uses.
counters = CounterRegistry()


#: Content-Type every /metrics endpoint replies with
METRICS_CONTENT_TYPE = "text/plain; version=0.0.4"


def metrics_text(gauges: Optional[dict] = None) -> str:
    """The full /metrics page: the counter registry plus the caller's
    service gauges — THE one renderer behind every /metrics endpoint
    (web_status, RESTfulAPI, GenerationAPI), so format changes happen
    in one place. ``gauges``: name → value (or (value, help) tuple)."""
    text = counters.prometheus_text()
    for name, val in (gauges or {}).items():
        help_text = None
        if isinstance(val, tuple):
            val, help_text = val
        text += gauge_text(name, val, help_text)
    return text


def gauge_text(name: str, value, help_text: Optional[str] = None) -> str:
    """One Prometheus gauge in exposition format — the shared renderer
    for the ad-hoc service gauges every /metrics endpoint appends after
    :func:`prometheus_text` (web_status, RESTfulAPI, GenerationAPI)."""
    lines = []
    if help_text:
        lines.append("# HELP %s %s" % (name, help_text))
    lines.append("# TYPE %s gauge" % name)
    val = float(value)
    lines.append("%s %s" % (name, int(val) if val.is_integer() else val))
    return "\n".join(lines) + "\n"


def inc(name: str, value: float = 1) -> float:
    return counters.inc(name, value)


def snapshot() -> Dict[str, float]:
    return counters.snapshot()


def prometheus_text() -> str:
    return counters.prometheus_text()
