"""Process-global, thread-safe performance counter registry.

The deterministic backbone of the telemetry subsystem: counters count
*events the framework itself causes* — device dispatches, XLA
compiles, jit-cache hits, host↔device bytes, serving retries — so a
perf gate on them is exact regardless of relay weather (wall-clock
through the shared TPU tunnel swings 7.6× between windows,
docs/perf.md). The HTTP services render :func:`prometheus_text` at
``/metrics`` (web_status.py, restful_api.py).

Naming follows the Prometheus convention: ``veles_<what>_total`` for
monotonic counters, snake_case, unit suffix where applicable
(``_bytes_total``). The registry is flat name → float; callers use the
module-level :func:`inc` / :func:`snapshot` / :func:`delta` helpers on
the singleton :data:`counters`.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

#: canonical counter names with HELP strings (also the /metrics HELP
#: lines). Ad-hoc names are allowed, but instrumented code sticks to
#: these so dashboards and gates agree on spelling.
DESCRIPTIONS = {
    "veles_dispatches_total":
        "Jitted device program executions (one per jitted call)",
    "veles_compiles_total":
        "XLA compilations observed (jit cache misses at call time)",
    "veles_jit_cache_hits_total":
        "Unit-level jit lookups served from the per-unit cache",
    "veles_h2d_bytes_total":
        "Bytes explicitly transferred host to device",
    "veles_d2h_bytes_total":
        "Bytes explicitly fetched device to host",
    "veles_unit_runs_total":
        "Unit.run invocations through the workflow scheduler",
    "veles_decode_tokens_total":
        "Tokens emitted by the generation stack",
    "veles_decode_dispatches_total":
        "Device dispatches spent producing those tokens",
    "veles_flash_attention_traces_total":
        "Programs (re)built containing the flash-attention kernel",
    "veles_spans_total":
        "Telemetry spans recorded",
    # resilience subsystem (veles_tpu/resilience/): these exist so
    # chaos runs are countable; bench.py's gate asserts they read 0 in
    # clean (no fault spec) runs
    "veles_faults_injected_total":
        "Faults fired by the deterministic injection plane",
    "veles_retries_total":
        "Operations retried by a RetryPolicy (backoff performed)",
    "veles_shed_requests_total":
        "Serving requests shed with 503 + Retry-After",
    "veles_watchdog_trips_total":
        "step_watchdog threshold trips (possible hangs)",
    "veles_snapshots_quarantined_total":
        "Corrupt snapshots renamed *.corrupt during chain restore",
    # elastic training plane (veles_tpu/resilience/elastic.py):
    # bench.py's gate asserts the generation counters read 0 in
    # non-elastic runs and bounds the per-handoff reshard time
    "veles_elastic_generations_total":
        "Elastic training generations started (first generation "
        "included)",
    "veles_elastic_preemptions_total":
        "Host-loss events that ended a generation (heartbeat lapse, "
        "join failure, or an injected distributed.host_loss fault)",
    "veles_elastic_reshard_seconds_total":
        "Seconds spent restoring + resharding state at elastic "
        "generation handoffs",
    "veles_elastic_barrier_timeouts_total":
        "Elastic survivor barriers that failed or timed out",
    "veles_manifest_cursor_defaults_total":
        "Snapshot manifests read without an {epoch, step, world_size} "
        "cursor (pre-elastic manifests; defaulted, never a crash)",
    # overlap subsystem (veles_tpu/overlap/): bench.py's gate asserts
    # the side-plane/prefetch counters read 0 in overlap-off runs
    "veles_sideplane_tasks_total":
        "Tasks executed by side-plane lane workers",
    "veles_sideplane_errors_total":
        "Side-plane tasks that raised (routed to drain + health)",
    "veles_sideplane_stall_seconds_total":
        "Seconds the main thread blocked on side-plane backpressure "
        "or drain barriers",
    "veles_prefetch_batches_total":
        "Batches staged ahead by the data-plane prefetcher",
    "veles_prefetch_hits_total":
        "Prefetcher gets served without waiting (batch was ready)",
    "veles_prefetch_misses_total":
        "Prefetcher gets that had to wait for the producer",
    "veles_prefetch_stall_seconds_total":
        "Seconds consumers waited on the prefetch queue",
    # continuous-batching serving engine (veles_tpu/serving/):
    # bench.py's gate asserts these read 0 in non-serving runs
    "veles_serving_admitted_total":
        "Requests admitted into continuous-batching KV-cache slots",
    "veles_serving_retired_total":
        "Slot rows retired (eos_id emitted or own n_new reached)",
    "veles_serving_prefill_dispatches_total":
        "Bucketed prefill programs dispatched by the serving engine",
    "veles_serving_decode_dispatches_total":
        "Pooled fixed-shape decode steps dispatched by the serving "
        "engine",
    "veles_serving_tokens_total":
        "Tokens emitted by the continuous-batching engine",
    "veles_serving_queue_wait_seconds_total":
        "Seconds requests waited in the serving queue before a slot",
    "veles_serving_expired_total":
        "Queued generation requests answered 503 past their deadline",
    "veles_serving_pages_alloc_total":
        "KV-cache pages allocated from the paged serving pool "
        "(admission prefills + decode-time growth)",
    "veles_serving_pages_free_total":
        "KV-cache pages returned to the paged serving pool at row "
        "retirement",
    "veles_serving_pages_exhausted_total":
        "Page allocations refused by an exhausted pool (admission "
        "waits; decode-time growth sheds 503 + Retry-After)",
    "veles_serving_spec_rounds_total":
        "On-device draft/verify speculation rounds run over slot-pool "
        "rows",
    "veles_serving_beam_steps_total":
        "Fixed-shape beam top-k steps run over slot-pool hypothesis "
        "groups",
    "veles_serving_compile_seconds_total":
        "Seconds the serving engine spent jit-tracing/compiling its "
        "live decode/prefill programs (0 in AOT-artifact mode)",
    # quantization subsystem (veles_tpu/quant/): bench.py's gate
    # asserts the quant/artifact counters read 0 in quant-off,
    # artifact-off runs
    "veles_quant_params_total":
        "Parameter tensors quantized to int8 (per-channel symmetric)",
    "veles_quant_bytes_saved_total":
        "Bytes saved by int8 weight quantization (float minus "
        "int8+scale storage)",
    "veles_quant_calibrations_total":
        "Weight-quantization calibration passes (amax scale scans)",
    "veles_artifact_loads_total":
        "AOT serve-artifacts loaded by the serving engine",
    "veles_artifact_load_failures_total":
        "AOT serve-artifact loads that failed and fell back to "
        "live jit (corrupt/mismatched/injected)",
    # tensor-parallel serving (serving/engine.py tp= knob): shard_map
    # over the ("model",) mesh slice — bench.py's gate asserts these
    # read 0 in tp=1 runs
    "veles_tp_engines_total":
        "Serving engines started in tensor-parallel mode (one per "
        "mesh slice, however many chips the slice spans)",
    "veles_tp_dispatches_total":
        "Fixed-shape serving programs dispatched as shard_mapped "
        "mesh programs (decode steps, bucketed prefills, chunks, "
        "page copies)",
    # kernel autotune DB provenance (ops/autotune.py): stale-entry
    # lookups — measured under a different jax than the running one
    "veles_autotune_stale_total":
        "kernel_tuning.json hits whose recorded jax version differs "
        "from (or predates) the running toolchain — reused, but due "
        "a re-sweep",
    # device-time measurement plane (telemetry/devtime.py): how each
    # bench section's device_time_s was obtained — profiler capture
    # vs the counted host-sync fallback — and how many gate sections
    # had to fall back to wall-clock (legacy pre-devtime documents)
    "veles_devtime_captures_total":
        "Profiler trace captures that yielded device-stream "
        "self-time",
    "veles_devtime_fallbacks_total":
        "Device-time measurements served by the host-sync wall-clock "
        "fallback (profiler unavailable or no device streams)",
    "veles_bench_legacy_sections_total":
        "Gate sections compared on wall-clock because a legacy bench "
        "document carries no device_time_s fields",
    # model-health observability (telemetry/tensormon.py +
    # telemetry/recorder.py): bench.py's gate asserts the sample/NaN
    # counters read 0 in tensormon-off runs
    "veles_tensormon_samples_total":
        "Tensor-statistics samples drained from the jitted train step",
    "veles_model_nan_total":
        "Non-finite (NaN/Inf) values detected in gradients, loss or "
        "activations by the tensormon taps",
    "veles_model_health_errors_total":
        "ModelHealthError raised by the NaN sentinel (halt policies)",
    "veles_blackbox_dumps_total":
        "Flight-recorder black-box dumps written",
    # request-plane SLO layer (serving/scheduler.py Ticket accounting
    # + the metrics_text renderer below)
    "veles_metrics_name_collisions_total":
        "Caller-supplied /metrics gauges dropped because their name "
        "shadowed an already-rendered counter/histogram series "
        "(duplicate names are invalid Prometheus exposition)",
    # serving fleet router (serving/router.py): bench.py's gate
    # asserts these read 0 in non-fleet runs
    "veles_router_requests_total":
        "Requests admitted by the fleet router's HTTP front",
    "veles_router_attempts_total":
        "Replica attempts the router proxied (first tries + "
        "failover retries)",
    "veles_router_failovers_total":
        "Requests retried on another replica after a failed attempt "
        "(crash, timeout, 5xx)",
    "veles_router_replica_errors_total":
        "Failed replica attempts the router observed (connection "
        "errors, timeouts, 5xx answers)",
    "veles_router_breaker_opens_total":
        "Circuit-breaker transitions to open (threshold consecutive "
        "failures, or a failed half-open probe)",
    "veles_router_duplicate_answers_total":
        "Late replica answers dropped by the exactly-once latch (a "
        "slow-then-successful attempt whose request was already "
        "answered by a failover)",
    "veles_router_respawns_total":
        "Dead serving replicas respawned by the ReplicaSupervisor",
    # lossless request plane (serving/journal.py + token-level
    # failover resume + drain-by-handoff): bench.py's gate asserts
    # these read 0 in non-fleet runs
    "veles_journal_appends_total":
        "Records durably appended to the router's request journal "
        "(admissions + terminals, fsync'd before dispatch/reply)",
    "veles_journal_replayed_total":
        "Journaled requests re-dispatched by a restarted router "
        "(admitted before a crash, unanswered at restart)",
    "veles_journal_salvaged_total":
        "Torn or corrupt journal records quarantined with a warning "
        "at replay (mid-write truncation, bitrot, injected "
        "router.journal corruption) — never a refused start",
    "veles_journal_compactions_total":
        "Journal rotations that rewrote the live (unanswered) "
        "entries into a fresh fsync'd segment and dropped the rest",
    "veles_resume_attempts_total":
        "Failover attempts dispatched with resume_tokens (the retry "
        "continues from tokens_done instead of re-decoding)",
    "veles_resume_tokens_total":
        "Tokens carried into a resumed decode instead of being "
        "re-decoded (the failover savings, summed over resumes)",
    "veles_handoff_requests_total":
        "In-flight requests a draining replica handed back with "
        "progress (503 + resume) instead of aborting or riding out "
        "the full generation",
    # prefix-sharing paged KV cache (serving/pages.py PrefixCache +
    # engine adoption/COW): bench.py's gate asserts these read 0 in
    # non-serving runs
    "veles_prefix_hits_total":
        "Admissions that adopted at least one shared prefix block "
        "from the radix prefix cache (prefill covers only the "
        "unmatched suffix)",
    "veles_prefix_misses_total":
        "Prefix-eligible admissions (>= 1 full token block) that "
        "matched nothing in the prefix cache and prefilled fully",
    "veles_prefix_shared_pages_total":
        "KV-cache pages adopted READ-ONLY into admitting slots from "
        "the prefix cache (each adoption takes one refcount share)",
    "veles_prefix_cow_copies_total":
        "Copy-on-write page copies: a write had to land inside a "
        "shared page (full-prompt match re-computing its last "
        "position), so its content moved to a private page first",
    "veles_prefix_evictions_total":
        "Prefix-cache blocks dropped by LRU leaf eviction (allocator "
        "pressure or the soft block budget)",
    # O(1)-state serving lane (serving/recurrent.py RecurrentEngine +
    # serving/pages.py StateCache): bench.py's gate asserts these read
    # 0 in non-recurrent runs
    "veles_o1_state_checkpoints_total":
        "Recurrent state snapshots cached at page_size-token block "
        "boundaries after a prefill scan (the state lane's prefix-"
        "cache writes)",
    "veles_o1_state_restores_total":
        "Admissions that adopted a cached state checkpoint copy-on-"
        "write and scanned only the unmatched prompt suffix",
    "veles_o1_state_restored_tokens_total":
        "Prompt tokens skipped by adopting state checkpoints instead "
        "of re-scanning them (the restore savings, summed)",
    "veles_o1_state_rescans_total":
        "State restores degraded to a full re-scan from zeros "
        "(injected serve.state_restore checkpoint loss; answers stay "
        "correct, only the scan work is repaid)",
    "veles_o1_state_evictions_total":
        "State-cache checkpoint blocks dropped by LRU leaf eviction "
        "(the soft max_blocks budget)",
    # fleet-wide distributed tracing (telemetry/spans.py ring pulls +
    # telemetry/fleet.py cross-process assembly): bench.py's gate
    # asserts these read 0 in non-fleet runs
    "veles_trace_rotations_total":
        "JSONL --trace-file rotations (the sink grew past "
        "root.common.trace.rotate_bytes; the previous segment is "
        "kept as <path>.1, older ones dropped)",
    "veles_trace_span_pulls_total":
        "Span-ring pulls served over GET /trace/spans (router + "
        "serving APIs; the fleet trace assembler's read path)",
    "veles_trace_fleet_merges_total":
        "Cross-process fleet traces assembled (span pulls merged "
        "onto one clock, one Chrome-trace lane per process)",
    # overload-hardened request plane (serving/overload.py QoS +
    # brownout governor, engine preempt-and-resume): bench.py's gate
    # asserts these read 0 in QoS-off runs
    "veles_qos_preemptions_total":
        "Batch decode rows preempted at a step boundary to free "
        "slots for waiting interactive requests (the row requeues "
        "with its emitted tokens and resumes bit-identical)",
    "veles_qos_preempted_tokens_total":
        "Tokens already decoded by preempted batch rows at the "
        "moment of preemption (all carried through the resume, none "
        "re-decoded)",
    "veles_qos_batch_deferrals_total":
        "Queued batch requests jumped by interactive arrivals in the "
        "priority-aware admission order (each deferral counts once "
        "per sweep it was overtaken in)",
    "veles_qos_throttled_total":
        "Batch requests refused admission by the router's AIMD "
        "controller or brownout ladder (503 + scaled Retry-After; "
        "interactive is never throttled)",
    "veles_qos_brownout_transitions_total":
        "Brownout ladder level changes in either direction "
        "(normal -> cap_n_new -> no_spec -> shed_batch and back)",
    "veles_qos_degraded_requests_total":
        "Admitted requests degraded by the brownout ladder (n_new "
        "capped or speculative decoding stripped)",
    "veles_qos_retry_denied_total":
        "Failover retries denied by the router-wide retry token "
        "bucket (storm control: failed first attempts still answer, "
        "they just do not amplify)",
    # load/chaos harness (veles_tpu/loadgen/): bench.py's gate
    # asserts these read 0 in non-loadgen runs
    "veles_loadgen_requests_total":
        "Requests dispatched open-loop by the load harness",
    "veles_loadgen_shed_total":
        "Load-harness requests answered 503 (shed/throttled/expired "
        "by the fleet under test)",
    "veles_loadgen_errors_total":
        "Load-harness requests that failed for any non-shed reason "
        "(transport errors, non-503 HTTP errors, timeouts)",
    "veles_loadgen_storms_total":
        "Timed chaos storms armed on the fault plane by the load "
        "harness (one per storm clause per run)",
    # distributed linear-algebra family (veles_tpu/linalg/): bench.py's
    # gate asserts these read 0 in non-linalg runs
    "veles_linalg_block_ops_total":
        "Host-side blocked linear-algebra dispatches (k-panel dots, "
        "potrf/trsm panels, SUMMA launches) — the linalg.block_op "
        "fault chokepoint",
    "veles_linalg_matmuls_total":
        "Blocked matmuls completed (single-device panel loop or "
        "SUMMA over the 2D mesh)",
    "veles_linalg_factorizations_total":
        "Blocked Cholesky factorizations completed",
    "veles_linalg_solves_total":
        "Linear solves completed (cholesky_solve calls and CG "
        "workflow finishes)",
    "veles_linalg_iterations_total":
        "Conjugate-gradient iterations run (CGStep executions)",
    "veles_linalg_residual_checks_total":
        "verify_residual trusted-path checks performed (|b-Ax|/|b| "
        "against the stated bound)",
    "veles_linalg_residual_failures_total":
        "Residual checks FAILED — the solve raised instead of "
        "returning a silently-wrong answer (chaos corrupt lands here)",
    # watchtower plane (telemetry/timeseries.py + telemetry/
    # alerts.py): bench.py's gate asserts these read 0 in watch-off
    # runs — the sampler thread and rule engine must not exist at all
    # unless root.common.telemetry.watch.enabled
    "veles_watch_samples_total":
        "Metric time-series samples taken by the watchtower "
        "SeriesStore ring (one per sampler period)",
    "veles_watch_pulls_total":
        "Watchtower history pulls served over GET /metrics/history "
        "(router + serving APIs + web status)",
    "veles_alert_evals_total":
        "Alert rule-set evaluation sweeps run by the watchtower "
        "(one per sample)",
    "veles_alert_transitions_total":
        "Alert rule state transitions in either direction "
        "(ok -> firing and firing -> resolved)",
    "veles_alert_critical_unready_total":
        "Critical-severity alert firings that marked this process "
        "unready and dumped the flight-recorder black box",
    "veles_loadgen_alert_aborts_total":
        "Load-harness runs aborted at alert fire time "
        "(--abort-on-alert saw a firing watchtower rule and stopped "
        "offering load)",
}


#: canonical histogram names: HELP string + FIXED bucket upper bounds
#: (seconds). Same registration discipline as DESCRIPTIONS — every
#: ``observe("veles_*")`` call site must appear here with HELP and
#: bounds (scripts/check_counters.py fails CI otherwise). Fixed
#: buckets keep fleet aggregation exact: summing the same bounds
#: across N /metrics endpoints is lossless, which per-process
#: quantile sketches would not be.
HISTOGRAMS = {
    # request-plane serving SLOs (serving/scheduler.py Ticket
    # accounting): bench.py's gate asserts ZERO samples in
    # non-serving runs
    "veles_serving_queue_wait_seconds": {
        "help": "Seconds a serving request waited in the queue "
                "before admission (deadline-shed/expired requests "
                "record their full wait)",
        "buckets": (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
    },
    "veles_serving_ttft_seconds": {
        "help": "Time to first token: request enqueue to the first "
                "generated token (prefill output), per request",
        "buckets": (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                    0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
    },
    "veles_serving_tpot_seconds": {
        "help": "Time per output token after the first (decode "
                "steady-state), per retired request",
        "buckets": (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                    0.05, 0.1, 0.25, 0.5, 1.0),
    },
    "veles_serving_e2e_seconds": {
        "help": "End-to-end serving latency: request enqueue to the "
                "answered ticket, per retired request",
        "buckets": (0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0, 30.0, 60.0, 120.0),
    },
}

#: bounds for ad-hoc (unregistered) histogram names — they still
#: record, but check_counters.py fails CI on them, like counters
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
                   10.0, 60.0)

#: the bucket-derived quantiles metrics_text exposes as gauges
QUANTILE_GAUGES = ((0.5, "p50"), (0.9, "p90"), (0.99, "p99"))


def describe_histogram(name: str) -> str:
    entry = HISTOGRAMS.get(name)
    return entry["help"] if entry else "veles_tpu histogram"


def histogram_buckets(name: str) -> Tuple[float, ...]:
    entry = HISTOGRAMS.get(name)
    return tuple(entry["buckets"]) if entry else DEFAULT_BUCKETS


def histogram_quantile(bounds, counts, q: float) -> Optional[float]:
    """Prometheus ``histogram_quantile`` estimation from fixed
    buckets: ``counts[i]`` is the NON-cumulative count of bucket
    ``bounds[i]`` (``counts[-1]`` the +Inf overflow). Linear
    interpolation inside the winning bucket; values landing in the
    overflow bucket report the largest finite bound (the histogram
    cannot see past it). None when the histogram is empty — shared
    by the live registry and fleet aggregation so both surfaces
    answer 'what is p99' with the same arithmetic."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    for i, cnt in enumerate(counts):
        prev = cum
        cum += cnt
        if cum >= rank and cnt > 0:
            if i >= len(bounds):            # +Inf overflow bucket
                return float(bounds[-1]) if bounds else None
            lower = float(bounds[i - 1]) if i > 0 else 0.0
            upper = float(bounds[i])
            return lower + (upper - lower) * (rank - prev) / cnt
    return float(bounds[-1]) if bounds else None


class HistogramRegistry:
    """Thread-safe fixed-bucket histograms (the latency twin of
    :class:`CounterRegistry`): flat name → (bucket counts, sum).
    Entries appear on first ``observe`` — an idle process renders no
    histogram rows at all, so non-serving /metrics pages (and the
    bench gate's zero-leakage sections) stay exactly as before."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: name -> per-bucket counts, len(bounds) + 1 (+Inf overflow)
        self._counts: Dict[str, List[int]] = {}
        self._sums: Dict[str, float] = {}
        self._bounds: Dict[str, Tuple[float, ...]] = {}

    def observe(self, name: str, value: float) -> None:
        """Record one sample into ``name``'s fixed buckets."""
        value = float(value)
        with self._lock:
            counts = self._counts.get(name)
            if counts is None:
                bounds = histogram_buckets(name)
                self._bounds[name] = bounds
                counts = self._counts[name] = [0] * (len(bounds) + 1)
                self._sums[name] = 0.0
            counts[bisect.bisect_left(self._bounds[name], value)] += 1
            self._sums[name] += value

    def count(self, name: str) -> int:
        with self._lock:
            return sum(self._counts.get(name, ()))

    def sum(self, name: str) -> float:
        with self._lock:
            return self._sums.get(name, 0.0)

    def snapshot(self) -> Dict[str, Dict]:
        """{name: {bounds, counts, sum, count}} — one instant."""
        with self._lock:
            return {
                name: {"bounds": self._bounds[name],
                       "counts": tuple(counts),
                       "sum": self._sums[name],
                       "count": sum(counts)}
                for name, counts in self._counts.items()}

    def quantile(self, name: str, q: float) -> Optional[float]:
        """Bucket-interpolated quantile; None when no samples."""
        with self._lock:
            counts = self._counts.get(name)
            if counts is None:
                return None
            bounds, counts = self._bounds[name], tuple(counts)
        return histogram_quantile(bounds, counts, q)

    def reset(self) -> None:
        """Zero everything — tests and bench section boundaries only
        (same contract as :meth:`CounterRegistry.reset`)."""
        with self._lock:
            self._counts.clear()
            self._sums.clear()
            self._bounds.clear()

    def prometheus_text(self, snap: Optional[Dict] = None) -> str:
        """Prometheus histogram exposition: cumulative ``_bucket{le=}``
        series plus ``_sum``/``_count`` per recorded histogram."""
        snap = self.snapshot() if snap is None else snap
        lines = []
        for name in sorted(snap):
            h = snap[name]
            lines.append("# HELP %s %s"
                         % (name, describe_histogram(name)))
            lines.append("# TYPE %s histogram" % name)
            cum = 0
            for bound, cnt in zip(h["bounds"], h["counts"]):
                cum += cnt
                lines.append('%s_bucket{le="%s"} %d'
                             % (name, format(float(bound), "g"), cum))
            lines.append('%s_bucket{le="+Inf"} %d'
                         % (name, h["count"]))
            s = float(h["sum"])
            lines.append("%s_sum %s"
                         % (name, int(s) if s.is_integer() else
                            round(s, 9)))
            lines.append("%s_count %d" % (name, h["count"]))
        return "\n".join(lines) + "\n" if lines else ""


#: THE process-global histogram registry (mirrors ``counters``).
histograms = HistogramRegistry()


def observe(name: str, value: float) -> None:
    histograms.observe(name, value)


def describe_counter(name: str) -> str:
    return DESCRIPTIONS.get(name, "veles_tpu counter")


#: increment observers installed by the flight recorder
#: (telemetry/recorder.py): called as ``hook(name, value, new_total)``
#: AFTER the registry lock is released, exceptions swallowed — an
#: observer can never deadlock or take an instrumented call site down.
_inc_hooks = []


def add_inc_hook(fn) -> None:
    if fn not in _inc_hooks:
        _inc_hooks.append(fn)


class CounterRegistry:
    """Flat, thread-safe name → value map of monotonic counters."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, float] = {}

    def inc(self, name: str, value: float = 1) -> float:
        """Add ``value`` (default 1) to ``name``; returns the new total."""
        with self._lock:
            new = self._values.get(name, 0) + value
            self._values[name] = new
        for hook in _inc_hooks:
            try:
                hook(name, value, new)
            except Exception:       # noqa: BLE001 — observers only
                pass
        return new

    def get(self, name: str) -> float:
        with self._lock:
            return self._values.get(name, 0)

    def snapshot(self) -> Dict[str, float]:
        """Point-in-time copy of every counter."""
        with self._lock:
            return dict(self._values)

    def delta(self, before: Dict[str, float],
              names: Optional[tuple] = None) -> Dict[str, float]:
        """Per-counter growth since a :meth:`snapshot`; zero-growth
        counters are omitted so span records stay small."""
        now = self.snapshot()
        keys = names if names is not None else now.keys()
        out = {}
        for k in keys:
            d = now.get(k, 0) - before.get(k, 0)
            if d:
                out[k] = d
        return out

    def reset(self) -> None:
        """Zero everything — tests and bench section boundaries only
        (production counters are monotonic for the life of the
        process, as Prometheus scraping expects)."""
        with self._lock:
            self._values.clear()

    def prometheus_text(self) -> str:
        """Prometheus exposition format (text/plain; version 0.0.4).
        One snapshot renders the whole page — names and values from
        the same instant."""
        lines = []
        for name, val in sorted(self.snapshot().items()):
            lines.append("# HELP %s %s" % (name, describe_counter(name)))
            lines.append("# TYPE %s counter" % name)
            # integral counters print without a trailing .0 (scrapers
            # accept both; humans diff these files)
            lines.append("%s %s" % (
                name, int(val) if float(val).is_integer() else val))
        return "\n".join(lines) + "\n"


#: THE process-global registry every instrumented call site uses.
counters = CounterRegistry()


#: Content-Type every /metrics endpoint replies with
METRICS_CONTENT_TYPE = "text/plain; version=0.0.4"


def metrics_text(gauges: Optional[dict] = None) -> str:
    """The full /metrics page: the counter registry, the histogram
    registry (with bucket-derived p50/p90/p99 quantile gauges per
    recorded histogram), then the caller's service gauges — THE one
    renderer behind every /metrics endpoint (web_status, RESTfulAPI,
    GenerationAPI), so format changes happen in one place. ``gauges``:
    name → value (or (value, help) tuple). A caller gauge whose name
    shadows an already-rendered series is DROPPED and counted
    (``veles_metrics_name_collisions_total``) — duplicate metric
    names are invalid exposition and would break every scraper; the
    collision counter itself lands on the next scrape (this page's
    counter section is already snapshotted)."""
    text = counters.prometheus_text()
    taken = set(counters.snapshot())
    hsnap = histograms.snapshot()
    text += histograms.prometheus_text(hsnap)
    for name in sorted(hsnap):
        taken.update((name, name + "_bucket", name + "_sum",
                      name + "_count"))
        h = hsnap[name]
        if not h["count"]:
            continue
        for q, label in QUANTILE_GAUGES:
            value = histogram_quantile(h["bounds"], h["counts"], q)
            gname = "%s_%s" % (name, label)
            text += gauge_text(
                gname, round(value, 9),
                "Bucket-estimated %s of %s" % (label, name))
            taken.add(gname)
    for name, val in (gauges or {}).items():
        if name in taken:
            counters.inc("veles_metrics_name_collisions_total")
            continue
        help_text = None
        if isinstance(val, tuple):
            val, help_text = val
        text += gauge_text(name, val, help_text)
        taken.add(name)
    return text


def gauge_text(name: str, value, help_text: Optional[str] = None) -> str:
    """One Prometheus gauge in exposition format — the shared renderer
    for the ad-hoc service gauges every /metrics endpoint appends after
    :func:`prometheus_text` (web_status, RESTfulAPI, GenerationAPI)."""
    lines = []
    if help_text:
        lines.append("# HELP %s %s" % (name, help_text))
    lines.append("# TYPE %s gauge" % name)
    val = float(value)
    lines.append("%s %s" % (name, int(val) if val.is_integer() else val))
    return "\n".join(lines) + "\n"


def inc(name: str, value: float = 1) -> float:
    return counters.inc(name, value)


def snapshot() -> Dict[str, float]:
    return counters.snapshot()


def prometheus_text() -> str:
    return counters.prometheus_text()
