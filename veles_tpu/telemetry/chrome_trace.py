"""Span JSONL → Chrome ``trace_event`` JSON (Perfetto-viewable).

The export half of the span pipeline: ``veles-tpu trace export
run.jsonl trace.json`` converts the recorder's JSONL stream into the
Trace Event Format consumed by Perfetto / chrome://tracing —
complete ("X") events carrying each span's duration, thread and
counter deltas in ``args``, plus counter ("C") tracks for the
dispatch/byte counters so the timeline shows *accounting* next to
wall time. Format reference: the "Trace Event Format" spec (Google);
only the stable subset below is emitted.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List

from . import spans

#: trace_event phases this exporter emits (and the validator accepts)
PHASES = ("X", "C", "M")


def _lane_events(recs: List[Dict[str, Any]], pid: int, t0: float
                 ) -> List[Dict[str, Any]]:
    """One process lane's data events: every span record becomes a
    complete ("X") event on lane ``pid``, timestamps µs relative to
    ``t0`` (epoch seconds). Counter ("C") tracks plot RUNNING TOTALS:
    each span record carries the counter's delta over that span;
    Perfetto wants the cumulative series, so accumulate in record
    order (the recorder ring appends at span end — chronological in
    end time). Top-level spans only: a nested span's delta is already
    inside its ancestors' deltas, so summing every depth would
    multiply-count."""
    events: List[Dict[str, Any]] = []
    running: Dict[str, float] = {}
    for rec in recs:
        args = {k: v for k, v in rec.items()
                if k not in ("name", "ts", "dur", "tid", "sid",
                             "seq", "parent", "depth")}
        ev = {
            "name": str(rec["name"]),
            "cat": str(rec.get("cat", "veles")),
            "ph": "X",
            "ts": (float(rec["ts"]) - t0) * 1e6,
            "dur": max(float(rec.get("dur", 0.0)), 0.0) * 1e6,
            "pid": pid,
            "tid": int(rec.get("tid", 0)),
            "args": args,
        }
        events.append(ev)
        if rec.get("depth", 0) != 0:
            continue
        for key, val in (rec.get("counters") or {}).items():
            running[key] = running.get(key, 0) + val
            events.append({
                "name": key, "ph": "C", "pid": pid,
                "ts": (float(rec["ts"]) - t0 + float(
                    rec.get("dur", 0.0))) * 1e6,
                "args": {key: running[key]},
            })
    return events


def to_trace_events(records: Iterable[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Span records (spans.py dicts) → trace_event list. Timestamps
    become microseconds relative to the earliest span so Perfetto's
    timeline starts at ~0 instead of the unix epoch."""
    recs = [r for r in records if "ts" in r and "name" in r]
    if not recs:
        return []
    t0 = min(float(r["ts"]) for r in recs)
    pid = os.getpid()
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": "veles_tpu"},
    }]
    events += _lane_events(recs, pid, t0)
    return events


def fleet_trace_events(processes: List[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """Multi-process trace assembly: ``processes`` is a list of
    ``{"name": lane label, "records": [span records]}`` whose
    timestamps are ALREADY on one common clock (the fleet assembler
    in telemetry/fleet.py subtracts each process's estimated offset
    first). Each process gets its own Perfetto lane (pid 1..N with a
    ``process_name`` metadata row — real pids may collide across
    hosts, so lanes are reindexed), timestamps relative to the
    earliest span anywhere, so the router's route.* spans and every
    replica's request spans line up on one timeline."""
    all_ts = [float(r["ts"]) for p in processes
              for r in p.get("records", ())
              if "ts" in r and "name" in r]
    if not all_ts:
        return []
    t0 = min(all_ts)
    events: List[Dict[str, Any]] = []
    for lane, proc in enumerate(processes, start=1):
        recs = [r for r in proc.get("records", ())
                if "ts" in r and "name" in r]
        if not recs:
            continue
        events.append({
            "name": "process_name", "ph": "M", "pid": lane, "tid": 0,
            "args": {"name": str(proc.get("name") or
                                 "process %d" % lane)},
        })
        events += _lane_events(recs, lane, t0)
    return events


def export(jsonl_path: str, out_path: str,
           request_id: str = None) -> int:
    """Read span JSONL, write a Chrome trace JSON; returns the number
    of spans exported. ``request_id`` keeps only the spans tagged
    with that serving request's id (the per-request lifecycle spans
    the Ticket emits plus any engine span carrying the tag) — one
    request's timeline without hand-grepping the JSONL. Raises
    ValueError when the input has no spans (an empty trace silently
    loading as a blank Perfetto page helps nobody)."""
    records = spans.read_jsonl(jsonl_path)
    if request_id is not None:
        # the shared correlation predicate: request_id OR trace_id —
        # one flag serves both "this replica's request" and "this
        # fleet trace's local spans", agreeing with blackbox inspect
        records = [r for r in records
                   if spans.matches_request(r, request_id)]
        if not records:
            raise ValueError(
                "no span records tagged request_id=%s in %s"
                % (request_id, jsonl_path))
    if not records:
        raise ValueError("no span records in %s" % jsonl_path)
    doc = {"traceEvents": to_trace_events(records),
           "displayTimeUnit": "ms"}
    errors = validate(doc)
    if errors:        # exporter bug, not user input — fail loudly
        raise ValueError("invalid trace produced: %s" % errors[:3])
    with open(out_path, "w") as f:
        json.dump(doc, f)
    return len(records)


def validate(doc: Any) -> List[str]:
    """Schema check against the trace_event subset this module emits
    (what the tests gate on): returns a list of violations, empty when
    the document is loadable by Perfetto."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return ["top level must be a JSON object"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(events):
        where = "traceEvents[%d]" % i
        if not isinstance(ev, dict):
            errors.append("%s: not an object" % where)
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            errors.append("%s: missing name" % where)
        ph = ev.get("ph")
        if ph not in PHASES:
            errors.append("%s: bad phase %r" % (where, ph))
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                errors.append("%s: bad ts %r" % (where, ts))
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append("%s: bad dur %r" % (where, dur))
        if ph == "C" and not isinstance(ev.get("args"), dict):
            errors.append("%s: counter event needs args" % where)
        if not isinstance(ev.get("pid", 0), int):
            errors.append("%s: pid must be int" % where)
        if not isinstance(ev.get("tid", 0), int):
            errors.append("%s: tid must be int" % where)
    return errors
