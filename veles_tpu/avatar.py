"""Avatar: device-side clone of another unit's output attributes.

Equivalent of the reference's veles/avatar.py:22 (used to decouple a
consumer from a producer whose buffers are overwritten each minibatch)."""

from __future__ import annotations

from typing import Dict

import numpy

from .accelerated import AcceleratedUnit
from .memory import Array


class Avatar(AcceleratedUnit):
    MAPPING = "avatar"
    hide_from_registry = False

    def __init__(self, workflow, source=None, attrs=("output",), **kwargs):
        super().__init__(workflow, **kwargs)
        self.view_group = "WORKER"
        self.source = source
        self.attrs = tuple(attrs)
        self.clones: Dict[str, Array] = {}
        self.demand("source")

    def initialize(self, device=None, **kwargs):
        res = super().initialize(device=device, **kwargs)
        if res:
            return res
        for a in self.attrs:
            src = getattr(self.source, a, None)
            if not (isinstance(src, Array) and src):
                # producer not allocated yet: use the re-queue protocol
                return True
        for a in self.attrs:
            src = getattr(self.source, a)
            clone = Array(numpy.array(src.map_read()),
                          name="%s.%s" % (self.name, a))
            self.clones[a] = clone
            setattr(self, a, clone)
        return None

    def xla_run(self) -> None:
        for a, clone in self.clones.items():
            src = getattr(self.source, a)
            clone.assign_devmem(src.device_view() + 0)  # device-side copy

    def numpy_run(self) -> None:
        for a, clone in self.clones.items():
            src = getattr(self.source, a)
            clone.reset(numpy.array(src.map_read()))
