"""Command-line surface.

Equivalent of the reference's veles/cmdline.py:61-278 (the veles(1) arg
set) collapsed to one explicit parser — the reference's metaclass-
distributed `init_parser` registry existed to merge flags from dozens of
optional units; here the surface is small enough to state in one place,
and unit-specific knobs ride the config tree (root.x.y=z overrides).
"""

from __future__ import annotations

import argparse


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="veles_tpu",
        description="TPU-native dataflow deep-learning framework "
                    "(rebuild of Samsung VELES capabilities)")
    p.add_argument("model", help="workflow .py file (defines "
                   "build_workflow() or run(load, main))")
    p.add_argument("config", nargs="?", default=None,
                   help="optional config .py/.json applied to root")
    p.add_argument("config_list", nargs="*", default=[],
                   help="inline overrides root.x.y=value")
    p.add_argument("-b", "--backend", default=None,
                   help="auto | tpu | cpu | xla | numpy")
    p.add_argument("--mesh", default=None,
                   help="mesh spec, e.g. data=8 or data=4,tensor=2")
    p.add_argument("-s", "--snapshot", default=None,
                   help="resume from snapshot file")
    p.add_argument("--snapshot-dir", default=None)
    p.add_argument("--random-seed", type=int, default=None)
    p.add_argument("--test", action="store_true",
                   help="run in test (inference) mode")
    p.add_argument("--result-file", default=None,
                   help="write gathered metrics JSON here")
    p.add_argument("--workflow-graph", default=None,
                   help="write the control graph DOT file and exit "
                        "after initialize")
    p.add_argument("--dump-config", action="store_true")
    p.add_argument("--dry-run", action="store_true",
                   help="build + initialize only")
    p.add_argument("--timings", action="store_true",
                   help="print per-unit timing table at exit")
    p.add_argument("--trace-file", default=None,
                   help="append event spans as JSON lines here")
    # model-health observability (veles_tpu/telemetry/tensormon.py +
    # recorder.py, docs/observability.md "Model health")
    p.add_argument("--tensormon", action="store_true",
                   help="in-graph tensor taps on the fused train step "
                        "(grad norms, update ratios, NaN/Inf counts, "
                        "activation saturation) — accumulated on "
                        "device, drained with the epoch metrics, "
                        "served as veles_model_* gauges on /metrics")
    p.add_argument("--nan-policy", default=None,
                   choices=("warn", "halt", "snapshot_and_halt"),
                   help="NaN sentinel policy (implies --tensormon): "
                        "warn logs and counts; halt marks health "
                        "unready and raises ModelHealthError; "
                        "snapshot_and_halt first commits a forensic "
                        "snapshot through the checkpoint chain")
    p.add_argument("--blackbox", action="store_true",
                   help="arm flight-recorder autodump: unhandled "
                        "workflow crashes, watchdog trips and SIGTERM "
                        "write blackbox-<ts>.jsonl next to the "
                        "snapshots (read with `veles-tpu blackbox "
                        "inspect`)")
    p.add_argument("--force-numpy", action="store_true")
    p.add_argument("--mixed-precision", action="store_true",
                   help="bf16 activation/param storage in the fused "
                        "step (f32 masters + accumulation); the HBM "
                        "lever for image-scale nets")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("--debug", default="", metavar="ClassA,ClassB",
                   help="enable DEBUG for specific unit/class loggers "
                        "('all' raises the root logger)")
    # observability services (reference graphics/web-status,
    # veles/graphics_server.py:73, veles/launcher.py:852-885)
    p.add_argument("--graphics", action="store_true",
                   help="live plots: spawn the renderer subprocess")
    p.add_argument("--plots-dir", default=None,
                   help="where the renderer writes plot PNGs")
    p.add_argument("--status-url", default=None,
                   help="web-status server to POST beacons to "
                        "(see python -m veles_tpu.web_status)")
    p.add_argument("--status-interval", type=float, default=10.0,
                   help="beacon period in seconds")
    p.add_argument("--serve-generate", type=int, default=None,
                   metavar="PORT",
                   help="after initialize (and optional --snapshot "
                        "resume), serve the workflow's generation stack "
                        "over HTTP instead of training (GenerationAPI: "
                        "greedy/sample/beam, micro-batched; + "
                        "speculative when --serve-draft is given); "
                        "0 picks an ephemeral port; Ctrl-C stops, "
                        "SIGTERM drains gracefully (/readyz flips to "
                        "draining, in-flight tickets finish, exit 0)")
    p.add_argument("--serve-drain-grace", type=float, default=None,
                   metavar="SEC",
                   help="graceful-drain budget for SIGTERM / POST "
                        "/generate/drain: seconds to wait for "
                        "in-flight requests before aborting the "
                        "stragglers 503 "
                        "(root.common.serving.drain_grace, default "
                        "30)")
    p.add_argument("--serve-drain-handoff", default=None,
                   choices=("on", "off"),
                   help="drain-by-handoff (default on): a draining "
                        "replica settles each in-flight ticket 503 + "
                        "its emitted-token resume progress at the "
                        "next step boundary — drain latency is one "
                        "handoff, not the longest generation; 'off' "
                        "restores the wait-out-the-grace drain "
                        "(root.common.serving.drain_handoff)")
    p.add_argument("--serve-engine", default=None,
                   choices=("continuous", "recurrent", "window"),
                   help="decode plane under --serve-generate: "
                        "'continuous' (default) runs the slot-pool "
                        "continuous-batching engine (greedy/sample "
                        "requests share one fixed-shape decode step, "
                        "admitted/retired per iteration; recurrent "
                        "LM stacks auto-route to the O(1)-state "
                        "pool); 'recurrent' pins the O(1)-state pool "
                        "(fixed per-slot state, pageless admission); "
                        "'window' keeps the legacy shape-keyed "
                        "micro-batcher")
    p.add_argument("--serve-slots", type=int, default=None, metavar="N",
                   help="KV-cache slot rows of the continuous-batching "
                        "pool (root.common.serving.max_slots)")
    p.add_argument("--serve-buckets", default=None, metavar="L1,L2,...",
                   help="prefill pad-to lengths; the serving jit cache "
                        "is bounded by len(buckets)+1 programs "
                        "(root.common.serving.buckets)")
    p.add_argument("--serve-max-context", type=int, default=None,
                   metavar="T",
                   help="per-slot KV capacity; requests need "
                        "len(prompt)+n_new <= T to ride the slot pool "
                        "(root.common.serving.max_context)")
    p.add_argument("--serve-page-size", type=int, default=None,
                   metavar="P",
                   help="positions per KV-cache page (a multiple of "
                        "the decode block); pool HBM is pages x P, "
                        "not slots x max-context "
                        "(root.common.serving.page_size)")
    p.add_argument("--serve-pages", type=int, default=None, metavar="N",
                   help="usable pages of the paged KV pool; default "
                        "is dense-equivalent capacity (every slot can "
                        "hold max-context) — SHRINK it to trade worst-"
                        "case context reservation for more concurrent "
                        "slots at the same HBM "
                        "(root.common.serving.pages)")
    p.add_argument("--serve-spec-gamma", type=int, default=None,
                   metavar="G",
                   help="draft tokens per on-device speculation round; "
                        "the pool serves mode=speculative requests "
                        "whose gamma matches this fixed shape "
                        "(root.common.serving.spec_gamma)")
    p.add_argument("--serve-beam-width", type=int, default=None,
                   metavar="W",
                   help="hypothesis rows per pooled beam request; the "
                        "pool serves mode=beam requests whose width "
                        "matches this fixed shape "
                        "(root.common.serving.beam_width)")
    p.add_argument("--serve-prefix-cache", default=None,
                   choices=("on", "off"),
                   help="prefix-sharing paged KV cache: a radix index "
                        "over page-size token blocks lets admissions "
                        "adopt a shared prompt prefix's pages "
                        "read-only and prefill only the suffix "
                        "(root.common.serving.prefix_cache; "
                        "greedy/sample on the float pool; answers "
                        "bit-identical on or off)")
    p.add_argument("--serve-prefill-chunk", type=int, default=None,
                   metavar="C",
                   help="prefill admissions in C-token chunks "
                        "co-scheduled with the decode tick instead of "
                        "one monolithic bucketed pass — bounds the "
                        "per-tick decode stall a long admission "
                        "causes (root.common.serving.prefill_chunk; "
                        "0 = monolithic)")
    p.add_argument("--serve-tp", type=int, default=None, metavar="N",
                   help="tensor-parallel serving over a 1D (\"model\",)"
                        " mesh slice: N chips serve as ONE logical "
                        "replica — attention heads and K/V pages shard "
                        "over the head axis, FC/embedding weights "
                        "column/row-parallel, while page tables and "
                        "the prefix cache stay replicated host data "
                        "(root.common.serving.tp; 1 = solo; answers "
                        "id-exact vs the unsharded engine; float "
                        "plane only)")
    p.add_argument("--serve-state-cache", default=None,
                   choices=("on", "off"),
                   help="state-checkpoint prefix cache of the O(1)-"
                        "state lane: prefill snapshots the recurrent "
                        "state every page-size tokens into a radix "
                        "index; a same-prefix admission adopts the "
                        "deepest snapshot copy-on-write and scans "
                        "only the suffix "
                        "(root.common.serving.state_cache; answers "
                        "bit-identical on or off)")
    p.add_argument("--serve-stream", default=None,
                   choices=("on", "off"),
                   help="honor stream=true requests with SSE "
                        "token-streaming responses (default on; "
                        "root.common.serving.stream — off answers "
                        "them buffered)")
    p.add_argument("--serve-qos", default=None,
                   choices=("on", "off"),
                   help="QoS classes on the serving plane (default "
                        "off; root.common.serving.qos): requests "
                        "carry priority=interactive|batch, admission "
                        "promotes interactive past queued batch, and "
                        "under slot pressure the engine preempts "
                        "batch rows at a step boundary — they requeue "
                        "with resume progress and finish bit-"
                        "identical (docs/services.md 'Overload & "
                        "QoS')")
    p.add_argument("--router-qos", default=None,
                   choices=("on", "off"),
                   help="adaptive admission at the fleet router "
                        "(default off; root.common.router.qos): AIMD "
                        "controller keyed on the TTFT p99 vs "
                        "--router-slo-ttft-ms throttles batch first, "
                        "a retry token bucket caps failover "
                        "amplification, and a hysteresis-guarded "
                        "brownout ladder degrades before shedding")
    p.add_argument("--router-slo-ttft-ms", type=float, default=None,
                   metavar="MS",
                   help="TTFT p99 SLO the router's AIMD controller "
                        "defends (root.common.router.slo_ttft_ms, "
                        "default 500)")
    p.add_argument("--serve-artifact", default=None, metavar="DIR",
                   help="AOT serve-artifact package (from `veles-tpu "
                        "export serve-artifact`): the continuous "
                        "engine loads its pre-exported prefill/decode "
                        "programs at initialize — zero jit compiles "
                        "on the serving path "
                        "(root.common.serving.artifact); a corrupt or "
                        "mismatched artifact falls back to live jit "
                        "with a counted warning")
    # quantization subsystem (veles_tpu/quant/, docs/services.md
    # "Quantized serving")
    p.add_argument("--quant-weights", action="store_true",
                   help="serve with per-channel symmetric int8 decode "
                        "matmul weights, dequantized on read inside "
                        "the serving programs "
                        "(root.common.quant.weights)")
    p.add_argument("--quant-kv", action="store_true",
                   help="store the serving KV-cache slot pool int8 "
                        "with per-slot scales — half the pool HBM at "
                        "the same --serve-slots "
                        "(root.common.quant.kv)")
    p.add_argument("--serve-draft", default=None, metavar="MODEL_PY",
                   help="draft model .py for mode=speculative under "
                        "--serve-generate (its build_workflow() is "
                        "initialized on the same backend)")
    p.add_argument("--serve-draft-snapshot", default=None,
                   help="snapshot to restore the --serve-draft model "
                        "from before serving")
    # multi-host (replaces master/slave -l/-m, veles/launcher.py:193-267)
    p.add_argument("--coordinator", default=None,
                   help="host:port of the jax distributed coordinator")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--slave-death-probability", type=float, default=0.0,
                   help="fault injection for recovery testing")
    p.add_argument("--elastic", action="store_true",
                   help="preemption-tolerant training: on detected "
                        "host loss (heartbeat lapse, join failure, or "
                        "an injected distributed.host_loss fault) the "
                        "run declares a new generation and resumes "
                        "from the newest valid checkpoint instead of "
                        "dying; multi-process survivors exit 43 for "
                        "the respawn plane "
                        "(root.common.resilience.elastic.{enabled,"
                        "min_hosts,generation_timeout,"
                        "max_generations}; docs/resilience.md "
                        "'Elastic training')")
    # overlap engine (veles_tpu/overlap/, docs/overlap.md)
    p.add_argument("--overlap", action="store_true",
                   help="overlap host I/O with device compute: "
                        "side-effect units (plotters/publishers/image "
                        "savers) run on an async side-plane, "
                        "snapshots commit+fsync on a checkpoint lane, "
                        "loaders prefetch the next batch. Results are "
                        "bit-identical with or without it")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   metavar="N",
                   help="stage up to N minibatches ahead on a "
                        "background thread (loader data plane; "
                        "implies nothing about --overlap — the two "
                        "compose)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax/XPlane profiler trace of the run "
                        "into this directory (view with tensorboard or "
                        "xprof; the TPU-era --timings deep dive)")
    p.add_argument("--job-timeout", type=float, default=0.0,
                   help="floor (seconds) for the per-dispatch hang "
                        "watchdog; 0 keeps only the mean+3σ adaptive "
                        "threshold (reference: veles/server.py:619-635)")
    # meta-learning (reference --optimize / --ensemble-train/-test,
    # veles/__main__.py:334-361,724-732)
    p.add_argument("--optimize", default=None, metavar="SIZE[:GENS]",
                   help="GA hyper-parameter search over Range() markers "
                        "in the config tree")
    p.add_argument("--optimize-subprocess", action="store_true",
                   help="evaluate each candidate in an isolated "
                        "subprocess instead of inline")
    p.add_argument("--optimize-workers", type=int, default=1, metavar="W",
                   help="evaluate up to W candidates concurrently via "
                        "the trial scheduler (implies subprocess "
                        "isolation; each worker slot gets its own "
                        "device placement)")
    p.add_argument("--trial-devices", type=int, default=0, metavar="D",
                   help="place each --optimize/--ensemble worker trial "
                        "on its own disjoint D-chip slice "
                        "(mesh_slice_placement via TPU_VISIBLE_CHIPS); "
                        "0 = private single CPU device per slot")
    p.add_argument("--optimize-crossover", default="uniform",
                   choices=("uniform", "arithmetic", "geometric",
                            "pointed"),
                   help="GA crossover operator")
    p.add_argument("--optimize-selection", default="roulette",
                   choices=("roulette", "random", "tournament"),
                   help="GA parent-selection procedure")
    p.add_argument("--ensemble-train", default=None, metavar="N[:RATIO]",
                   help="train N ensemble members, each on RATIO of the "
                        "train set (default 1.0)")
    p.add_argument("--ensemble-test", default=None, metavar="MANIFEST",
                   help="soft-vote evaluate a trained ensemble manifest")
    p.add_argument("--ensemble-file", default="ensemble.json",
                   help="where --ensemble-train writes its manifest")
    p.add_argument("--ensemble-workers", type=int, default=1, metavar="W",
                   help="train up to W ensemble members concurrently via "
                        "the trial scheduler (members become CLI "
                        "subprocesses)")
    p.add_argument("--ensemble-member", type=int, default=None,
                   metavar="I",
                   help="(internal) train only member I of the "
                        "--ensemble-train set and write its manifest "
                        "entry to --result-file — the unit a parallel "
                        "ensemble worker executes")
    return p


def parse_args(parser: argparse.ArgumentParser, argv):
    """Parse accepting SPLIT positional groups: real invocations (and
    the child commands the trial scheduler builds) routinely interleave
    ``root.x.y=value`` overrides with optionals —
    ``model.py --optimize 3:1 root.lr=0.1 --backend cpu`` — which
    plain ``parse_args`` rejects ("unrecognized arguments"): argparse
    commits the whole positional pattern to the FIRST positional run
    it meets. ``parse_intermixed_args`` (two-pass: optionals first,
    then the collected positionals as one run) accepts them; the
    fallback covers parser shapes intermixed parsing refuses (it
    forbids some nargs forms), where the classic behavior is kept."""
    try:
        return parser.parse_intermixed_args(argv)
    except TypeError:
        return parser.parse_args(argv)


def split_child_argv(extra):
    """Partition forwarded argv into (positional config overrides,
    flag arguments). Child commands built for the trial scheduler must
    group ALL positionals (``root.x=y`` overrides, config files)
    directly after the model path — argparse cannot consume a second
    positional group appearing after optionals like ``--backend cpu``.
    """
    positionals, flags = [], []
    it = iter(extra)
    for item in it:
        if item.startswith("-"):
            flags.append(item)
            # flags used by forwarded child argv are all value-taking
            # (--backend X, --random-seed N); keep the pair together
            if "=" not in item:
                try:
                    flags.append(next(it))
                except StopIteration:
                    pass
        else:
            positionals.append(item)
    return positionals, flags


def parse_mesh(spec: str):
    """'data=4,tensor=2' → {'data': 4, 'tensor': 2}."""
    out = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        out[name.strip()] = int(size)
    return out


def apply_config_overrides(root, items):
    """Inline ``root.x.y=value`` overrides (reference --config-list,
    veles/__main__.py:474-481)."""
    import json
    for item in items:
        path, _, value = item.partition("=")
        if not _:
            raise ValueError("override %r is not of form root.x.y=value"
                             % item)
        parts = path.split(".")
        if parts[0] == "root":
            parts = parts[1:]
        node = root
        for part in parts[:-1]:
            node = getattr(node, part)
        try:
            value = json.loads(value)
        except ValueError:
            pass
        setattr(node, parts[-1], value)
