"""Import a user workflow .py file as a module.

Equivalent of the reference's veles/import_file.py:1-80
(import_file_as_module / as_package, used by Main._load_model,
veles/__main__.py:396-424)."""

from __future__ import annotations

import importlib.util
import os
import sys
from types import ModuleType


def import_file_as_module(path: str, name: str = None) -> ModuleType:
    path = os.path.abspath(path)
    if name is None:
        # namespaced key: a model file named json.py/numpy.py must not
        # clobber the real library in sys.modules
        name = "veles_model_" + os.path.splitext(
            os.path.basename(path))[0]
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise ImportError("cannot import %s" % path)
    module = importlib.util.module_from_spec(spec)
    sys.modules[name] = module
    # the model file's siblings (shared loaders etc.) become importable
    sys.path.insert(0, os.path.dirname(path))
    try:
        spec.loader.exec_module(module)
    except BaseException:
        sys.modules.pop(name, None)  # no half-initialized cache entry
        raise
    finally:
        sys.path.pop(0)
    return module
