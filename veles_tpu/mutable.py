"""Shared mutable booleans and cross-object attribute aliasing.

Equivalent of the reference's veles/mutable.py:44-357. ``Bool`` is a mutable
flag object shared by reference between units: gate expressions like
``~decision.complete & loader.epoch_ended`` build derived Bools that re-read
their operands at evaluation time. ``LinkableAttribute`` makes ``a.attr`` a
live pointer to ``b.attr`` (reference ``link_attrs``).

Unlike the reference (which composed pickled lambda expressions,
veles/mutable.py:163-190), derived Bools here store an operator tree of plain
objects, so they pickle/deepcopy naturally — important for checkpointing.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple


class Bool:
    """Mutable shared boolean with lazy operator algebra
    (reference: veles/mutable.py:44)."""

    __slots__ = ("_value", "_op", "_operands", "on_true")

    def __init__(self, value: bool = False) -> None:
        self._value = bool(value)
        self._op: Optional[str] = None
        self._operands: Tuple["Bool", ...] = ()
        #: optional callback fired by ``<<=`` when the flag becomes True
        self.on_true: Optional[Callable[[], None]] = None

    @classmethod
    def _derived(cls, op: str, *operands: "Bool") -> "Bool":
        b = cls()
        b._op = op
        b._operands = operands
        return b

    # -- evaluation ---------------------------------------------------------
    def __bool__(self) -> bool:
        if self._op is None:
            return self._value
        vals = [bool(o) for o in self._operands]
        if self._op == "not":
            return not vals[0]
        if self._op == "and":
            return all(vals)
        if self._op == "or":
            return any(vals)
        if self._op == "xor":
            return vals[0] != vals[1]
        raise AssertionError(self._op)

    # -- mutation -----------------------------------------------------------
    def __ilshift__(self, value: Any) -> "Bool":
        """``flag <<= True`` — in-place assignment that preserves identity so
        every holder of the reference observes the change
        (reference: veles/mutable.py:117-131)."""
        if self._op is not None:
            raise ValueError("cannot assign to a derived Bool expression")
        self._value = bool(value)
        if self._value and self.on_true is not None:
            self.on_true()
        return self

    # -- algebra ------------------------------------------------------------
    def __invert__(self) -> "Bool":
        return Bool._derived("not", self)

    def __and__(self, other: "Bool") -> "Bool":
        return Bool._derived("and", self, _coerce(other))

    def __or__(self, other: "Bool") -> "Bool":
        return Bool._derived("or", self, _coerce(other))

    def __xor__(self, other: "Bool") -> "Bool":
        return Bool._derived("xor", self, _coerce(other))

    __rand__ = __and__
    __ror__ = __or__
    __rxor__ = __xor__

    def __repr__(self) -> str:
        if self._op is None:
            return "<Bool %s at 0x%x>" % (self._value, id(self))
        return "<Bool %s(%s)>" % (self._op, ", ".join(map(repr,
                                                          self._operands)))


def _coerce(v: Any) -> Bool:
    return v if isinstance(v, Bool) else Bool(bool(v))


_MISSING = object()


class LinkableAttribute:
    """Descriptor making ``owner.attr`` an alias of ``(target, attr)``
    (reference: veles/mutable.py:219-353). Installed on the *class* lazily;
    per-instance pointers live in ``instance.__linked__``. Any pre-existing
    class-level default is preserved for unlinked sibling instances."""

    def __init__(self, name: str, default: Any = _MISSING) -> None:
        self.name = name
        self.default = default

    def __get__(self, obj: Any, objtype: Any = None) -> Any:
        if obj is None:
            return self
        links = obj.__dict__.get("__linked__", {})
        if self.name in links:
            target, attr = links[self.name]
            return getattr(target, attr)
        # unlinked instance of a class that has linked instances elsewhere
        if self.name in obj.__dict__:
            return obj.__dict__[self.name]
        if self.default is not _MISSING:
            return self.default
        raise AttributeError(self.name)

    def __set__(self, obj: Any, value: Any) -> None:
        links = obj.__dict__.setdefault("__linked__", {})
        if self.name in links:
            target, attr = links[self.name]
            setattr(target, attr, value)
        else:
            # direct assignment before linking: behave like a plain attr
            obj.__dict__[self.name] = value

    @staticmethod
    def link(dst: Any, dst_attr: str, src: Any, src_attr: str,
             two_way: bool = False) -> None:
        """Make ``dst.dst_attr`` an alias of ``src.src_attr``
        (reference: mutable.link, veles/mutable.py:353). Since the alias is
        a live pointer, both reads AND writes through ``dst`` already reach
        ``src`` — the reference's ``two_way`` mode (assignment direction)
        is subsumed and accepted as a no-op for API parity; a reverse
        pointer would create an unreadable cycle."""
        cls = type(dst)
        desc = cls.__dict__.get(dst_attr)
        if not isinstance(desc, LinkableAttribute):
            # preserve an inherited/class-level default for siblings
            prev = getattr(cls, dst_attr, _MISSING)
            if isinstance(prev, LinkableAttribute):
                prev = _MISSING
            setattr(cls, dst_attr, LinkableAttribute(dst_attr, prev))
        dst.__dict__.pop(dst_attr, None)  # shadow removal
        links = dst.__dict__.setdefault("__linked__", {})
        links[dst_attr] = (src, src_attr)


    @staticmethod
    def unlink(obj: Any, attr: str) -> None:
        """Remove a pointer: the attribute keeps its current value as plain
        instance storage and stops tracking the link source."""
        links = obj.__dict__.get("__linked__", {})
        if attr in links:
            value = getattr(obj, attr)
            del links[attr]
            obj.__dict__[attr] = value


def link(dst: Any, dst_attr: str, src: Any, src_attr: str = None,
         two_way: bool = False) -> None:
    LinkableAttribute.link(dst, dst_attr, src, src_attr or dst_attr, two_way)
