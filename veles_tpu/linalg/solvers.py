"""Iterative solvers as Workflow graphs of Units.

Conjugate gradient is expressed on the SAME dataflow engine every
training workflow runs on — ``Repeater`` loop head, a step unit, a
decision unit gating the back-edge vs the EndPoint — so the control
plane (gates, heartbeats, spans, flight recorder, side-plane) applies
to a linear solve exactly as to an SGD loop. That is the point of this
family: the reference VELES was a general dataflow platform, and this
is its first non-NN workload here (ROADMAP item 5).

Residual-norm telemetry is per iteration: ``CGStep`` appends to the
state's ``residual_history``, stamps a ``linalg.cg_iteration`` span
and counts ``veles_linalg_iterations_total``. When the workflow
finishes *claiming convergence*, :class:`CGWorkflow` re-verifies the
answer through ``blocked.verify_residual`` (the trusted dense path,
outside the faultable block dispatch) and raises instead of returning
a silently-wrong x — corrupt-block chaos lands here.

The 2-level multigrid V-cycle (:class:`TwoLevelPoisson`) is the
stretch preconditioner: damped-Jacobi pre/post smoothing around a
Galerkin coarse-grid correction whose coarse operator is factored ONCE
with ``blocked_cholesky`` — the direct and iterative halves of the
family composed. Plug it into :func:`build_cg_workflow` via
``preconditioner=`` for PCG.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy

from ..error import VelesError
from ..mutable import Bool
from ..plumbing import Repeater
from ..telemetry.counters import inc
from ..telemetry.spans import span
from ..units import Unit
from ..workflow import Workflow
from .blocked import (DEFAULT_BLOCK, LinalgError, blocked_cholesky,
                      blocked_matmul, blocked_triangular_solve,
                      residual_tolerance, verify_residual)


def _jnp():
    import jax.numpy as jnp
    return jnp


class CGState:
    """The solve's mutable state, shared by the CG units through
    ``link_attrs`` (one object, no copies across the loop)."""

    def __init__(self):
        self.x = None
        self.r = None
        self.p = None
        self.z = None
        self.rz = 0.0
        self.bnorm = 1.0
        self.iteration = 0
        self.residual_history = []
        self.converged = False
        self.true_residual = None

    @property
    def residual(self) -> float:
        return (self.residual_history[-1] if self.residual_history
                else float("inf"))


class CGSetup(Unit):
    """Prepares the Krylov state: r₀ = b − A x₀, first (preconditioned)
    direction, residual norm baseline. Re-running the workflow re-seeds
    the state, so a solve is repeatable."""

    MAPPING = "cg_setup"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "CGSetup")
        super().__init__(workflow, **kwargs)
        self.operator: Optional[Callable] = None   # matvec callable
        self.rhs = None
        self.x0 = None
        self.preconditioner: Optional[Callable] = None
        self.state = CGState()
        self.demand("operator", "rhs")

    def run(self):
        jnp = _jnp()
        st = self.state
        b = jnp.asarray(self.rhs)
        st.x = (jnp.zeros_like(b) if self.x0 is None
                else jnp.asarray(self.x0))
        st.r = b - self.operator(st.x)
        st.z = (self.preconditioner(st.r) if self.preconditioner
                else st.r)
        st.p = st.z
        st.rz = float(st.r @ st.z)
        st.bnorm = float(jnp.linalg.norm(b)) or 1.0
        st.iteration = 0
        st.residual_history = [
            float(jnp.linalg.norm(st.r)) / st.bnorm]
        st.converged = False
        st.true_residual = None


class CGStep(Unit):
    """One conjugate-gradient iteration over the linked
    :class:`CGState` — the loop body between Repeater and decision.
    Appends the recurrence residual to ``residual_history`` and stamps
    per-iteration telemetry (``linalg.cg_iteration`` span,
    ``veles_linalg_iterations_total``)."""

    MAPPING = "cg_step"

    def __init__(self, workflow, **kwargs):
        kwargs.setdefault("name", "CGStep")
        super().__init__(workflow, **kwargs)
        self.operator: Optional[Callable] = None
        self.preconditioner: Optional[Callable] = None
        self.state: Optional[CGState] = None
        self.demand("operator", "state")

    def run(self):
        jnp = _jnp()
        st = self.state
        q = self.operator(st.p)
        pq = float(st.p @ q)
        if pq <= 0:
            raise LinalgError(
                "cg: direction curvature p·Ap = %.3e <= 0 — operator "
                "is not SPD (or a corrupt block op broke it)" % pq)
        alpha = st.rz / pq
        st.x = st.x + alpha * st.p
        st.r = st.r - alpha * q
        st.z = (self.preconditioner(st.r) if self.preconditioner
                else st.r)
        rz_new = float(st.r @ st.z)
        beta = rz_new / st.rz if st.rz else 0.0
        st.p = st.z + beta * st.p
        st.rz = rz_new
        st.iteration += 1
        resid = float(jnp.linalg.norm(st.r)) / st.bnorm
        st.residual_history.append(resid)
        inc("veles_linalg_iterations_total")
        with span("linalg.cg_iteration", iteration=st.iteration,
                  resid=resid):
            pass


class CGDecision(Unit):
    """Convergence gate of the solve loop: latches ``complete`` when
    the recurrence residual reaches ``tol`` or ``max_iters`` runs out
    (the workflow wires ``repeater.gate_block = complete`` and
    ``end_point.gate_block = ~complete``, the same back-edge idiom as
    the training Decision)."""

    MAPPING = "cg_decision"

    def __init__(self, workflow, **kwargs):
        self.tol = float(kwargs.pop("tol", 1e-6))
        self.max_iters = int(kwargs.pop("max_iters", 500))
        kwargs.setdefault("name", "CGDecision")
        super().__init__(workflow, **kwargs)
        self.state: Optional[CGState] = None
        self.complete = Bool(False)
        self.demand("state")

    def run(self):
        st = self.state
        st.converged = st.residual <= self.tol
        self.complete <<= (st.converged
                           or st.iteration >= self.max_iters)

    def get_metric_values(self):
        st = self.state
        return {
            "iterations": st.iteration,
            "residual": st.residual,
            "residual_history": list(st.residual_history),
            "converged": bool(st.converged),
            "true_residual": st.true_residual,
        }


class CGWorkflow(Workflow):
    """Conjugate gradient on the dataflow graph:
    ``Start → CGSetup → Repeater → CGStep → CGDecision`` with the
    decision gating the back-edge and the EndPoint.

    ``operator`` may be a dense (n, n) matrix — the matvec then runs
    through :func:`blocked_matmul` over ``mesh``, and the final
    verification applies the matrix with a plain dense dot — or any
    SPD matvec callable (verified against itself; the callable is the
    caller's trusted problem definition). On a finish that *claims*
    convergence the answer must pass ``verify_residual`` within
    ``verify_tol`` (default ``max(100·tol, dtype residual floor)``) or
    the run raises: never a silently-wrong x."""

    def __init__(self, workflow=None, operator=None, rhs=None, x0=None,
                 tol: float = 1e-6, max_iters: int = 500,
                 preconditioner: Optional[Callable] = None,
                 mesh=None, block: int = DEFAULT_BLOCK,
                 verify_tol: Optional[float] = None, **kwargs):
        kwargs.setdefault("name", "cg")
        super().__init__(workflow, **kwargs)
        if operator is None or rhs is None:
            raise LinalgError("CGWorkflow needs operator= and rhs=")
        self._dense = None if callable(operator) else operator
        if self._dense is not None:
            matvec = _blocked_matvec(self._dense, mesh, block)
        else:
            matvec = operator
        self.rhs = rhs
        self.verify_tol = verify_tol
        self.tol = float(tol)

        self.cg_setup = CGSetup(self)
        self.cg_setup.operator = matvec
        self.cg_setup.rhs = rhs
        self.cg_setup.x0 = x0
        self.cg_setup.preconditioner = preconditioner
        self.repeater = Repeater(self)
        self.cg_step = CGStep(self)
        self.cg_step.operator = matvec
        self.cg_step.preconditioner = preconditioner
        self.cg_step.link_attrs(self.cg_setup, "state")
        self.cg_decision = CGDecision(self, tol=tol, max_iters=max_iters)
        self.cg_decision.link_attrs(self.cg_setup, "state")

        self.cg_setup.link_from(self.start_point)
        self.repeater.link_from(self.cg_setup)
        self.cg_step.link_from(self.repeater)
        self.cg_decision.link_from(self.cg_step)
        self.repeater.link_from(self.cg_decision)
        self.repeater.gate_block = self.cg_decision.complete
        self.end_point.link_from(self.cg_decision)
        self.end_point.gate_block = ~self.cg_decision.complete

    @property
    def solution(self):
        return self.cg_setup.state.x

    def on_workflow_finished(self):
        st = self.cg_setup.state
        if st.converged:
            dtype = numpy.asarray(st.x).dtype
            bound = (self.verify_tol if self.verify_tol is not None
                     else max(100.0 * self.tol,
                              residual_tolerance(dtype)))
            target = (self._dense if self._dense is not None
                      else self.cg_setup.operator)
            st.true_residual = verify_residual(
                target, st.x, self.rhs, tol=bound, what="linalg.cg")
        inc("veles_linalg_solves_total")
        super().on_workflow_finished()


def _blocked_matvec(a, mesh, block: int) -> Callable:
    """Dense matvec routed through the blocked (and, given a mesh,
    SUMMA-sharded) matmul — the faultable path CG iterates through."""
    def matvec(v):
        return blocked_matmul(a, v[:, None], block=block,
                              mesh=mesh)[:, 0]
    return matvec


def build_cg_workflow(operator, rhs, **kwargs) -> CGWorkflow:
    """Convenience constructor mirroring the models' public
    ``build_workflow`` shape; see :class:`CGWorkflow` for knobs."""
    return CGWorkflow(operator=operator, rhs=rhs, **kwargs)


# ---------------------------------------------------------------------------
# the SPD Poisson model problem + 2-level multigrid preconditioner
# ---------------------------------------------------------------------------

def poisson2d_matvec(n: int) -> Callable:
    """The 5-point 2D Dirichlet Laplacian on an n×n interior grid as a
    matvec over flattened (n²,) vectors: (Au)ᵢⱼ = 4uᵢⱼ − u_{i±1,j} −
    u_{i,j±1} (zero outside). SPD — the family's model problem."""
    def apply(v):
        jnp = _jnp()
        u = jnp.asarray(v).reshape(n, n)
        out = 4.0 * u
        out = out - jnp.pad(u[1:, :], ((0, 1), (0, 0)))
        out = out - jnp.pad(u[:-1, :], ((1, 0), (0, 0)))
        out = out - jnp.pad(u[:, 1:], ((0, 0), (0, 1)))
        out = out - jnp.pad(u[:, :-1], ((0, 0), (1, 0)))
        return out.reshape(-1)
    return apply


def poisson2d_dense(n: int, dtype=numpy.float32) -> numpy.ndarray:
    """The same operator as an explicit dense (n², n²) matrix — the
    reference for small equality tests and the Galerkin coarse build."""
    size = n * n
    a = numpy.zeros((size, size), dtype=dtype)
    for i in range(n):
        for j in range(n):
            k = i * n + j
            a[k, k] = 4.0
            if i > 0:
                a[k, k - n] = -1.0
            if i < n - 1:
                a[k, k + n] = -1.0
            if j > 0:
                a[k, k - 1] = -1.0
            if j < n - 1:
                a[k, k + 1] = -1.0
    return a


class TwoLevelPoisson:
    """Symmetric 2-level multigrid V-cycle preconditioner for
    :func:`poisson2d_matvec` (n even): damped-Jacobi pre-smooth, a
    Galerkin coarse-grid correction (restriction = 2×2 aggregation,
    prolongation its transpose, coarse operator A_c = PᵀAP factored
    ONCE with ``blocked_cholesky``), damped-Jacobi post-smooth. The
    same smoother on both sides keeps M⁻¹ symmetric positive definite,
    so it drops straight into PCG via ``preconditioner=``."""

    def __init__(self, n: int, omega: float = 0.8,
                 block: int = DEFAULT_BLOCK, mesh=None,
                 dtype=numpy.float32):
        if n % 2:
            raise LinalgError("TwoLevelPoisson needs even n, got %d" % n)
        self.n = n
        self.nc = n // 2
        self.omega = float(omega)
        self._apply = poisson2d_matvec(n)
        # Galerkin coarse operator, one column per coarse basis vector
        # (nc² applies of the fine operator — a one-time setup cost)
        size_c = self.nc * self.nc
        a_c = numpy.zeros((size_c, size_c), dtype=dtype)
        for i in range(size_c):
            e = numpy.zeros(size_c, dtype=dtype)
            e[i] = 1.0
            a_c[:, i] = numpy.asarray(
                self._restrict(self._apply(self._prolong(e))))
        self._chol_c = blocked_cholesky(a_c, block=block, mesh=mesh)

    def _prolong(self, zc):
        jnp = _jnp()
        u = jnp.asarray(zc).reshape(self.nc, self.nc)
        return jnp.repeat(jnp.repeat(u, 2, axis=0), 2,
                          axis=1).reshape(-1)

    def _restrict(self, r):
        jnp = _jnp()
        u = jnp.asarray(r).reshape(self.nc, 2, self.nc, 2)
        return u.sum(axis=(1, 3)).reshape(-1)

    def _coarse_solve(self, rc):
        y = blocked_triangular_solve(self._chol_c, rc, lower=True)
        return blocked_triangular_solve(self._chol_c.T, y, lower=False)

    def __call__(self, r):
        jnp = _jnp()
        r = jnp.asarray(r)
        z = self.omega * r / 4.0                       # pre-smooth
        d = r - self._apply(z)
        z = z + self._prolong(self._coarse_solve(self._restrict(d)))
        z = z + self.omega * (r - self._apply(z)) / 4.0  # post-smooth
        return z
