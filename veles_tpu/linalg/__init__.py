"""Distributed linear-algebra workload family (ROADMAP item 5).

Blocked, mesh-sharded dense kernels (``blocked``) and iterative
solvers hosted on the Workflow/Unit graph (``solvers``) — the first
non-NN workloads on this platform, instrumented through the same
telemetry/cost/fault planes as training. See docs/workloads.md.
"""

# every counter this package increments — bench.py's gate_linalg
# checks each one is registered in telemetry.counters.DESCRIPTIONS and
# that non-linalg bench docs show them all at zero (no leakage).
LINALG_COUNTERS = (
    "veles_linalg_block_ops_total",
    "veles_linalg_matmuls_total",
    "veles_linalg_factorizations_total",
    "veles_linalg_solves_total",
    "veles_linalg_iterations_total",
    "veles_linalg_residual_checks_total",
    "veles_linalg_residual_failures_total",
)

from .blocked import (DEFAULT_BLOCK, LinalgError, blocked_cholesky,
                      blocked_matmul, blocked_triangular_solve,
                      cholesky_solve, cyclic_permutation,
                      default_tolerance, linalg_mesh, matmul_cost,
                      cholesky_cost, predict_summa_time,
                      residual_tolerance, verify_residual)
from .solvers import (CGDecision, CGSetup, CGState, CGStep, CGWorkflow,
                      TwoLevelPoisson, build_cg_workflow,
                      poisson2d_dense, poisson2d_matvec)

__all__ = [
    "LINALG_COUNTERS",
    "DEFAULT_BLOCK", "LinalgError", "blocked_cholesky",
    "blocked_matmul", "blocked_triangular_solve", "cholesky_solve",
    "cyclic_permutation", "default_tolerance", "linalg_mesh",
    "matmul_cost", "cholesky_cost", "predict_summa_time",
    "residual_tolerance", "verify_residual", "CGDecision", "CGSetup",
    "CGState", "CGStep", "CGWorkflow", "TwoLevelPoisson",
    "build_cg_workflow", "poisson2d_dense", "poisson2d_matvec",
]
