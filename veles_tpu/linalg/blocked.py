"""Blocked, mesh-sharded dense linear algebra.

The first non-NN workload family this build hosts (ROADMAP item 5 —
the reference VELES was a general dataflow platform, not an NN
trainer). The kernels follow the TPU linear-algebra literature
(PAPERS.md: "Large Scale Distributed Linear Algebra With Tensor
Processing Units", "JAXMg"): dense matrices are tiled into blocks, the
block grid is laid out block-cyclically over a 2D ("rows", "cols")
device mesh, and every distributed operation decomposes into *local
block dots plus psums* expressed with ``shard_map`` (through
``parallel/compat.py``, the one shim every shard_map call site in this
tree uses).

Three layers, each falsifiable against the layer below:

- ``blocked_matmul`` — SUMMA: for each of the ``G = lcm(pr, pc)``
  k-panels, the owner column broadcasts its A panel along the mesh row
  (a masked psum), the owner row broadcasts its B panel along the mesh
  column, and every device accumulates one local dot. The single-device
  path runs the same panel loop without the mesh; both are asserted
  equal to ``a @ b`` in tests.
- ``blocked_cholesky`` / ``blocked_triangular_solve`` — right-looking
  blocked factorization: small dense potrf on the diagonal block, a
  triangular solve for the panel, and the trailing SYRK update routed
  through ``blocked_matmul`` (which is where the mesh enters).
  Reference: ``np.linalg.cholesky`` / ``scipy``-style substitution.
- ``verify_residual`` — the trusted check every solver must pass
  before an answer is returned. It applies the operator with a PLAIN
  dense dot (never through the faultable block dispatch below), so an
  injected corruption can never vouch for itself: a corrupt block
  makes the solve fail loudly instead of returning a silently-wrong x.

Fault surface: every host-side block dispatch calls
``resilience.faults.fire("linalg.block_op")`` — ``raise`` aborts the
dispatch, ``corrupt`` flips bytes in the dispatched block (the chaos
test proves the residual check catches it). Costs are recorded into
``telemetry.cost.model`` as analytic entries (2mnk matmul flops, n³/3
potrf) keyed ``linalg.*``, with MFU priced against the *computation
dtype's* peak (``peak_flops_entry`` — f32 work is not graded against
the bf16 peak).

Tolerances (stated so the equality claims are falsifiable): blocked
results match the dense reference to ``rtol = 100·eps(dtype)`` of the
result's scale — f32 ≈ 1.2e-5, f64 ≈ 2.2e-14 — and solver residuals
must pass ``verify_residual``'s relative bound (default
``RESIDUAL_TOL`` per dtype below).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Tuple, Union

import numpy

from ..error import VelesError
from ..telemetry.counters import inc
from ..telemetry.spans import span
from ..telemetry import cost as cost_mod
from ..resilience import faults


class LinalgError(VelesError):
    """A linear-algebra kernel produced (or was asked to produce) an
    answer it cannot stand behind: residual check failure, non-SPD
    input to Cholesky, malformed mesh/shape."""


#: default k-panel width for the single-device blocked paths
DEFAULT_BLOCK = 128

#: default relative residual bound of :func:`verify_residual`, keyed by
#: result dtype itemsize (4 → f32, 8 → f64). Stated here so the
#: "never silently wrong" claim has one number to refute.
RESIDUAL_TOL = {4: 1e-4, 8: 1e-10}


def _jnp():
    import jax.numpy as jnp
    return jnp


def default_tolerance(dtype) -> float:
    """The stated blocked-vs-dense equality tolerance for ``dtype``:
    100·eps, relative to the result's scale."""
    return 100.0 * float(numpy.finfo(numpy.dtype(dtype)).eps)


def residual_tolerance(dtype) -> float:
    """Default :func:`verify_residual` bound for ``dtype``."""
    return RESIDUAL_TOL.get(numpy.dtype(dtype).itemsize, 1e-4)


# ---------------------------------------------------------------------------
# fault surface: one chokepoint every blocked dispatch goes through
# ---------------------------------------------------------------------------

def _dispatch_block(block, **ctx):
    """The ``linalg.block_op`` injection chokepoint: counts the
    dispatch, then lets the fault plane raise, or corrupt the block's
    bytes. The payload is framed big-endian and padded so
    ``Fault.corrupt``'s middle-byte flip lands on the sign/exponent
    byte of one element — real damage the residual check MUST catch
    (a little-endian middle byte would be a mantissa LSB: a 1-ulp
    perturbation inside every stated tolerance, proving nothing)."""
    inc("veles_linalg_block_ops_total")
    fault = faults.fire("linalg.block_op", **ctx)
    if fault is None:
        return block
    arr = numpy.asarray(block)
    if arr.size == 0:                 # nothing to damage
        return block
    be = arr.dtype.newbyteorder(">")
    raw = arr.astype(be).tobytes()
    item = arr.dtype.itemsize
    pad = next(q for q in range(0, 2 * item + 1)
               if ((len(raw) + q) // 2 - q) % item == 0
               and (len(raw) + q) // 2 >= q)
    damaged = fault.corrupt(b"\x00" * pad + raw)[pad:]
    return _jnp().asarray(numpy.frombuffer(damaged, dtype=be)
                          .reshape(arr.shape).astype(arr.dtype))


# ---------------------------------------------------------------------------
# mesh + block-cyclic layout helpers
# ---------------------------------------------------------------------------

def linalg_mesh(grid: Optional[Tuple[int, int]] = None, devices=None):
    """A 2D ``("rows", "cols")`` device mesh for the blocked kernels.

    ``grid=None`` picks the squarest (pr, pc) factorization of the
    visible device count (8 devices → 2×4). A submesh (grid smaller
    than the device count) is allowed, mirroring ``backends.make_mesh``.
    """
    import jax
    from jax.sharding import Mesh
    devices = list(jax.devices() if devices is None else devices)
    if grid is None:
        n = len(devices)
        pr = int(math.sqrt(n))
        while pr > 1 and n % pr:
            pr -= 1
        grid = (pr, n // pr)
    pr, pc = int(grid[0]), int(grid[1])
    if pr < 1 or pc < 1:
        raise LinalgError("linalg mesh grid must be positive, got %r"
                          % (grid,))
    need = pr * pc
    if need > len(devices):
        raise LinalgError("linalg mesh %dx%d needs %d devices, have %d"
                          % (pr, pc, need, len(devices)))
    arr = numpy.asarray(devices[:need]).reshape(pr, pc)
    return Mesh(arr, ("rows", "cols"))


def _pad_to(a, rows: int, cols: int):
    """Zero-pad a 2D array up to (rows, cols)."""
    jnp = _jnp()
    r, c = a.shape
    if r == rows and c == cols:
        return a
    return jnp.pad(a, ((0, rows - r), (0, cols - c)))


def cyclic_permutation(n_pad: int, slabs: int, p: int):
    """Block-cyclic layout as a row permutation.

    Splitting the (padded) axis into ``slabs`` equal slabs and dealing
    them round-robin over ``p`` shards is the classic block-cyclic
    distribution; with shard_map's *contiguous* sharding the same
    layout is obtained by permuting slab ``s`` into the contiguous
    range of shard ``s mod p`` first. Returns ``(perm, inv)`` index
    vectors (``a[perm][inv] == a``).
    """
    if n_pad % slabs:
        raise LinalgError("cyclic layout: %d not divisible into %d slabs"
                          % (n_pad, slabs))
    w = n_pad // slabs
    order = [s for d in range(p) for s in range(d, slabs, p)]
    perm = numpy.concatenate(
        [numpy.arange(s * w, (s + 1) * w) for s in order])
    inv = numpy.empty_like(perm)
    inv[perm] = numpy.arange(n_pad)
    return perm, inv


# ---------------------------------------------------------------------------
# SUMMA matmul
# ---------------------------------------------------------------------------

def _summa_local(ax_r: str, ax_c: str, pr: int, pc: int, G: int, w: int):
    """The per-device SUMMA body: G panel steps, each one masked-psum
    broadcast of the A panel along the mesh row and of the B panel
    along the mesh column, then a local dot accumulate."""
    import jax

    def local(a_loc, b_loc):
        jnp = _jnp()
        row = jax.lax.axis_index(ax_r)
        col = jax.lax.axis_index(ax_c)
        acc = jnp.zeros((a_loc.shape[0], b_loc.shape[1]), a_loc.dtype)
        for g in range(G):
            # A's k-axis is sharded over cols: each col shard holds
            # G/pc consecutive panels; panel g lives in col g//(G/pc)
            oc, la = divmod(g, G // pc)
            orow, lb = divmod(g, G // pr)
            a_sub = a_loc[:, la * w:(la + 1) * w]
            b_sub = b_loc[lb * w:(lb + 1) * w, :]
            a_g = jax.lax.psum(
                jnp.where(col == oc, a_sub, jnp.zeros_like(a_sub)), ax_c)
            b_g = jax.lax.psum(
                jnp.where(row == orow, b_sub, jnp.zeros_like(b_sub)), ax_r)
            acc = acc + a_g @ b_g
        return acc

    return local


def blocked_matmul(a, b, block: int = DEFAULT_BLOCK, mesh=None,
                   cyclic: bool = True):
    """``a @ b`` by blocked panels — SUMMA over a 2D mesh, or the same
    panel loop on one device when ``mesh is None``.

    ``cyclic=True`` (the default, mesh path only) lays the block grid
    out block-cyclically: the matrix axes are slab-permuted before
    sharding and the result is un-permuted, so device (i, j) holds a
    round-robin set of blocks instead of one contiguous tile —
    mathematically identical (matmul commutes with a shared row/column
    permutation), better balanced for the triangular updates built on
    top. Records an analytic 2mnk-FLOP cost under ``linalg.matmul``.
    """
    jnp = _jnp()
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
        raise LinalgError("blocked_matmul shapes %r @ %r"
                          % (tuple(a.shape), tuple(b.shape)))
    m, k = a.shape
    n = b.shape[1]
    with span("linalg.matmul", m=m, k=k, n=n,
              mesh=(tuple(mesh.devices.shape) if mesh is not None
                    else None)):
        if mesh is None:
            out = _matmul_single(a, b, block)
        else:
            out = _matmul_summa(a, b, mesh, cyclic)
    cost_mod.model.record("linalg.matmul", matmul_cost(m, k, n, a.dtype))
    inc("veles_linalg_matmuls_total")
    return out


def _matmul_single(a, b, block: int):
    """Single-device reference path: the identical k-panel loop, one
    block dispatch per panel."""
    jnp = _jnp()
    m, k = a.shape
    n = b.shape[1]
    acc = jnp.zeros((m, n), a.dtype)
    for s in range(0, k, block):
        e = min(k, s + block)
        a_sub = _dispatch_block(a[:, s:e], op="matmul", panel=s // block)
        acc = acc + a_sub @ b[s:e, :]
    return acc


def _matmul_summa(a, b, mesh, cyclic: bool):
    import jax
    from jax.sharding import PartitionSpec as P

    from ..parallel.compat import shard_map_compat

    jnp = _jnp()
    if len(mesh.devices.shape) != 2:
        raise LinalgError("linalg needs a 2D mesh, got shape %r"
                          % (tuple(mesh.devices.shape),))
    pr, pc = mesh.devices.shape
    ax_r, ax_c = mesh.axis_names
    G = pr * pc // math.gcd(pr, pc)          # lcm: k-panel count
    m, k = a.shape
    n = b.shape[1]
    mp = G * -(-m // G)
    kp = G * -(-k // G)
    np_ = G * -(-n // G)
    a_p = _pad_to(a, mp, kp)
    b_p = _pad_to(b, kp, np_)
    if cyclic:
        pm, pm_inv = cyclic_permutation(mp, G, pr)
        pk, _ = cyclic_permutation(kp, G, pc)
        pn, pn_inv = cyclic_permutation(np_, G, pc)
        # the SAME k-permutation on A's columns and B's rows cancels in
        # the contraction; row/col permutations are undone on C
        a_p = a_p[pm][:, pk]
        b_p = b_p[pk][:, pn]
    a_p = _dispatch_block(a_p, op="summa", grid=(int(pr), int(pc)))
    spec = P(ax_r, ax_c)
    fn = shard_map_compat(
        _summa_local(ax_r, ax_c, int(pr), int(pc), G, kp // G),
        mesh=mesh, in_specs=(spec, spec), out_specs=spec)
    with mesh:
        c_p = jax.jit(fn)(a_p, b_p)
    if cyclic:
        c_p = c_p[pm_inv][:, pn_inv]
    return c_p[:m, :n]


def matmul_cost(m: int, k: int, n: int, dtype) -> "cost_mod.Cost":
    """Analytic matmul cost: 2mnk FLOPs, one read of each operand and
    one write of the result."""
    itemsize = numpy.dtype(dtype).itemsize
    return cost_mod.Cost(
        flops=2.0 * m * n * k,
        bytes_accessed=float((m * k + k * n + m * n) * itemsize),
        source="analytic")


# ---------------------------------------------------------------------------
# right-looking blocked Cholesky + blocked triangular solve
# ---------------------------------------------------------------------------

def blocked_cholesky(a, block: int = DEFAULT_BLOCK, mesh=None,
                     mesh_min: int = 64):
    """Lower-triangular L with ``L @ L.T == a`` by right-looking blocked
    panels.

    Per panel k: dense potrf of the diagonal block, a triangular solve
    for the sub-diagonal panel, then the trailing SYRK update
    ``A22 -= L21 @ L21.T`` — routed through :func:`blocked_matmul`
    (and hence over ``mesh`` whenever the trailing size is at least
    ``mesh_min``, which is where the distribution enters; the panel
    factorization itself is small and stays on one device, the standard
    distributed-Cholesky split). Raises :class:`LinalgError` if ``a``
    is not positive definite. Records n³/3 FLOPs under
    ``linalg.cholesky``.
    """
    import jax
    jnp = _jnp()
    a = jnp.asarray(a)
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise LinalgError("cholesky needs a square matrix, got %r"
                          % (tuple(a.shape),))
    n = a.shape[0]
    with span("linalg.cholesky", n=n, block=block,
              mesh=(tuple(mesh.devices.shape) if mesh is not None
                    else None)):
        work = a
        for s in range(0, n, block):
            e = min(n, s + block)
            diag = _dispatch_block(work[s:e, s:e], op="potrf",
                                   panel=s // block)
            l_kk = jnp.linalg.cholesky(diag)
            work = work.at[s:e, s:e].set(l_kk)
            if e < n:
                # L21 = A21 @ L11^{-T}: one triangular solve per panel
                panel = jax.scipy.linalg.solve_triangular(
                    l_kk, jnp.swapaxes(work[e:, s:e], 0, 1),
                    lower=True).T
                work = work.at[e:, s:e].set(panel)
                upd = blocked_matmul(
                    panel, panel.T, block=block,
                    mesh=(mesh if mesh is not None and (n - e) >= mesh_min
                          else None))
                work = work.at[e:, e:].add(-upd)
        out = jnp.tril(work)
        if bool(jnp.any(jnp.isnan(out))):
            inc("veles_linalg_residual_failures_total")
            raise LinalgError(
                "cholesky: matrix is not positive definite (NaN panel)")
    cost_mod.model.record("linalg.cholesky", cholesky_cost(n, a.dtype))
    inc("veles_linalg_factorizations_total")
    return out


def cholesky_cost(n: int, dtype) -> "cost_mod.Cost":
    """Analytic potrf cost: n³/3 FLOPs, read+write of the matrix."""
    itemsize = numpy.dtype(dtype).itemsize
    return cost_mod.Cost(flops=n ** 3 / 3.0,
                         bytes_accessed=float(2 * n * n * itemsize),
                         source="analytic")


def blocked_triangular_solve(l, b, lower: bool = True,
                             block: int = DEFAULT_BLOCK):
    """Solve ``l @ x = b`` (or upper-triangular back-substitution when
    ``lower=False``) by blocked forward/backward substitution: per
    block row, subtract the already-solved block dots, then one small
    dense triangular solve."""
    import jax
    jnp = _jnp()
    l = jnp.asarray(l)
    b = jnp.asarray(b)
    vector = b.ndim == 1
    if vector:
        b = b[:, None]
    n = l.shape[0]
    x = jnp.zeros_like(b)
    ranges = list(range(0, n, block))
    if not lower:
        ranges = ranges[::-1]
    for s in ranges:
        e = min(n, s + block)
        if lower:
            rhs = b[s:e] - _dispatch_block(l[s:e, :s], op="trsm") @ x[:s]
        else:
            rhs = b[s:e] - _dispatch_block(l[s:e, e:], op="trsm") @ x[e:]
        x = x.at[s:e].set(jax.scipy.linalg.solve_triangular(
            l[s:e, s:e], rhs, lower=lower))
    return x[:, 0] if vector else x


def cholesky_solve(a, b, block: int = DEFAULT_BLOCK, mesh=None,
                   check: bool = True, tol: Optional[float] = None):
    """Solve SPD ``a @ x = b`` via blocked Cholesky + two blocked
    triangular solves. With ``check=True`` (default) the answer must
    pass :func:`verify_residual` before it is returned — a corrupted
    block op can therefore never produce a silently-wrong x."""
    l = blocked_cholesky(a, block=block, mesh=mesh)
    y = blocked_triangular_solve(l, b, lower=True, block=block)
    x = blocked_triangular_solve(l.T, y, lower=False, block=block)
    if check:
        verify_residual(a, x, b, tol=tol, what="linalg.cholesky_solve")
    inc("veles_linalg_solves_total")
    return x


# ---------------------------------------------------------------------------
# the trusted residual check
# ---------------------------------------------------------------------------

def verify_residual(operator: Union[Callable, object], x, b,
                    tol: Optional[float] = None,
                    what: str = "linalg.solve") -> float:
    """Relative residual ``|b - A x| / |b|`` of a proposed solution,
    raising :class:`LinalgError` when it exceeds ``tol``.

    THE trusted path of the family: a matrix operator is applied with a
    plain dense dot on the host — never through the faultable
    ``linalg.block_op`` dispatch — so an injected corruption in the
    solve cannot also corrupt its own acceptance check. Callable
    operators are applied as given (they are the caller's trusted
    definition of the problem). Returns the residual; every call is
    counted (``veles_linalg_residual_checks_total`` /
    ``_failures_total``).
    """
    xv = numpy.asarray(x, dtype=numpy.float64)
    bv = numpy.asarray(b, dtype=numpy.float64)
    if callable(operator):
        ax = numpy.asarray(operator(x), dtype=numpy.float64)
        dtype = numpy.asarray(x).dtype
    else:
        av = numpy.asarray(operator, dtype=numpy.float64)
        ax = av @ xv
        dtype = numpy.asarray(operator).dtype
    bound = residual_tolerance(dtype) if tol is None else float(tol)
    denom = float(numpy.linalg.norm(bv))
    resid = float(numpy.linalg.norm(bv - ax)) / (denom or 1.0)
    inc("veles_linalg_residual_checks_total")
    with span("linalg.residual_check", what=what, resid=resid,
              tol=bound):
        if not numpy.isfinite(resid) or resid > bound:
            inc("veles_linalg_residual_failures_total")
            raise LinalgError(
                "%s: residual check FAILED: |b-Ax|/|b| = %.3e > %.3e "
                "(corrupt block or ill-posed system; refusing to "
                "return x)" % (what, resid, bound))
    return resid


# ---------------------------------------------------------------------------
# the falsifiable SUMMA step-time model (SCALING.json's linalg row)
# ---------------------------------------------------------------------------

def predict_summa_time(m: int, k: int, n: int, grid: Tuple[int, int],
                       t1_step_s: float, dtype=numpy.float32,
                       ici_bw: Optional[float] = None,
                       device_kind: Optional[str] = None) -> dict:
    """Predicted SUMMA step time on a (pr, pc) mesh, every input stated
    (the same falsifiability contract as
    ``resilience.elastic.predict_step_time`` / the PR 9 elastic row):

    ``t_pred = t1_step/N + psum_bytes/ici_bw`` where per-device psum
    traffic sums, over the G = lcm(pr, pc) panel steps, one ring
    all-reduce of the A panel along the row (2·(pc-1)/pc of its bytes)
    and one of the B panel along the column.
    """
    pr, pc = int(grid[0]), int(grid[1])
    n_dev = pr * pc
    G = pr * pc // math.gcd(pr, pc)
    itemsize = numpy.dtype(dtype).itemsize
    mp = G * -(-m // G)
    kp = G * -(-k // G)
    np_ = G * -(-n // G)
    w = kp // G
    a_panel_bytes = (mp // pr) * w * itemsize
    b_panel_bytes = w * (np_ // pc) * itemsize
    if ici_bw is None:
        ici_bw_source, ici_bw = cost_mod.ici_bandwidth_entry(device_kind)
    else:
        ici_bw_source = "caller"
    psum_bytes = G * (2.0 * (pc - 1) / pc * a_panel_bytes
                      + 2.0 * (pr - 1) / pr * b_panel_bytes)
    compute_s = t1_step_s / n_dev
    comm_s = psum_bytes / ici_bw
    return {
        "predicted_step_s": compute_s + comm_s,
        "compute_s": compute_s,
        "comm_s": comm_s,
        "inputs": {
            "t1_step_s": t1_step_s,
            "grid": [pr, pc],
            "panels": G,
            "block_bytes_a_panel": a_panel_bytes,
            "block_bytes_b_panel": b_panel_bytes,
            "psum_bytes_per_device": psum_bytes,
            "ici_bw_assumed_bytes_per_s": ici_bw,
            "ici_bw_source": ici_bw_source,
        },
    }
