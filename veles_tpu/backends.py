"""Device backends: XLA (TPU/CPU) and NumPy oracle.

Equivalent of the reference's veles/backends.py:166-949 (BackendRegistry,
Device/OpenCLDevice/CUDADevice/NumpyDevice/AutoDevice). TPU-first redesign:

- One accelerated backend — XLA — instead of per-vendor kernel dispatch;
  ``XLADevice`` owns the device set, the logical ``jax.sharding.Mesh`` and
  the dtype policy. The reference's OpenCL block-size auto-tuner
  (veles/backends.py:672-731) has no equivalent: XLA tiles for the MXU.
- ``NumpyDevice`` is kept as the universal test oracle (the reference's
  "numpy is the oracle" property, SURVEY.md §4).
- Selection via ``root.common.engine.backend`` or ``VELES_BACKEND`` env,
  priority tpu > other-xla > numpy (reference AutoDevice priorities,
  veles/backends.py:406-424).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import numpy

from .config import root
from .error import VelesError
from .logger import Logger


class BackendRegistry(type):
    """name → Device class (reference: veles/backends.py:166)."""

    backends: Dict[str, type] = {}

    def __init__(cls, name, bases, clsdict):
        super().__init__(name, bases, clsdict)
        backend = clsdict.get("BACKEND")
        if backend:
            BackendRegistry.backends[backend] = cls


def _resolve_dtype(name) -> numpy.dtype:
    """numpy.dtype() extended with the ml_dtypes names (bfloat16 &c.) —
    plain numpy does not know them, so NumpyDevice would crash on the
    default bf16 compute policy."""
    try:
        return numpy.dtype(name)
    except TypeError:
        import ml_dtypes
        return numpy.dtype(getattr(ml_dtypes, str(name)))


class Device(Logger, metaclass=BackendRegistry):
    """Abstract device (reference: veles/backends.py:184)."""

    BACKEND: Optional[str] = None

    def __init__(self) -> None:
        super().__init__()
        self.compute_dtype = _resolve_dtype(
            root.common.engine.compute_dtype)
        self.precision_dtype = _resolve_dtype(
            root.common.engine.precision_type)

    @property
    def is_accelerated(self) -> bool:
        return True

    @property
    def name(self) -> str:
        return self.BACKEND or type(self).__name__

    def sync(self) -> None:
        """Block until outstanding device work completes."""

    def exists(self) -> bool:
        return True


class NumpyDevice(Device):
    """Pure-host oracle backend (reference: veles/backends.py:918)."""

    BACKEND = "numpy"

    @property
    def is_accelerated(self) -> bool:
        return False


_cache_enabled = False


def guard_unresponsive_backend(timeout: float = 150.0) -> bool:
    """Probe device enumeration in a killable SUBPROCESS; pin the CPU
    platform when it HANGS (a dead tunnel relay blocks in-process
    jax.devices() forever — observed live 2026-07-30). Fast failures
    are left alone: they surface as normal exceptions to the caller.
    Returns True when the guard engaged (CPU pinned). No-op when a
    platform is already pinned, when jax is already initialized in
    this process, or under VELES_TPU_NO_PROBE=1."""
    import subprocess
    import sys as _sys
    import tempfile
    import time as _time
    # only a HOST pin makes probing redundant: an accelerator pin
    # (this rig exports JAX_PLATFORMS=axon globally) carries the exact
    # hang risk the guard exists for — round 2's guard skipped on it
    # and the bench slow-failed for 25 minutes in-process
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu") or \
            os.environ.get("VELES_TPU_NO_PROBE"):
        return False
    if "jax" in _sys.modules and getattr(
            _sys.modules["jax"], "_veles_probe_done", False):
        return False
    # a fresh last-good stamp skips the probe: the child pays a full
    # backend init (seconds + a transient claim on an exclusive chip),
    # too costly on EVERY healthy launch
    stamp = os.path.join(tempfile.gettempdir(),
                         "veles_tpu_backend_ok_%d" % os.getuid())
    try:
        if _time.time() - os.path.getmtime(stamp) < 600:
            return False
    except OSError:
        pass
    engaged = False
    for probe_round in range(2):
        try:
            proc = subprocess.run([_sys.executable, "-c",
                                   "import jax; jax.devices()"],
                                  capture_output=True, timeout=timeout)
            # the stamp means "backend KNOWN GOOD"; a fast nonzero exit
            # is a failure, not health — stamping it would advertise a
            # broken backend for 10 minutes
            if proc.returncode == 0:
                try:
                    with open(stamp, "w"):
                        pass
                except OSError:
                    pass
            break
        except subprocess.TimeoutExpired:
            if probe_round == 0:
                # one retry before pinning: an exclusive chip held by
                # another client probes exactly like a dead relay, but
                # busy chips free up — dead relays stay dead
                Logger().warning(
                    "backend probe hung %.0fs — retrying once before "
                    "pinning CPU (chip may be busy, not dead)", timeout)
                continue
            os.environ["JAX_PLATFORMS"] = "cpu"
            Logger().warning(
                "accelerator backend unresponsive after 2x%.0fs "
                "(transport down?) — pinning JAX_PLATFORMS=cpu so this "
                "process cannot hang", timeout)
            engaged = True
    import jax
    if engaged:
        # the env var alone is NOT enough: the tunnelled-TPU plugin
        # re-writes jax_platforms at registration (veles_tpu/__init__
        # re-pins from the env only at ITS import, already past here)
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            from jax.extend.backend import clear_backends
            clear_backends()
            jax.config.update("jax_platforms", "cpu")
    jax._veles_probe_done = True
    return engaged


def _enable_compilation_cache(jax) -> None:
    """Persistent compiled-program cache (reference analogue: the
    device-keyed kernel-binary tarballs, veles/accelerated_units.py:
    605-673). Off when root.common.engine.compilation_cache is empty."""
    global _cache_enabled
    if _cache_enabled:
        return
    if os.environ.get("VELES_TPU_TEST"):
        return    # the test harness must not grow a cache in $HOME
    path = str(root.common.engine.get("compilation_cache", "") or "")
    if not path:
        return
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # only compiles worth re-reading get persisted: sub-second
        # compiles would pay a disk write for nothing and the cache has
        # no eviction — bounding what enters is the size control
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
        _cache_enabled = True
    except Exception as e:       # never let caching break device init
        Logger().warning("compilation cache disabled: %s", e)


def _disable_compilation_cache(jax) -> None:
    global _cache_enabled
    if not _cache_enabled:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:
        pass
    _cache_enabled = False


class XLADevice(Device):
    """JAX/XLA device set + logical mesh (the reference's
    Device-per-accelerator model collapses to one object owning all chips:
    SPMD means the framework addresses the *mesh*, not a chip)."""

    BACKEND = "xla"

    def __init__(self, platform: Optional[str] = None,
                 mesh_axes: Optional[Dict[str, int]] = None) -> None:
        super().__init__()
        import jax
        self._jax = jax
        self.jax_devices = (jax.devices(platform) if platform
                            else jax.devices())
        if not self.jax_devices:
            raise VelesError("no XLA devices for platform %r" % platform)
        self.platform = self.jax_devices[0].platform
        # accelerators only: XLA:CPU caches AOT results keyed without
        # host machine features — reloading one compiled elsewhere (or
        # with other flags) risks SIGILL; and CPU compiles are fast
        # enough not to need persistence. The jax setting is process-
        # global, so a CPU device must actively switch it OFF again.
        if self.platform != "cpu":
            _enable_compilation_cache(jax)
        else:
            _disable_compilation_cache(jax)
        axes = dict(mesh_axes if mesh_axes is not None
                    else root.common.mesh.axes.as_dict()
                    if hasattr(root.common.mesh.axes, "as_dict")
                    else root.common.mesh.axes)
        self.mesh = make_mesh(self.jax_devices, axes)
        self.info("XLA backend: %d %s device(s), mesh %s",
                  len(self.jax_devices), self.platform,
                  dict(zip(self.mesh.axis_names, self.mesh.devices.shape)))

    @property
    def device_count(self) -> int:
        return len(self.jax_devices)

    def sync(self) -> None:
        # NOT block_until_ready: through the tunnelled-TPU transport that
        # returns immediately. A host fetch of a freshly enqueued scalar
        # drains the (in-order) compute stream for real.
        import numpy
        numpy.asarray(self._jax.device_put(0.0) + 0)

    def compute_power(self, n: int = 2048) -> float:
        """GEMM benchmark → GFLOP/s; the reference used the same measurement
        for load balancing (veles/accelerated_units.py:843-858); kept here
        as telemetry."""
        import jax
        import jax.numpy as jnp
        import time
        import numpy
        a = jnp.ones((n, n), dtype=jnp.bfloat16) * 1e-3
        f = jax.jit(lambda x: x @ x * 1e-3)
        numpy.asarray(f(a)[0, :1].astype(jnp.float32))   # warm + true sync
        t0 = time.time()
        reps = 8
        r = a
        for _ in range(reps):            # dependency chain: no overlap games
            r = f(r)
        numpy.asarray(r[0, :1].astype(jnp.float32))      # host fetch = sync
        dt = (time.time() - t0) / reps
        return 2.0 * n ** 3 / dt / 1e9


def make_mesh(devices, axes: Dict[str, int]):
    """Build a jax Mesh from an axis-name → size spec; one axis may be -1
    (absorbs remaining devices). Reserved axis vocabulary:
    data / fsdp / tensor / sequence / expert / pipeline (SURVEY.md §5.7)."""
    import numpy as np
    from jax.sharding import Mesh
    total = len(devices)
    sizes = dict(axes)
    wild = [k for k, v in sizes.items() if v == -1]
    fixed = int(np.prod([v for v in sizes.values() if v != -1])) if sizes \
        else 1
    if len(wild) > 1:
        raise VelesError("at most one mesh axis may be -1: %s" % axes)
    if wild:
        if total % fixed:
            raise VelesError("mesh %s does not divide %d devices" %
                             (axes, total))
        sizes[wild[0]] = total // fixed
    shape = tuple(sizes.values()) or (total,)
    names = tuple(sizes.keys()) or ("data",)
    need = int(np.prod(shape))
    if need > total:
        raise VelesError("mesh %s needs %d devices, only %d present" %
                         (sizes, need, total))
    # a submesh over the first N devices is allowed, but never silently
    if need < total:
        import logging
        logging.getLogger("make_mesh").warning(
            "mesh %s uses %d of %d devices; %d idle", sizes, need, total,
            total - need)
    return Mesh(np.asarray(devices[:need]).reshape(shape), names)


_auto_device: Optional[Device] = None


def Device_for(backend: Optional[str] = None) -> Device:
    """Resolve a backend name to a Device (reference: Device.__new__
    dispatch on -a/--backend or VELES_BACKEND, veles/backends.py:184-243)."""
    backend = (backend or os.environ.get("VELES_BACKEND") or
               root.common.engine.backend)
    if backend == "numpy" or root.common.engine.force_numpy:
        return NumpyDevice()
    if backend in ("auto", None):
        return AutoDevice()
    if backend in ("xla", "tpu", "cpu", "gpu", "axon"):
        platform = None if backend == "xla" else backend
        if platform == "tpu":
            # the tunnelled TPU registers as its own platform name on some
            # stacks (e.g. "axon"); accept the default device set only if
            # it actually is an accelerator — never silently run on CPU
            # when the user explicitly asked for TPU
            try:
                return XLADevice("tpu")
            except Exception:
                dev = XLADevice(None)
                if dev.platform == "cpu":
                    raise VelesError(
                        "backend 'tpu' requested but only CPU XLA devices "
                        "are present")
                return dev
        return XLADevice(platform)
    raise VelesError("unknown backend %r (have: %s)" %
                     (backend, sorted(BackendRegistry.backends)))


def AutoDevice() -> Device:
    """Priority: accelerated XLA > numpy (reference: veles/backends.py:406)."""
    global _auto_device
    if _auto_device is not None:
        return _auto_device
    try:
        _auto_device = XLADevice()
    except Exception as e:  # pragma: no cover - jax always importable here
        Logger().warning("XLA unavailable (%s); falling back to numpy", e)
        _auto_device = NumpyDevice()
    return _auto_device
