"""Downloader: fetch + unpack datasets at initialize.

Equivalent of the reference's veles/downloader.py:56-131 (Downloader
unit): link it before a loader; at initialize it ensures ``files`` exist
under ``directory``, downloading ``url`` (http(s)/file) and unpacking
archives (tar.*, zip) when they do not. Skips entirely when the files are
already present (idempotent re-runs)."""

from __future__ import annotations

import os
import shutil
import tarfile
import urllib.request
import zipfile
from typing import Sequence

from .config import root
from .error import VelesError
from .units import Unit


class Downloader(Unit):
    MAPPING = "downloader"

    def __init__(self, workflow, url: str = "", directory: str = None,
                 files: Sequence[str] = (), **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.url = url
        self.directory = directory or root.common.dirs.datasets
        self.files = list(files)

    def _have_all(self) -> bool:
        return all(os.path.exists(os.path.join(self.directory, f))
                   for f in self.files)

    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        if self.files and self._have_all():
            self.debug("%s: all files present in %s", self.name,
                       self.directory)
            return None
        if not self.url:
            raise VelesError("%s: files missing from %s and no url set"
                             % (self.name, self.directory))
        os.makedirs(self.directory, exist_ok=True)
        local = os.path.join(self.directory, os.path.basename(self.url))
        if not os.path.exists(local):
            self.info("downloading %s → %s", self.url, local)
            tmp = local + ".part"
            with urllib.request.urlopen(self.url) as rin, \
                    open(tmp, "wb") as fout:
                shutil.copyfileobj(rin, fout)
            os.replace(tmp, local)
        self._unpack(local)
        if self.files and not self._have_all():
            raise VelesError("%s: %s still missing after download"
                             % (self.name, self.files))
        return None

    def _unpack(self, path: str) -> None:
        if tarfile.is_tarfile(path):
            self.info("unpacking tar %s", path)
            with tarfile.open(path) as tin:
                tin.extractall(self.directory, filter="data")
        elif zipfile.is_zipfile(path):
            self.info("unpacking zip %s", path)
            with zipfile.ZipFile(path) as zin:
                zin.extractall(self.directory)

    def run(self) -> None:
        pass
