"""Downloader: fetch + unpack datasets at initialize.

Equivalent of the reference's veles/downloader.py:56-131 (Downloader
unit): link it before a loader; at initialize it ensures ``files`` exist
under ``directory``, downloading ``url`` (http(s)/file) and unpacking
archives (tar.*, zip) when they do not. Skips entirely when the files are
already present (idempotent re-runs).

Resilience (the reference did one bare ``urlopen`` with no timeout):
every attempt carries an explicit socket timeout, attempts are retried
under a :class:`~veles_tpu.resilience.retry.RetryPolicy` (exponential
backoff + jitter), an interrupted transfer resumes its ``.part`` file
via a Range request, a size-mismatched ``.part`` is deleted (stale
partials never survive), and an optional ``sha256`` kwarg verifies the
finished download before it is committed. The ``download`` fault point
fires before each attempt (inside the retry loop, so injected faults
exercise the retry path)."""

from __future__ import annotations

import os
import shutil
import tarfile
import urllib.error
import urllib.request
import zipfile
from typing import Optional, Sequence

from .config import root
from .error import VelesError
from .resilience.checkpoint_chain import file_sha256
from .resilience.faults import fire as fire_fault
from .resilience.retry import RetryPolicy, TransientError
from .units import Unit


class Downloader(Unit):
    MAPPING = "downloader"

    def __init__(self, workflow, url: str = "", directory: str = None,
                 files: Sequence[str] = (), timeout: float = None,
                 sha256: Optional[str] = None,
                 retry: Optional[RetryPolicy] = None, **kwargs) -> None:
        super().__init__(workflow, **kwargs)
        self.view_group = "SERVICE"
        self.url = url
        self.directory = directory or root.common.dirs.datasets
        self.files = list(files)
        self.timeout = float(timeout if timeout is not None
                             else root.common.resilience.get(
                                 "download_timeout", 60.0) or 60.0)
        #: expected hex digest of the downloaded archive; verified
        #: before the .part file is committed
        self.sha256 = sha256.lower() if sha256 else None
        # timeouts/resets/5xx retry; a 4xx is the caller's mistake and
        # must fail immediately, not after the whole backoff budget
        self.retry = retry or RetryPolicy(
            name=self.name + ".download",
            retry_if=lambda e: not (isinstance(e, urllib.error.HTTPError)
                                    and e.code < 500))

    def _have_all(self) -> bool:
        return all(os.path.exists(os.path.join(self.directory, f))
                   for f in self.files)

    def initialize(self, **kwargs):
        res = super().initialize(**kwargs)
        if res:
            return res
        if self.files and self._have_all():
            self.debug("%s: all files present in %s", self.name,
                       self.directory)
            return None
        if not self.url:
            raise VelesError("%s: files missing from %s and no url set"
                             % (self.name, self.directory))
        os.makedirs(self.directory, exist_ok=True)
        local = os.path.join(self.directory, os.path.basename(self.url))
        if not os.path.exists(local):
            self.info("downloading %s → %s", self.url, local)
            self.retry.call(self._fetch_once, local)
            self._commit(local)
        self._unpack(local)
        if self.files and not self._have_all():
            raise VelesError("%s: %s still missing after download"
                             % (self.name, self.files))
        return None

    # -- one retried attempt --------------------------------------------------
    @staticmethod
    def _discard(*paths: str) -> None:
        for p in paths:
            try:
                os.unlink(p)
            except OSError:
                pass

    def _fetch_once(self, local: str) -> None:
        fire_fault("download")
        tmp = local + ".part"
        meta = tmp + ".meta"        # resume validator (ETag/Last-Mod)
        offset = os.path.getsize(tmp) if os.path.exists(tmp) else 0
        validator = None
        if offset:
            try:
                with open(meta) as fin:
                    validator = fin.read().strip() or None
            except OSError:
                validator = None
            if validator is None:
                # resuming without a validator could stitch bytes from
                # two VERSIONS of the resource into one file — restart
                self._discard(tmp)
                offset = 0
        headers = {}
        if offset:
            headers["Range"] = "bytes=%d-" % offset
            # If-Range: the server sends 206 only if the resource is
            # unchanged; otherwise a fresh 200 body replaces the .part
            headers["If-Range"] = validator
        req = urllib.request.Request(self.url, headers=headers)
        try:
            rin = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            if e.code == 416:
                # complete-or-bogus .part (range not satisfiable):
                # clear it so the retried attempt starts clean
                self._discard(tmp, meta)
                raise TransientError(
                    "%s: HTTP 416 resuming at byte %d — stale .part "
                    "deleted" % (self.name, offset)) from e
            raise
        with rin:
            status = getattr(rin, "status", 200)
            if offset and status != 206:
                offset = 0          # changed/no-range: restart from 0
            if status != 206:
                val = (rin.headers.get("ETag")
                       or rin.headers.get("Last-Modified"))
                if val:
                    with open(meta, "w") as fout:
                        fout.write(val)
                else:
                    self._discard(meta)
            expected = rin.headers.get("Content-Length")
            expected = (int(expected) + offset
                        if expected is not None else None)
            with open(tmp, "ab" if offset else "wb") as fout:
                shutil.copyfileobj(rin, fout)
        size = os.path.getsize(tmp)
        if expected is not None and size != expected:
            # stale/truncated partial: delete it so the retried attempt
            # starts clean instead of resuming garbage
            self._discard(tmp, meta)
            raise TransientError(
                "%s: got %d bytes, expected %d — stale .part deleted"
                % (self.name, size, expected))

    def _commit(self, local: str) -> None:
        """Verify (when a digest was declared) and atomically publish
        the finished ``.part`` file."""
        tmp = local + ".part"
        if self.sha256:
            digest = file_sha256(tmp)
            if digest != self.sha256:
                self._discard(tmp, tmp + ".meta")
                raise VelesError(
                    "%s: SHA-256 mismatch for %s (got %s, want %s) — "
                    "stale .part deleted; the source changed or the "
                    "pinned digest is wrong" % (self.name, self.url,
                                                digest, self.sha256))
        os.replace(tmp, local)
        self._discard(tmp + ".meta")

    def _unpack(self, path: str) -> None:
        if tarfile.is_tarfile(path):
            self.info("unpacking tar %s", path)
            with tarfile.open(path) as tin:
                tin.extractall(self.directory, filter="data")
        elif zipfile.is_zipfile(path):
            self.info("unpacking zip %s", path)
            with zipfile.ZipFile(path) as zin:
                zin.extractall(self.directory)

    def run(self) -> None:
        pass
