"""Launcher: run-mode resolution and workflow lifecycle.

Equivalent of the reference's veles/launcher.py:100-906. Mode resolution
simplifies radically: the reference arbitrated standalone/master/slave and
spawned slaves over SSH; here every process is a peer in one SPMD job
(jax distributed runtime), so the modes are standalone vs multi-host
participant (+ train vs test). Preserved surface: device creation,
workflow initialize ordering, snapshot resume, graceful stop, results
gathering/reporting, elapsed/timing reporting, status beacon hook.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, Optional

from .backends import Device_for, XLADevice
from .config import root
from .logger import Logger
from . import prng
from .parallel import distributed


class Launcher(Logger):
    def __init__(self, backend: Optional[str] = None,
                 mesh: Optional[Dict[str, int]] = None,
                 coordinator: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 random_seed: Optional[int] = None,
                 test_mode: bool = False) -> None:
        super().__init__()
        self.test_mode = test_mode
        self.workflow = None
        self.device = None
        self._backend = backend
        self._mesh = mesh
        self._dist = (coordinator, num_processes, process_id)
        if random_seed is not None:
            prng.seed_all(random_seed)
        self._start_time = None
        self.stopped = False
        self.interrupted = False

    # -- lifecycle -----------------------------------------------------------
    def make_device(self):
        """Distributed init + device/mesh resolution; shared by the normal
        path and the meta-learning modes (--optimize/--ensemble-*)."""
        from .error import VelesError
        coordinator, nproc, pid = self._dist
        distributed.initialize_multihost(coordinator, nproc, pid)
        if self._mesh:
            if self._backend == "numpy" or root.common.engine.force_numpy:
                raise VelesError(
                    "--mesh requires an XLA backend; it cannot combine "
                    "with numpy/--force-numpy")
            platform = (self._backend
                        if self._backend in ("cpu", "tpu") else None)
            self.device = XLADevice(platform=platform,
                                    mesh_axes=self._mesh)
        else:
            self.device = Device_for(self._backend)
        return self.device

    def initialize(self, workflow) -> None:
        self.make_device()
        self.workflow = workflow
        workflow.initialize(device=self.device)
        distributed.verify_checksums(workflow)
        if self.test_mode:
            self._enter_test_mode(workflow)
        self.event("launcher.initialize", "single",
                   device=self.device.name,
                   processes=distributed.process_count())

    def _enter_test_mode(self, workflow) -> None:
        """--test: one evaluation-only pass — no parameter updates
        (reference test mode, veles/launcher.py mode resolution)."""
        step = getattr(workflow, "train_step", None)
        decision = getattr(workflow, "decision", None)
        if step is not None:
            step.evaluation_mode = True
        if decision is not None:
            decision.max_epochs = decision.epoch_number + 1

    def resume(self, snapshot_path: str) -> None:
        from .snapshotter import resume
        resume(self.workflow, snapshot_path)
        decision = getattr(self.workflow, "decision", None)
        if decision is not None:
            decision.complete <<= False
        self.info("resumed from %s", snapshot_path)

    def run(self) -> Dict[str, Any]:
        self._start_time = time.time()
        self.event("launcher.work", "begin")
        try:
            self.workflow.run()
        except KeyboardInterrupt:
            self.warning("interrupted — stopping workflow")
            self.workflow.stop()
            self.interrupted = True
        finally:
            self.event("launcher.work", "end")
            self.stopped = True
        elapsed = time.time() - self._start_time
        self.info("elapsed: %.1fs", elapsed)
        results = self.workflow.gather_results()
        results["elapsed_sec"] = round(elapsed, 3)
        if self.interrupted:
            results["interrupted"] = True
        return results

    def stop(self) -> None:
        if self.workflow is not None:
            self.workflow.stop()
        self.stopped = True

    # -- reporting -----------------------------------------------------------
    def write_results(self, results: Dict[str, Any], path: str) -> None:
        """--result-file (reference: veles/workflow.py:827-849)."""
        if not distributed.is_coordinator():
            return
        with open(path, "w") as fout:
            json.dump(results, fout, indent=2, default=str)
        self.info("results → %s", path)

    def print_stats(self) -> None:
        self.workflow.print_stats()
