"""Launcher: run-mode resolution and workflow lifecycle.

Equivalent of the reference's veles/launcher.py:100-906. Mode resolution
simplifies radically: the reference arbitrated standalone/master/slave and
spawned slaves over SSH; here every process is a peer in one SPMD job
(jax distributed runtime), so the modes are standalone vs multi-host
participant (+ train vs test). Preserved surface: device creation,
workflow initialize ordering, snapshot resume, graceful stop, results
gathering/reporting, elapsed/timing reporting, status beacon hook.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from .backends import Device_for, XLADevice
from .config import root
from .logger import Logger
from . import prng
from .parallel import distributed


class Launcher(Logger):
    def __init__(self, backend: Optional[str] = None,
                 mesh: Optional[Dict[str, int]] = None,
                 coordinator: Optional[str] = None,
                 num_processes: Optional[int] = None,
                 process_id: Optional[int] = None,
                 random_seed: Optional[int] = None,
                 test_mode: bool = False,
                 graphics: bool = False,
                 plots_dir: Optional[str] = None,
                 status_url: Optional[str] = None,
                 notification_interval: float = 10.0,
                 profile_dir: Optional[str] = None) -> None:
        super().__init__()
        self.test_mode = test_mode
        self.workflow = None
        self.device = None
        self._graphics_enabled = graphics
        self._plots_dir = plots_dir
        self.graphics_server = None
        self._status_url = status_url
        self._notification_interval = notification_interval
        self.status_reporter = None
        self._backend = backend
        self._mesh = mesh
        #: XPlane trace capture (SURVEY.md §5.1 TPU mapping of the
        #: reference's event spans + --timings): device timeline,
        #: compiled-op breakdown, host/device overlap
        self._profile_dir = profile_dir
        self._dist = (coordinator, num_processes, process_id)
        if random_seed is not None:
            prng.seed_all(random_seed)
        self._start_time = None
        self.stopped = False
        self.interrupted = False

    # -- lifecycle -----------------------------------------------------------
    def make_device(self):
        """Distributed init + device/mesh resolution; shared by the normal
        path and the meta-learning modes (--optimize/--ensemble-*)."""
        from .error import VelesError
        from .backends import guard_unresponsive_backend
        # a dead accelerator transport (e.g. a collapsed TPU tunnel
        # relay) makes in-process device enumeration HANG, not raise —
        # probe in a killable subprocess before the first backend init
        # so a training launch degrades to CPU with a warning instead
        # of freezing (failure-detection story, SURVEY.md §5.3)
        guard_unresponsive_backend()
        coordinator, nproc, pid = self._dist
        distributed.initialize_multihost(coordinator, nproc, pid)
        if self._mesh:
            if self._backend == "numpy" or root.common.engine.force_numpy:
                raise VelesError(
                    "--mesh requires an XLA backend; it cannot combine "
                    "with numpy/--force-numpy")
            platform = (self._backend
                        if self._backend in ("cpu", "tpu") else None)
            self.device = XLADevice(platform=platform,
                                    mesh_axes=self._mesh)
        else:
            self.device = Device_for(self._backend)
        return self.device

    def initialize(self, workflow) -> None:
        self.make_device()
        self.workflow = workflow
        if self._graphics_enabled and not root.common.disable.plotting:
            from .graphics import GraphicsServer
            self.graphics_server = GraphicsServer()
            workflow.graphics = self.graphics_server
            # per-run default dir: a shared cache/plots would let the
            # newest-by-mtime gallery pick up a CONCURRENT run's PNGs
            # and misattribute them on the drill-down page
            plots_dir = self._plots_dir or os.path.join(
                root.common.dirs.cache, "plots",
                "%s@%d" % (getattr(workflow, "name", "wf"), os.getpid()))
            self.graphics_server.launch_client(out_dir=plots_dir)
        workflow.initialize(device=self.device)
        distributed.verify_checksums(workflow)
        self._arm_failure_hooks(workflow)
        if self._status_url and distributed.is_coordinator():
            from .web_status import StatusReporter
            self.status_reporter = StatusReporter(
                self._status_url, self._notification_interval)
            self.status_reporter.start_periodic(self._status_payload)
        if self.test_mode:
            self._enter_test_mode(workflow)
        self.event("launcher.initialize", "single",
                   device=self.device.name,
                   processes=distributed.process_count())

    def _enter_test_mode(self, workflow) -> None:
        """--test: one evaluation-only pass — no parameter updates
        (reference test mode, veles/launcher.py mode resolution)."""
        step = getattr(workflow, "train_step", None)
        decision = getattr(workflow, "decision", None)
        if step is not None:
            step.evaluation_mode = True
        if decision is not None:
            decision.max_epochs = decision.epoch_number + 1

    def _arm_failure_hooks(self, workflow) -> None:
        """Production wiring of the failure story (SURVEY.md §5.3): every
        TrainStep dispatch runs under the hang watchdog (the reference's
        job-timeout dropper, veles/server.py:619-635, as a local monitor),
        passes the ``dispatch`` fault-injection point, beats the health
        registry, and — when --slave-death-probability is set — rolls the
        legacy fault-injection die (veles/client.py:303-307)."""
        step = getattr(workflow, "train_step", None)
        if step is None or getattr(step, "_failure_hooks_armed", False):
            return
        from .resilience import elastic
        from .resilience.faults import fire as fire_fault
        from .resilience.health import heartbeats
        death_p = float(
            root.common.get("slave_death_probability", 0.0) or 0.0)
        timeout = float(root.common.get("job_timeout", 0.0) or 0.0)
        elastic_on = elastic.enabled()
        host_beat = None
        if elastic_on:
            try:
                import jax
                host_beat = (elastic.HOST_BEAT_PREFIX
                             + str(jax.process_index()))
            except Exception:         # noqa: BLE001 — numpy backend
                host_beat = elastic.HOST_BEAT_PREFIX + "0"
        #: run()'s finally unregisters it — a completed run's host beat
        #: must not age into a false /healthz failure on a process that
        #: keeps serving
        self._host_beat = host_beat
        self.step_history = []      # per-dispatch wall times (telemetry)
        inner_run = step.run

        def armed_run():
            fire_fault("dispatch")
            if elastic_on:
                # elastic plane: this host's liveness beat + one
                # host-loss probe per dispatch (injected faults and
                # lapsed host:* heartbeats raise HostLostError, which
                # ends the generation — resilience/elastic.py)
                heartbeats.beat(host_beat)
                elastic.check_hosts()
            with distributed.step_watchdog(
                    step.name, timeout=timeout, history=self.step_history):
                inner_run()
            heartbeats.beat("train_step")
            if death_p > 0:
                distributed.fault_injection(death_p)
        step.run = armed_run
        step._failure_hooks_armed = True

    def try_restore_latest(self) -> bool:
        """Elastic restart: resume from the newest snapshot in the
        configured snapshot directory, if any (preemption/crash recovery —
        the reference's 'recover from any disaster' story,
        docs/manualrst_veles_distributed_training.rst:10)."""
        wf = self.workflow
        directory, prefix = root.common.dirs.snapshots, "wf"
        from .snapshotter import Snapshotter, SnapshotterToDB, resume
        snap_unit = None
        for u in getattr(wf, "units", ()):
            if isinstance(u, Snapshotter):
                snap_unit = u
                directory, prefix = u.directory, u.prefix
                break
        if snap_unit is None:
            # restoring works off bare directory contents, but WRITING
            # needs a Snapshotter unit: a user running with
            # --snapshot-dir and none linked thinks they have disaster
            # recovery and doesn't
            self.warning(
                "workflow %r has no Snapshotter unit — snapshots will "
                "NOT be written this run; link "
                "vt.Snapshotter(None, prefix=...) (directory defaults "
                "to the --snapshot-dir / root.common.dirs.snapshots "
                "setting) via StandardWorkflow(snapshotter_unit=...)",
                getattr(wf, "name", "?"))
        if isinstance(snap_unit, SnapshotterToDB):
            # DB sink: newest row in the sqlite store
            dsn = snap_unit._resolve_dsn()
            if not os.path.exists(dsn):
                return False
            try:
                resume(wf, "sqlite://" + dsn)
            except FileNotFoundError:
                return False
        else:
            if not directory or not os.path.isdir(directory):
                return False
            if not distributed.restore_latest(wf, directory, prefix):
                return False
        decision = getattr(wf, "decision", None)
        if decision is not None:
            decision.complete <<= False
        #: where the chain lives — the elastic controller logs the
        #: manifest cursor of this chain at generation handoffs
        self._last_restore_dir = directory
        self._last_restore_prefix = prefix
        self.info("auto-resumed from latest snapshot in %s", directory)
        return True

    def resume(self, snapshot_path: str) -> None:
        from .resilience.checkpoint_chain import SnapshotCorruptError
        from .snapshotter import resume
        try:
            resume(self.workflow, snapshot_path)
        except (FileNotFoundError, SnapshotCorruptError) as e:
            # elastic rerun idempotency: resuming via the `_current`
            # link after the previous run quarantined its target (the
            # link dangles, or points at a not-yet-quarantined corrupt
            # file) must skip straight to the older valid snapshot in
            # the same chain instead of killing the relaunch
            base = os.path.basename(snapshot_path)
            if "_current.pickle" not in base:
                raise
            prefix = base.split("_current.pickle")[0]
            directory = os.path.dirname(snapshot_path) or "."
            self.warning(
                "snapshot link %s is unusable (%s: %s) — falling back "
                "to the newest valid snapshot of chain %r in %s",
                snapshot_path, type(e).__name__, e, prefix, directory)
            from .resilience.checkpoint_chain import (
                restore_latest as walk)
            restored = walk(self.workflow, directory, prefix)
            if restored is None:
                raise
            self._last_restore_dir = directory
            self._last_restore_prefix = prefix
            snapshot_path = restored   # log the REAL source, not the
            # dead link — quarantine forensics must name the snapshot
            # the run actually resumed from
        decision = getattr(self.workflow, "decision", None)
        if decision is not None:
            decision.complete <<= False
        self.info("resumed from %s", snapshot_path)

    def run_elastic(self) -> Dict[str, Any]:
        """Run under the elastic generation controller
        (resilience/elastic.py): on detected host loss the run resumes
        from the newest valid checkpoint in a new generation instead
        of dying — ``--elastic`` /
        ``root.common.resilience.elastic.enabled``."""
        from .resilience.elastic import ElasticController
        return ElasticController(self).run()

    def run(self, keep_services: bool = False) -> Dict[str, Any]:
        """``keep_services=True`` (elastic generations) defers the
        plotter/graphics/status teardown to :meth:`finalize_services`
        — generation 2..N must keep the dashboard and beacon alive,
        not train against services generation 1's finally killed."""
        from .resilience.health import heartbeats
        from .telemetry.recorder import flight
        # preemption forensics: a SIGTERM (the k8s/preemption kill)
        # dumps the flight recorder before the previous disposition
        # runs — only when autodump is armed (crash_dump gates itself)
        if flight.autodump_enabled():
            flight.install_sigterm()
        self._start_time = time.time()
        heartbeats.beat("launcher")
        self.event("launcher.work", "begin")
        profiling = False
        if self._profile_dir:
            try:
                import jax
                jax.profiler.start_trace(self._profile_dir)
                profiling = True
                self.info("profiler trace → %s", self._profile_dir)
            except Exception as e:
                self.warning("profiler unavailable: %s", e)
        try:
            self.workflow.run()
        except KeyboardInterrupt:
            self.warning("interrupted — stopping workflow")
            self.workflow.stop()
            self.interrupted = True
        finally:
            if profiling:
                try:
                    import jax
                    jax.profiler.stop_trace()
                except Exception as e:
                    self.warning("profiler stop failed: %s", e)
            self.event("launcher.work", "end")
            self.stopped = True
            if not keep_services:
                self.finalize_services()
            # the run is over (completed OR raised) — these beats are
            # not hangs; leaving them registered would age into a false
            # /healthz failure on any long-lived process
            heartbeats.unregister("launcher")
            heartbeats.unregister("train_step")
            if getattr(self, "_host_beat", None):
                heartbeats.unregister(self._host_beat)
        elapsed = time.time() - self._start_time
        self.info("elapsed: %.1fs", elapsed)
        results = self.workflow.gather_results()
        results["elapsed_sec"] = round(elapsed, 3)
        if self.interrupted:
            results["interrupted"] = True
        return results

    def finalize_services(self) -> None:
        """Final plot redraws, graphics shutdown, last status beacon —
        the once-per-JOB half of run()'s teardown. Idempotent: the
        elastic controller calls it after the last generation."""
        from .plotter import Plotter
        for u in getattr(self.workflow, "units", ()):
            if isinstance(u, Plotter):
                try:
                    u.finalize()
                except Exception as e:   # noqa: BLE001 — best effort
                    self.warning("final redraw of %s failed: %s",
                                 u.name, e)
        if self.graphics_server is not None:
            self.graphics_server.shutdown()
            self.graphics_server = None
        if self.status_reporter is not None:
            self.status_reporter.send(self._status_payload())
            self.status_reporter.stop()
            self.status_reporter = None

    def stop(self) -> None:
        if self.workflow is not None:
            self.workflow.stop()
        self.stopped = True

    def _status_payload(self) -> Dict[str, Any]:
        """Beacon body (reference: veles/launcher.py:852-885)."""
        wf = self.workflow
        decision = getattr(wf, "decision", None)
        metric = None
        if decision is not None:
            try:
                values = decision.get_metric_values()
                for key in ("best_err", "best_rmse", "err", "rmse"):
                    if key in values:
                        metric = values[key]
                        break
            except Exception:
                metric = None
        payload = {
            "id": "%s@%d" % (getattr(wf, "name", "?"), os.getpid()),
            "name": getattr(wf, "name", "?"),
            "device": getattr(self.device, "name", None),
            "epoch": getattr(decision, "epoch_number", None),
            "metric": metric,
            "elapsed_sec": (round(time.time() - self._start_time, 1)
                            if self._start_time else 0.0),
            "stopped": self.stopped,
        }
        # drill-down detail (reference: the web/ app's per-master pages
        # served unit tables and event/log views, veles/web_status.py:
        # 66-111): per-unit timing, recent event spans, and the latest
        # rendered plots ride the same stateless beacon
        try:
            payload["units"] = [
                {"name": n, "cls": c, "runs": r, "time_s": round(t, 4)}
                for t, n, c, r in sorted(
                    ((u.timers.get("run", 0.0), u.name,
                      type(u).__name__, u.run_count) for u in wf),
                    reverse=True)[:40]]
        except Exception:       # a half-built workflow must not kill
            pass                # the beacon thread
        from .logger import events
        payload["events"] = [
            {"name": e.get("name"), "type": e.get("type"),
             "time": e.get("time"), "who": e.get("who")}
            for e in events()[-60:]]
        plots = self._plot_payload()
        if plots is not None:
            payload["plots"] = plots
        return payload

    def _plot_payload(self, max_plots: int = 6,
                      max_bytes: int = 150_000):
        """Newest rendered plot PNGs, inlined base64 so the dashboard
        works across hosts (the reference backed its gallery with
        Mongo-stored blobs for the same reason). Returns None when the
        plot set is unchanged since the last beacon — the key is then
        omitted and the server carries the previous gallery forward,
        so steady-state ticks don't re-ship megabytes of identical
        PNGs. Every REFRESH_EVERY-th beacon re-ships regardless: the
        signature lives launcher-side, so a restarted web-status server
        (carried-forward state lost) would otherwise show an empty
        gallery until some plot file changed (ADVICE r4)."""
        import base64
        import glob as _glob

        REFRESH_EVERY = 10
        self._plot_beacons = getattr(self, "_plot_beacons", -1) + 1
        force = self._plot_beacons % REFRESH_EVERY == 0

        def mtime(p):
            # the renderer rewrites files concurrently: a vanished path
            # must not kill the beacon thread via the sort key
            try:
                return os.path.getmtime(p)
            except OSError:
                return 0.0

        gs = self.graphics_server
        out_dir = getattr(gs, "out_dir", None) if gs is not None else None
        if not out_dir or not os.path.isdir(out_dir):
            pngs = []
        else:
            pngs = sorted(_glob.glob(os.path.join(out_dir, "*.png")),
                          key=mtime, reverse=True)[:max_plots]
        signature = tuple((p, mtime(p)) for p in pngs)
        if not force and \
                signature == getattr(self, "_plot_signature", None):
            return None
        self._plot_signature = signature
        out = []
        for p in pngs:
            try:
                if os.path.getsize(p) > max_bytes:
                    continue
                with open(p, "rb") as fin:
                    out.append({
                        "name": os.path.basename(p),
                        "png_b64": base64.b64encode(
                            fin.read()).decode()})
            except OSError:
                continue
        return out

    # -- reporting -----------------------------------------------------------
    def write_results(self, results: Dict[str, Any], path: str) -> None:
        """--result-file (reference: veles/workflow.py:827-849)."""
        if not distributed.is_coordinator():
            return
        from .json_encoders import NumpyJSONEncoder
        with open(path, "w") as fout:
            json.dump(results, fout, indent=2, cls=NumpyJSONEncoder)
        self.info("results → %s", path)

    def print_stats(self) -> None:
        self.workflow.print_stats()
