"""Dataset acquisition for the bundled model zoo.

The reference's Downloader unit fetched datasets over HTTP at initialize
time (veles/downloader.py:56). This environment has no egress, so each
loader here: (1) looks for the real dataset in the canonical cache
locations (keras/torchvision layouts + root.common.dirs.datasets), and
(2) otherwise synthesizes a deterministic surrogate with identical shapes,
dtypes and class structure — so every workflow, test and benchmark runs
end-to-end anywhere; throughput numbers are shape-dependent only.
"""

from __future__ import annotations

import gzip
import os
import struct
from typing import Optional, Tuple

import numpy

from .config import root
from . import prng

Arrays = Tuple[numpy.ndarray, numpy.ndarray, numpy.ndarray, numpy.ndarray]


def _dataset_dirs():
    yield root.common.dirs.datasets
    yield os.path.expanduser("~/.keras/datasets")
    yield os.path.expanduser("~/data")
    yield "/root/.veles_tpu/datasets"


def _find(*names: str) -> Optional[str]:
    for d in _dataset_dirs():
        for n in names:
            p = os.path.join(d, n)
            if os.path.exists(p):
                return p
    return None


def _read_idx(path: str) -> numpy.ndarray:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return numpy.frombuffer(f.read(), dtype=numpy.uint8).reshape(shape)


def load_mnist(flat: bool = True) -> Arrays:
    """(train_x, train_y, test_x, test_y); x float32 in [0,1),
    shape (N, 784) or (N, 28, 28, 1)."""
    npz = _find("mnist.npz")
    if npz is not None:
        with numpy.load(npz) as d:
            tx, ty = d["x_train"], d["y_train"]
            vx, vy = d["x_test"], d["y_test"]
    else:
        idx = _find("train-images-idx3-ubyte.gz", "train-images-idx3-ubyte")
        if idx is not None:
            base = os.path.dirname(idx)

            def g(n):
                p = os.path.join(base, n + ".gz")
                return _read_idx(p if os.path.exists(p)
                                 else os.path.join(base, n))
            tx = g("train-images-idx3-ubyte")
            ty = g("train-labels-idx1-ubyte")
            vx = g("t10k-images-idx3-ubyte")
            vy = g("t10k-labels-idx1-ubyte")
        else:
            return _synthetic_images((28, 28), 10, 60000, 10000, flat,
                                     key="mnist")
    tx = tx.astype(numpy.float32) / 255.0
    vx = vx.astype(numpy.float32) / 255.0
    if flat:
        tx, vx = tx.reshape(len(tx), -1), vx.reshape(len(vx), -1)
    else:
        tx, vx = tx[..., None], vx[..., None]
    return tx, ty.astype(numpy.int32), vx, vy.astype(numpy.int32)


def load_cifar10(n_train: int = 50000, n_test: int = 10000) -> Arrays:
    """(train_x, train_y, test_x, test_y); x float32 NHWC (N,32,32,3)."""
    d = _find("cifar-10-batches-py")
    if d is not None:
        import pickle
        xs, ys = [], []
        for i in range(1, 6):
            with open(os.path.join(d, "data_batch_%d" % i), "rb") as f:
                b = pickle.load(f, encoding="bytes")
            xs.append(b[b"data"])
            ys.extend(b[b"labels"])
        tx = numpy.concatenate(xs)
        ty = numpy.asarray(ys, dtype=numpy.int32)
        with open(os.path.join(d, "test_batch"), "rb") as f:
            b = pickle.load(f, encoding="bytes")
        vx = numpy.asarray(b[b"data"])
        vy = numpy.asarray(b[b"labels"], dtype=numpy.int32)

        def fmt(x):
            return (x.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
                    .astype(numpy.float32) / 255.0)
        return fmt(tx), ty, fmt(vx), vy
    return _synthetic_images((32, 32, 3), 10, n_train, n_test, flat=False,
                             key="cifar10")


def load_synthetic(sample_shape, n_classes, n_train, n_test,
                   flat=False, key="synth") -> Arrays:
    """Public class-template surrogate generator (the same one the real
    loaders fall back to): zoo models for datasets absent in-image
    (AlexNet/ImageNet, STL-10) build on THIS, not the private helper."""
    return _synthetic_images(sample_shape, n_classes, n_train, n_test,
                             flat, key=key)


def _synthetic_images(sample_shape, n_classes, n_train, n_test, flat,
                      key="synth") -> Arrays:
    """Deterministic class-structured surrogate: each class is a smooth
    random template + per-sample noise, so simple models genuinely learn
    (error decreases) and shapes/throughput match the real dataset."""
    rng = numpy.random.RandomState(
        prng.RandomGenerator(key, seed=20260101).initial_seed)
    if len(sample_shape) == 2:
        full_shape = sample_shape + (1,)
    else:
        full_shape = sample_shape
    templates = rng.rand(n_classes, *full_shape).astype(numpy.float32)
    # smooth the templates a little so convs have structure to find
    for _ in range(2):
        templates = (templates +
                     numpy.roll(templates, 1, axis=1) +
                     numpy.roll(templates, 1, axis=2)) / 3.0

    def make(n, seed):
        r = numpy.random.RandomState(seed)
        y = r.randint(0, n_classes, n).astype(numpy.int32)
        x = templates[y] * 0.7 + 0.3 * r.rand(n, *full_shape).astype(
            numpy.float32)
        return x.astype(numpy.float32), y

    tx, ty = make(n_train, 1)
    vx, vy = make(n_test, 2)
    if len(sample_shape) == 2:
        tx, vx = tx[..., 0], vx[..., 0]
        if flat:
            tx, vx = tx.reshape(n_train, -1), vx.reshape(n_test, -1)
        else:
            tx, vx = tx[..., None], vx[..., None]
    return tx, ty, vx, vy


def mnist_is_real() -> bool:
    return _find("mnist.npz", "train-images-idx3-ubyte.gz",
                 "train-images-idx3-ubyte") is not None


def cifar10_is_real() -> bool:
    return _find("cifar-10-batches-py") is not None
