"""Timed chaos storms for the load harness.

A storm is nothing but a ``window=T0:T1`` fault clause
(resilience/faults.py): the action arms between the T0-th and T1-th
trigger of an existing injection point and then HEALS — so "replica 2
dies mid-burst" or "page allocation fails for 30 admissions" are plain
specs, reproducible because the trigger count (not wall time) indexes
the storm. The harness arms every storm's clause on the process-global
fault plane for the run and restores whatever spec was active before.
"""

from __future__ import annotations

import os
from typing import List, Sequence, Tuple

from ..resilience.faults import parse_spec, plane
from ..telemetry.counters import inc


class ChaosStorm:
    """One timed storm: ``point:action:window=T0:T1[,k=v...]``."""

    def __init__(self, point: str, action: str = "raise",
                 window: Tuple[int, int] = (0, 1),
                 p: float = 1.0) -> None:
        self.point = point
        self.action = action
        self.window = (int(window[0]), int(window[1]))
        self.p = float(p)
        # parse eagerly: a typo'd point/action fails at harness
        # CONSTRUCTION, not silently mid-run
        parse_spec(self.spec())

    def spec(self) -> str:
        clause = "%s:%s:window=%d:%d" % (self.point, self.action,
                                         *self.window)
        if self.p < 1.0:
            clause += ",p=%g" % self.p
        return clause

    def __repr__(self) -> str:
        return "<ChaosStorm %s>" % self.spec()


def parse_storm(text: str) -> ChaosStorm:
    """CLI-facing storm parser: a full fault clause with a mandatory
    ``window=`` field (``veles-tpu loadgen --storm ...``)."""
    faults = parse_spec(text)
    if len(faults) != 1:
        raise ValueError("one storm per --storm flag (got %r)" % text)
    fault = faults[0]
    if fault.window is None:
        raise ValueError(
            "a storm needs a window=T0:T1 field (got %r)" % text)
    return ChaosStorm(fault.point, fault.action,
                      window=fault.window, p=fault.p)


class StormPlan:
    """Arm a set of storms on the process-global fault plane for the
    duration of a run; context-manager shaped so the previous spec is
    ALWAYS restored (a crashed harness must not leave the fleet
    haunted). Arming goes through the ``VELES_FAULTS`` env var — the
    plane re-resolves env/config on every fire, so a bare
    ``plane.configure(text)`` would be reverted at the next call
    site; the env var (which WINS the resolution) sticks for the
    whole run. Storms therefore reach in-process fleets only; a
    remote replica wants the same clause in its own VELES_FAULTS."""

    def __init__(self, storms: Sequence[ChaosStorm]) -> None:
        self.storms: List[ChaosStorm] = list(storms)
        self._prior_env: "str | None" = None

    def spec(self) -> str:
        return ";".join(s.spec() for s in self.storms)

    def __enter__(self) -> "StormPlan":
        if self.storms:
            self._prior_env = os.environ.get("VELES_FAULTS")
            prior = plane.current_spec()
            combined = self.spec()
            if prior:
                combined = prior + ";" + combined
            os.environ["VELES_FAULTS"] = combined
            plane.configure()
            inc("veles_loadgen_storms_total", len(self.storms))
        return self

    def __exit__(self, *exc) -> None:
        if self.storms:
            if self._prior_env is None:
                os.environ.pop("VELES_FAULTS", None)
            else:
                os.environ["VELES_FAULTS"] = self._prior_env
            plane.configure()
