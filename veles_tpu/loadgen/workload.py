"""Deterministic open-loop workload synthesis for the load harness.

One :class:`Workload` is a SEEDED program: the same knobs + seed
produce the same arrival instants and the same request bodies, so a
stamped loadgen verdict is reproducible run-to-run (the ROADMAP's
"reproduces stamped p50/p99 within tolerance across two runs" gate
depends on exactly this).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy

#: arrival-rate shapes (offered load over the run's duration)
SHAPES = ("steady", "burst", "diurnal")


class Workload:
    """Synthesize ``n_requests`` request bodies plus their open-loop
    arrival offsets (seconds from harness start).

    - **prompt lengths** are Zipf-distributed (exponent ``zipf_a``)
      clipped to ``[min_prompt, max_prompt]`` — the heavy-tailed mix
      real traffic has (most prompts short, a long tail of huge ones);
    - **shared prefixes**: a ``shared_fraction`` of requests open with
      one of ``n_prefixes`` fixed ``prefix_len``-token system prompts,
      exercising the radix prefix / state-checkpoint caches;
    - **QoS mix**: ``batch_fraction`` of requests are labeled
      ``priority=batch`` (the rest interactive — the class the SLO
      verdict defends); interactive requests carry ``deadline_ms``
      when set;
    - **client mix**: ``stream_fraction`` stream (SSE), the rest
      buffer; ``sample_fraction`` decode with ``mode=sample`` at
      ``temperature`` (per-request seeds), the rest greedy;
    - **arrival shape**: ``steady`` (homogeneous Poisson at ``rate``),
      ``burst`` (a ``burst_fraction`` span mid-run at ``burst_factor``
      × rate), ``diurnal`` (sinusoidal modulation, one full period
      over the run) — all open-loop: the schedule never waits for
      answers.
    """

    def __init__(self, n_requests: int = 100, rate: float = 20.0,
                 shape: str = "steady", burst_factor: float = 4.0,
                 burst_fraction: float = 0.25,
                 diurnal_amplitude: float = 0.6,
                 zipf_a: float = 1.4, min_prompt: int = 4,
                 max_prompt: int = 64, n_new: int = 8,
                 shared_fraction: float = 0.5, prefix_len: int = 12,
                 n_prefixes: int = 3, vocab: int = 128,
                 batch_fraction: float = 0.5,
                 stream_fraction: float = 0.0,
                 sample_fraction: float = 0.25,
                 temperature: float = 0.8,
                 deadline_ms: Optional[float] = None,
                 seed: int = 0) -> None:
        if shape not in SHAPES:
            raise ValueError("shape must be one of %s" % (SHAPES,))
        if not 1 <= min_prompt <= max_prompt:
            raise ValueError("need 1 <= min_prompt <= max_prompt")
        if rate <= 0:
            raise ValueError("rate must be > 0 req/s")
        self.n_requests = int(n_requests)
        self.rate = float(rate)
        self.shape = shape
        self.burst_factor = float(burst_factor)
        self.burst_fraction = min(1.0, max(0.0, float(burst_fraction)))
        self.diurnal_amplitude = min(0.95, max(0.0,
                                               float(diurnal_amplitude)))
        self.zipf_a = float(zipf_a)
        self.min_prompt = int(min_prompt)
        self.max_prompt = int(max_prompt)
        self.n_new = int(n_new)
        self.shared_fraction = min(1.0, max(0.0,
                                            float(shared_fraction)))
        self.prefix_len = min(int(prefix_len), self.min_prompt)
        self.n_prefixes = max(1, int(n_prefixes))
        self.vocab = int(vocab)
        self.batch_fraction = min(1.0, max(0.0, float(batch_fraction)))
        self.stream_fraction = min(1.0, max(0.0,
                                            float(stream_fraction)))
        self.sample_fraction = min(1.0, max(0.0,
                                            float(sample_fraction)))
        self.temperature = float(temperature)
        self.deadline_ms = (None if deadline_ms is None
                            else float(deadline_ms))
        self.seed = int(seed)

    def _rate_at(self, frac: float) -> float:
        """Offered rate at run fraction ``frac`` in [0, 1)."""
        if self.shape == "burst":
            lo = 0.5 - self.burst_fraction / 2.0
            hi = 0.5 + self.burst_fraction / 2.0
            return self.rate * (self.burst_factor
                                if lo <= frac < hi else 1.0)
        if self.shape == "diurnal":
            return self.rate * (1.0 + self.diurnal_amplitude
                                * math.sin(2.0 * math.pi * frac))
        return self.rate

    def arrivals(self) -> List[float]:
        """Open-loop arrival offsets (seconds, sorted ascending)."""
        rng = numpy.random.RandomState(self.seed)
        out, t = [], 0.0
        for i in range(self.n_requests):
            rate = max(1e-6, self._rate_at(i / max(1, self.n_requests)))
            t += float(rng.exponential(1.0 / rate))
            out.append(t)
        return out

    def _prompt_len(self, rng) -> int:
        span = self.max_prompt - self.min_prompt
        if span == 0:
            return self.min_prompt
        # Zipf over the EXTRA length past min_prompt, clipped to the
        # span: heavy-tailed, bounded, seeded
        extra = int(rng.zipf(self.zipf_a)) - 1
        return self.min_prompt + min(span, extra)

    def requests(self) -> List[Dict[str, Any]]:
        """The request bodies, index-aligned with :meth:`arrivals`.
        Seeded independently of the arrival stream so changing the
        shape never reshuffles the prompts."""
        rng = numpy.random.RandomState(self.seed + 1)
        prefixes = [
            [int(x) for x in rng.randint(1, self.vocab,
                                         size=self.prefix_len)]
            for _ in range(self.n_prefixes)]
        out = []
        for i in range(self.n_requests):
            t_p = self._prompt_len(rng)
            prompt = [int(x) for x in rng.randint(1, self.vocab,
                                                  size=t_p)]
            if self.prefix_len and rng.rand() < self.shared_fraction:
                pfx = prefixes[int(rng.randint(self.n_prefixes))]
                prompt[:len(pfx)] = pfx
            body: Dict[str, Any] = {
                "prompt": prompt, "n_new": self.n_new,
                "priority": ("batch"
                             if rng.rand() < self.batch_fraction
                             else "interactive"),
            }
            if rng.rand() < self.sample_fraction:
                body["mode"] = "sample"
                body["temperature"] = self.temperature
                body["seed"] = int(rng.randint(1, 2 ** 31 - 1))
            else:
                body["mode"] = "greedy"
            if rng.rand() < self.stream_fraction:
                body["stream"] = True
            if self.deadline_ms is not None \
                    and body["priority"] == "interactive":
                body["deadline_ms"] = self.deadline_ms
            out.append(body)
        return out

    def describe(self) -> Dict[str, Any]:
        """The knob block, stamped into every loadgen report."""
        return {
            "n_requests": self.n_requests, "rate": self.rate,
            "shape": self.shape, "zipf_a": self.zipf_a,
            "min_prompt": self.min_prompt,
            "max_prompt": self.max_prompt, "n_new": self.n_new,
            "shared_fraction": self.shared_fraction,
            "prefix_len": self.prefix_len,
            "batch_fraction": self.batch_fraction,
            "stream_fraction": self.stream_fraction,
            "sample_fraction": self.sample_fraction,
            "deadline_ms": self.deadline_ms, "seed": self.seed,
        }
