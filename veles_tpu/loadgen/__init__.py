"""Fleet load harness: the millions-of-users testbed (ROADMAP item 5).

``veles-tpu loadgen`` drives a real serving fleet OPEN-LOOP — arrivals
follow the offered-load schedule whatever the fleet's latency does, so
overload is actually offered, not self-throttled away like a
closed-loop client would. The pieces:

- :class:`~veles_tpu.loadgen.workload.Workload` — deterministic
  (seeded) request synthesis: Zipf-distributed prompt lengths,
  shared-prefix mixes, interactive/batch QoS labels, streaming and
  buffered clients, steady/burst/diurnal arrival shapes;
- :class:`~veles_tpu.loadgen.storm.ChaosStorm` — timed fault storms
  expressed as plain ``window=T0:T1`` fault specs over the existing
  injection points (``serve.replica_death``,
  ``router.replica_request``, ``serve.page_alloc``, ...);
- :class:`~veles_tpu.loadgen.harness.LoadGen` — the driver: dispatch
  at the scheduled instants, record per-request outcomes client-side,
  and emit an SLO VERDICT merging the client's view with the serving
  histograms (veles_serving_ttft_seconds et al., PR 11).

Operator guide: docs/services.md "Overload & QoS".
"""

from .workload import Workload                          # noqa: F401
from .storm import (ChaosStorm, StormPlan,              # noqa: F401
                    parse_storm)
from .harness import (LoadGen, aggregate,               # noqa: F401
                      percentile, verdict)

#: every counter the load harness increments — registered in
#: telemetry/counters.py DESCRIPTIONS and asserted zero in
#: non-loadgen runs by ``python bench.py gate``'s overload section
LOADGEN_COUNTERS = (
    "veles_loadgen_requests_total",
    "veles_loadgen_shed_total",
    "veles_loadgen_errors_total",
    "veles_loadgen_storms_total",
    "veles_loadgen_alert_aborts_total",
)
