"""The open-loop load driver and its SLO verdict.

:class:`LoadGen` fires a :class:`~veles_tpu.loadgen.workload.Workload`
at a fleet endpoint on the workload's own clock — one thread per
in-flight request, dispatched at the scheduled arrival instant whether
or not earlier requests have answered (open loop: offered load is the
schedule's, not the fleet's). Each request records its client-observed
outcome (status, TTFT for streamed requests, end-to-end latency,
tokens); :func:`verdict` folds those records — plus the server-side
SLO histograms when the fleet shares this process's registry — into a
pass/fail report against explicit SLO bounds.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

from ..logger import Logger
from ..telemetry.counters import histograms, inc
from .storm import ChaosStorm, StormPlan
from .workload import Workload


def percentile(values: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank client-side percentile; None on empty input."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return None
    rank = max(0, min(len(vals) - 1,
                      int(round(q * (len(vals) - 1)))))
    return vals[rank]


def _send(url: str, body: Dict[str, Any],
          timeout: float) -> Dict[str, Any]:
    """POST one request; returns the client-observed record. A
    streamed request's TTFT is the first token event's arrival; a
    buffered one cannot observe first-token time client-side (its
    ttft_s is None — the server histograms cover it)."""
    rec: Dict[str, Any] = {
        "priority": body.get("priority", "interactive"),
        "stream": bool(body.get("stream")), "status": None,
        "error": None, "shed": False, "ttft_s": None, "e2e_s": None,
        "tokens": 0,
    }
    data = json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    t0 = time.time()
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            if body.get("stream") and "event-stream" in (
                    resp.headers.get("Content-Type", "")):
                tokens = 0
                for line in resp:
                    line = line.strip()
                    if not line.startswith(b"data:"):
                        continue
                    try:
                        ev = json.loads(line[5:].strip())
                    except ValueError:
                        continue
                    if not isinstance(ev, dict):
                        continue
                    if ev.get("done"):
                        rec["status"] = int(ev.get("code", 200))
                        if ev.get("error") is not None:
                            rec["error"] = str(ev["error"])
                        toks = ev.get("tokens")
                        if isinstance(toks, list):
                            tokens = max(tokens, len(toks))
                        break
                    toks = ev.get("tokens")
                    if isinstance(toks, list) and toks:
                        if rec["ttft_s"] is None:
                            rec["ttft_s"] = time.time() - t0
                        tokens += len(toks)
                rec["tokens"] = tokens
                if rec["status"] is None:
                    rec["status"] = 200
                    rec["error"] = "stream ended without a terminal"
            else:
                payload = json.loads(resp.read() or b"{}")
                rec["status"] = resp.status
                toks = payload.get("tokens")
                rec["tokens"] = (len(toks)
                                 if isinstance(toks, list) else 0)
    except urllib.error.HTTPError as e:
        rec["status"] = e.code
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        rec["error"] = str(payload.get("error", "HTTP %d" % e.code))
        rec["shed"] = e.code == 503
    except Exception as e:      # noqa: BLE001 — a dead fleet is data
        rec["error"] = "%s: %s" % (type(e).__name__, e)
    rec["e2e_s"] = time.time() - t0
    return rec


class LoadGen(Logger):
    """Drive ``workload`` at ``url`` open-loop, optionally under
    ``storms``; :meth:`run` returns the full report (records +
    aggregates + the storm/workload stamps)."""

    def __init__(self, url: str, workload: Workload,
                 storms: Sequence[ChaosStorm] = (),
                 path: str = "/generate",
                 timeout: float = 60.0,
                 time_scale: float = 1.0,
                 name: str = "loadgen",
                 abort_on_alert: bool = False,
                 alert_poll: float = 0.5) -> None:
        super().__init__()
        self.url = url.rstrip("/")
        self.path = path
        self.workload = workload
        self.storms = list(storms)
        self.timeout = float(timeout)
        #: compress (<1) or stretch (>1) the arrival schedule —
        #: drills run the same WORKLOAD faster without changing its
        #: per-request content
        self.time_scale = float(time_scale)
        self.name = name
        #: poll the fleet's ``GET /alerts`` (the watchtower rule
        #: states, telemetry/alerts.py) while driving and stop
        #: dispatching the moment any rule fires — a storm that burns
        #: error budget fails AT FIRE TIME, not minutes later in the
        #: end-of-run verdict
        self.abort_on_alert = bool(abort_on_alert)
        self.alert_poll = float(alert_poll)
        self._abort = threading.Event()
        self._abort_rules: List[str] = []

    def _alert_poll_loop(self, stop: threading.Event) -> None:
        """Daemon poller behind ``abort_on_alert``: first firing rule
        set trips the abort latch (counted
        ``veles_loadgen_alert_aborts_total``). Poll errors are
        ignored — a fleet without a watchtower (``enabled: false``)
        simply never aborts."""
        target = self.url + "/alerts"
        while not stop.wait(self.alert_poll):
            try:
                with urllib.request.urlopen(
                        target, timeout=self.alert_poll + 2.0) as r:
                    payload = json.loads(r.read() or b"{}")
            except Exception:    # noqa: BLE001 — observers only
                continue
            firing = payload.get("firing") or []
            if payload.get("enabled") and firing:
                self._abort_rules = [str(r) for r in firing]
                if not self._abort.is_set():
                    inc("veles_loadgen_alert_aborts_total")
                    self.warning(
                        "%s: aborting on firing alert(s): %s",
                        self.name, ", ".join(self._abort_rules))
                self._abort.set()
                return

    def run(self) -> Dict[str, Any]:
        arrivals = self.workload.arrivals()
        bodies = self.workload.requests()
        records: List[Optional[Dict[str, Any]]] = [None] * len(bodies)
        threads: List[threading.Thread] = []
        target = self.url + self.path

        def fire(i: int, body: Dict[str, Any]) -> None:
            inc("veles_loadgen_requests_total")
            rec = _send(target, body, self.timeout)
            rec["i"] = i
            if rec["shed"]:
                inc("veles_loadgen_shed_total")
            elif rec["error"] is not None:
                inc("veles_loadgen_errors_total")
            records[i] = rec

        self.info("%s: offering %d requests at %s (shape=%s, "
                  "%d storm(s))", self.name, len(bodies), target,
                  self.workload.shape, len(self.storms))
        t_run = time.time()
        dispatched = 0
        poll_stop = threading.Event()
        poller: Optional[threading.Thread] = None
        if self.abort_on_alert:
            self._abort.clear()
            self._abort_rules = []
            poller = threading.Thread(
                target=self._alert_poll_loop, args=(poll_stop,),
                daemon=True, name=self.name + ".alertpoll")
            poller.start()
        try:
            with StormPlan(self.storms):
                t0 = time.time()
                for i, (at, body) in enumerate(zip(arrivals, bodies)):
                    if self._abort.is_set():
                        break
                    # open loop: sleep to the SCHEDULED instant, then
                    # dispatch — never wait for an answer
                    delay = at * self.time_scale - (time.time() - t0)
                    if delay > 0:
                        if self._abort.wait(delay):
                            break
                    th = threading.Thread(
                        target=fire, args=(i, body), daemon=True,
                        name="%s.%d" % (self.name, i))
                    th.start()
                    threads.append(th)
                    dispatched += 1
                deadline = time.time() + self.timeout + 5.0
                for th in threads:
                    th.join(timeout=max(0.1, deadline - time.time()))
        finally:
            poll_stop.set()
            if poller is not None:
                poller.join(timeout=5)
        done = [r for r in records if r is not None]
        wall = time.time() - t_run
        report = {
            "workload": self.workload.describe(),
            "storms": [s.spec() for s in self.storms],
            "wall_seconds": round(wall, 3),
            "offered": len(bodies),
            "dispatched": dispatched,
            "answered": len(done),
            "records": done,
            "aggregates": aggregate(done, wall),
        }
        if self._abort.is_set():
            report["aborted_on_alert"] = {
                "rules": list(self._abort_rules),
                "after_requests": dispatched,
            }
        return report


def aggregate(records: Sequence[Dict[str, Any]],
              wall: float) -> Dict[str, Any]:
    """Per-priority-class client-side aggregates + fleet goodput."""
    out: Dict[str, Any] = {}
    for cls in ("interactive", "batch"):
        rows = [r for r in records if r["priority"] == cls]
        ok = [r for r in rows if r["status"] == 200
              and r["error"] is None]
        ttfts = [r["ttft_s"] for r in ok if r["ttft_s"] is not None]
        e2es = [r["e2e_s"] for r in ok if r["e2e_s"] is not None]
        out[cls] = {
            "offered": len(rows),
            "ok": len(ok),
            "shed": sum(1 for r in rows if r["shed"]),
            "errors": sum(1 for r in rows if r["error"] is not None
                          and not r["shed"]),
            "tokens": sum(r["tokens"] for r in ok),
            "ttft_p50_ms": _ms(percentile(ttfts, 0.50)),
            "ttft_p99_ms": _ms(percentile(ttfts, 0.99)),
            "e2e_p50_ms": _ms(percentile(e2es, 0.50)),
            "e2e_p99_ms": _ms(percentile(e2es, 0.99)),
        }
    total_tokens = sum(out[c]["tokens"] for c in out)
    out["goodput_tokens_per_s"] = round(
        total_tokens / wall, 2) if wall > 0 else 0.0
    # server-side SLO histograms: meaningful when the fleet shares
    # this process's registry (the in-process drill); a remote fleet
    # reports None here and is judged on the client-side numbers
    out["server_ttft_p99_ms"] = _ms(
        histograms.quantile("veles_serving_ttft_seconds", 0.99))
    out["server_queue_wait_p99_ms"] = _ms(
        histograms.quantile("veles_serving_queue_wait_seconds", 0.99))
    return out


def _ms(seconds: Optional[float]) -> Optional[float]:
    return None if seconds is None else round(seconds * 1000.0, 3)


def verdict(report: Dict[str, Any],
            slo_ttft_ms: float = 2000.0,
            max_interactive_loss: float = 0.05,
            min_goodput_tokens_per_s: float = 0.0
            ) -> Dict[str, Any]:
    """Fold a :meth:`LoadGen.run` report into an explicit pass/fail
    SLO verdict:

    - **interactive TTFT p99** (server histogram when available, else
      the streamed client observations) within ``slo_ttft_ms``;
    - **interactive loss** (sheds + errors over offered) at most
      ``max_interactive_loss`` — batch absorbs the overload, the
      protected class keeps answering;
    - **goodput** at least ``min_goodput_tokens_per_s`` — brownout
      degrades, it must not collapse.
    """
    agg = report["aggregates"]
    inter = agg["interactive"]
    checks: List[Dict[str, Any]] = []

    ttft = agg.get("server_ttft_p99_ms")
    if ttft is None:
        ttft = inter["ttft_p99_ms"]
    checks.append({
        "name": "interactive_ttft_p99_ms",
        "observed": ttft, "bound": slo_ttft_ms,
        "ok": ttft is None or ttft <= slo_ttft_ms})
    loss = ((inter["shed"] + inter["errors"]) / inter["offered"]
            if inter["offered"] else 0.0)
    checks.append({
        "name": "interactive_loss_fraction",
        "observed": round(loss, 4), "bound": max_interactive_loss,
        "ok": loss <= max_interactive_loss})
    goodput = agg["goodput_tokens_per_s"]
    checks.append({
        "name": "goodput_tokens_per_s",
        "observed": goodput, "bound": min_goodput_tokens_per_s,
        "ok": goodput >= min_goodput_tokens_per_s})
    aborted = report.get("aborted_on_alert")
    if aborted is not None:
        # --abort-on-alert tripped: the run is a FAIL at fire time
        # whatever the partial aggregates say — the whole point of
        # polling /alerts is failing before the storm finishes
        checks.append({
            "name": "aborted_on_alert",
            "observed": ",".join(aborted.get("rules", ())) or "yes",
            "bound": "no firing alerts", "ok": False})
    return {"pass": all(c["ok"] for c in checks), "checks": checks}
