"""compare_snapshots: structural diff of two training checkpoints.

Equivalent of the reference's veles/scripts/compare_snapshots.py (BFS diff
of two pickled workflows). Here snapshots are the explicit state schema of
veles_tpu/snapshotter.py (``__units__``/``__prng__``/``__meta__``), so the
walk is over that tree: every leaf is compared by shape/dtype/value and
the differences are printed as a table with max|Δ| per array.

Usage: ``python -m veles_tpu.scripts.compare_snapshots A.snap B.snap
[--rtol 1e-5] [--atol 1e-8] [--show-equal]``
"""

from __future__ import annotations

import argparse
from typing import Any, Dict, Iterator, List, Tuple

import numpy


def walk(prefix: str, node: Any) -> Iterator[Tuple[str, Any]]:
    if isinstance(node, dict):
        for key in sorted(node, key=str):
            yield from walk("%s/%s" % (prefix, key), node[key])
    elif isinstance(node, (list, tuple)) and not \
            isinstance(node, numpy.ndarray):
        for i, item in enumerate(node):
            yield from walk("%s[%d]" % (prefix, i), item)
    else:
        yield prefix, node


def compare(a: Dict[str, Any], b: Dict[str, Any], rtol: float = 1e-5,
            atol: float = 1e-8) -> List[Dict[str, Any]]:
    """Rows: {path, status, detail}; status ∈ equal/close/differs/
    only_a/only_b/shape/dtype."""
    fa, fb = dict(walk("", a)), dict(walk("", b))
    rows: List[Dict[str, Any]] = []
    for path in sorted(set(fa) | set(fb)):
        if path not in fb:
            rows.append({"path": path, "status": "only_a", "detail": ""})
            continue
        if path not in fa:
            rows.append({"path": path, "status": "only_b", "detail": ""})
            continue
        va, vb = fa[path], fb[path]
        if isinstance(va, numpy.ndarray) or isinstance(vb, numpy.ndarray):
            va, vb = numpy.asarray(va), numpy.asarray(vb)
            if va.shape != vb.shape:
                rows.append({"path": path, "status": "shape",
                             "detail": "%s vs %s" % (va.shape, vb.shape)})
            elif va.dtype != vb.dtype:
                rows.append({"path": path, "status": "dtype",
                             "detail": "%s vs %s" % (va.dtype, vb.dtype)})
            elif va.size and numpy.issubdtype(va.dtype, numpy.number):
                delta = float(numpy.abs(
                    va.astype(numpy.float64) -
                    vb.astype(numpy.float64)).max())
                if delta == 0.0:
                    rows.append({"path": path, "status": "equal",
                                 "detail": ""})
                elif numpy.allclose(va, vb, rtol=rtol, atol=atol):
                    rows.append({"path": path, "status": "close",
                                 "detail": "max|Δ|=%.3g" % delta})
                else:
                    rows.append({"path": path, "status": "differs",
                                 "detail": "max|Δ|=%.3g" % delta})
            else:
                same = (va.tolist() == vb.tolist())
                rows.append({"path": path,
                             "status": "equal" if same else "differs",
                             "detail": ""})
        else:
            same = (va == vb)
            rows.append({"path": path,
                         "status": "equal" if same else "differs",
                         "detail": "" if same else
                         "%r vs %r" % (va, vb)})
    return rows


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("snapshot_a")
    parser.add_argument("snapshot_b")
    parser.add_argument("--rtol", type=float, default=1e-5)
    parser.add_argument("--atol", type=float, default=1e-8)
    parser.add_argument("--show-equal", action="store_true")
    args = parser.parse_args(argv)
    from ..snapshotter import load_snapshot
    a = load_snapshot(args.snapshot_a)
    b = load_snapshot(args.snapshot_b)
    rows = compare(a, b, args.rtol, args.atol)
    shown = 0
    for row in rows:
        if row["status"] == "equal" and not args.show_equal:
            continue
        print("%-8s %-60s %s" % (row["status"], row["path"],
                                 row["detail"]))
        shown += 1
    counts: Dict[str, int] = {}
    for row in rows:
        counts[row["status"]] = counts.get(row["status"], 0) + 1
    print("—", ", ".join("%s: %d" % kv for kv in sorted(counts.items())))
    bad = sum(counts.get(k, 0) for k in
              ("differs", "shape", "dtype", "only_a", "only_b"))
    return 0 if bad == 0 else 1


if __name__ == "__main__":      # pragma: no cover
    import sys
    sys.exit(main())
