"""Operator CLI tools (reference: veles/scripts/ — compare_snapshots,
generate_frontend, bboxer, update_forge; forge CLI lives in
veles_tpu/forge.py)."""
