"""generate_frontend: static HTML command composer for the CLI.

Equivalent of the reference's veles/scripts/generate_frontend.py (which
walked the distributed argparse registry and emitted the ``--frontend``
wizard HTML). Here the single source of truth is
veles_tpu/cmdline.py's parser: every option becomes a form control and
the page assembles the ``python -m veles_tpu …`` command line live.

Usage: ``python -m veles_tpu.scripts.generate_frontend [-o frontend.html]``
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, List

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>veles_tpu command composer</title>
<style>
body {{ font-family: sans-serif; max-width: 60em; margin: 2em auto; }}
fieldset {{ margin-bottom: 1em; }} label {{ display: inline-block;
min-width: 16em; }} .row {{ margin: 0.3em 0; }}
#cmd {{ background: #222; color: #9f9; padding: 1em; display: block;
white-space: pre-wrap; word-break: break-all; }}
small {{ color: #666; }}
</style></head><body>
<h1>veles_tpu — command composer</h1>
<div id="form"></div>
<h2>Command</h2><code id="cmd"></code>
<script>
const OPTIONS = {options_json};
const form = document.getElementById('form');
const state = {{}};
function rebuild() {{
  let cmd = 'python -m veles_tpu';
  const pos = OPTIONS.filter(o => !o.flag);
  for (const o of pos) if (state[o.dest]) cmd += ' ' + state[o.dest];
  for (const o of OPTIONS.filter(o => o.flag)) {{
    const v = state[o.dest];
    if (o.is_bool) {{ if (v) cmd += ' ' + o.flag; }}
    else if (v !== undefined && v !== '') cmd += ' ' + o.flag + ' ' + v;
  }}
  document.getElementById('cmd').textContent = cmd;
}}
for (const o of OPTIONS) {{
  const row = document.createElement('div'); row.className = 'row';
  const label = document.createElement('label');
  label.textContent = o.flag || o.dest;
  row.appendChild(label);
  let input;
  if (o.is_bool) {{
    input = document.createElement('input'); input.type = 'checkbox';
    input.onchange = () => {{ state[o.dest] = input.checked; rebuild(); }};
  }} else {{
    input = document.createElement('input'); input.type = 'text';
    if (o.default !== null) input.placeholder = String(o.default);
    input.oninput = () => {{ state[o.dest] = input.value; rebuild(); }};
  }}
  row.appendChild(input);
  if (o.help) {{
    const help = document.createElement('small');
    help.textContent = ' ' + o.help; row.appendChild(help);
  }}
  form.appendChild(row);
}}
rebuild();
</script></body></html>"""


def collect_options(parser: argparse.ArgumentParser
                    ) -> List[Dict[str, Any]]:
    out = []
    for action in parser._actions:      # the argparse introspection surface
        if isinstance(action, argparse._HelpAction):
            continue
        flag = max(action.option_strings, key=len) \
            if action.option_strings else None
        out.append({
            "dest": action.dest,
            "flag": flag,
            "is_bool": isinstance(action, (argparse._StoreTrueAction,
                                           argparse._StoreFalseAction,
                                           argparse._CountAction)),
            "default": action.default
            if isinstance(action.default, (int, float, str, bool,
                                           type(None))) else None,
            "help": (action.help or "").replace("\n", " "),
        })
    return out


def generate(out_path: str) -> str:
    from ..cmdline import make_parser
    options = collect_options(make_parser())
    # JS-context embedding: escape '<' as < (prevents </script>
    # breakout); html.escape would leave &lt; entities undecoded in JS
    page = _PAGE.format(
        options_json=json.dumps(options).replace("<", "\\u003c"))
    with open(out_path, "w") as fout:
        fout.write(page)
    return out_path


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("-o", "--output", default="frontend.html")
    args = parser.parse_args(argv)
    print(generate(args.output))
    return 0


if __name__ == "__main__":      # pragma: no cover
    import sys
    sys.exit(main())
