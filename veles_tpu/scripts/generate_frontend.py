"""generate_frontend: HTML command composer for the CLI.

Equivalent of the reference's veles/scripts/generate_frontend.py (which
walked the distributed argparse registry and emitted the ``--frontend``
wizard HTML) plus the live wizard the reference served from
veles/__main__.py:258-332 (an interactive tornado command composer).
Here the single source of truth is veles_tpu/cmdline.py's parser: every
option becomes a form control and the page assembles the ``python -m
veles_tpu …`` command line live.

Usage:
  python -m veles_tpu.scripts.generate_frontend [-o frontend.html]
  python -m veles_tpu.scripts.generate_frontend --serve [--port N]

``--serve`` adds the interactive round trip the static page cannot do:
``POST /compose`` with a ``{dest: value}`` state dict returns the
assembled command line AND validates it against the real parser (the
reference wizard's compose step; launching the command stays with the
user — a web endpoint that executes arbitrary CLI strings would be an
injection surface, which is also why the reference's execute button
stayed on localhost).
"""

from __future__ import annotations

import argparse
import json
from typing import Any, Dict, List

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>veles_tpu command composer</title>
<style>
body {{ font-family: sans-serif; max-width: 60em; margin: 2em auto; }}
fieldset {{ margin-bottom: 1em; }} label {{ display: inline-block;
min-width: 16em; }} .row {{ margin: 0.3em 0; }}
#cmd {{ background: #222; color: #9f9; padding: 1em; display: block;
white-space: pre-wrap; word-break: break-all; }}
small {{ color: #666; }}
</style></head><body>
<h1>veles_tpu — command composer</h1>
<div id="form"></div>
<h2>Command</h2><code id="cmd"></code>
<script>
const OPTIONS = {options_json};
const form = document.getElementById('form');
const state = {{}};
function rebuild() {{
  let cmd = 'python -m veles_tpu';
  const pos = OPTIONS.filter(o => !o.flag);
  for (const o of pos) if (state[o.dest]) cmd += ' ' + state[o.dest];
  for (const o of OPTIONS.filter(o => o.flag)) {{
    const v = state[o.dest];
    if (o.is_bool) {{ if (v) cmd += ' ' + o.flag; }}
    else if (v !== undefined && v !== '') cmd += ' ' + o.flag + ' ' + v;
  }}
  document.getElementById('cmd').textContent = cmd;
}}
for (const o of OPTIONS) {{
  const row = document.createElement('div'); row.className = 'row';
  const label = document.createElement('label');
  label.textContent = o.flag || o.dest;
  row.appendChild(label);
  let input;
  if (o.is_bool) {{
    input = document.createElement('input'); input.type = 'checkbox';
    input.onchange = () => {{ state[o.dest] = input.checked; rebuild(); }};
  }} else {{
    input = document.createElement('input'); input.type = 'text';
    if (o.default !== null) input.placeholder = String(o.default);
    input.oninput = () => {{ state[o.dest] = input.value; rebuild(); }};
  }}
  row.appendChild(input);
  if (o.help) {{
    const help = document.createElement('small');
    help.textContent = ' ' + o.help; row.appendChild(help);
  }}
  form.appendChild(row);
}}
rebuild();
</script></body></html>"""


def collect_options(parser: argparse.ArgumentParser
                    ) -> List[Dict[str, Any]]:
    out = []
    for action in parser._actions:      # the argparse introspection surface
        if isinstance(action, argparse._HelpAction):
            continue
        flag = max(action.option_strings, key=len) \
            if action.option_strings else None
        out.append({
            "dest": action.dest,
            "flag": flag,
            "is_bool": isinstance(action, (argparse._StoreTrueAction,
                                           argparse._StoreFalseAction,
                                           argparse._CountAction)),
            "default": action.default
            if isinstance(action.default, (int, float, str, bool,
                                           type(None))) else None,
            "help": (action.help or "").replace("\n", " "),
        })
    return out


def generate(out_path: str) -> str:
    from ..cmdline import make_parser
    options = collect_options(make_parser())
    # JS-context embedding: escape '<' as < (prevents </script>
    # breakout); html.escape would leave &lt; entities undecoded in JS
    page = _PAGE.format(
        options_json=json.dumps(options).replace("<", "\\u003c"))
    with open(out_path, "w") as fout:
        fout.write(page)
    return out_path


def compose(state: Dict[str, Any]) -> Dict[str, Any]:
    """Assemble ``python -m veles_tpu …`` argv from a ``{dest: value}``
    state dict and VALIDATE it against the real parser. Returns
    ``{"cmd", "argv", "valid", "error"}`` — the server-side half of the
    wizard round trip."""
    import shlex
    from ..cmdline import make_parser
    parser = make_parser()
    actions = [a for a in parser._actions
               if not isinstance(a, argparse._HelpAction)]

    def skipped(value):
        # None/empty/unchecked-box are "not set". NOT `in (None, "",
        # False)`: 0 == False, which would silently drop legitimate
        # zero values (--process-id 0 is exactly the coordinator)
        return value is None or value is False or value == ""

    argv: List[str] = []
    # positionals in the PARSER's declared order (model, config,
    # config_list) — client JSON key order must not re-bind them — and
    # first overall (argparse cannot take a second positional group
    # after flags, the same rule the trial-scheduler children follow)
    for a in actions:
        if a.option_strings:
            continue
        value = state.get(a.dest)
        if skipped(value):
            continue
        argv.extend([str(v) for v in value] if isinstance(value, list)
                    else [str(value)])
    for a in actions:
        if not a.option_strings:
            continue
        value = state.get(a.dest)
        if skipped(value):
            continue
        flag = max(a.option_strings, key=len)
        if isinstance(a, (argparse._StoreTrueAction,
                          argparse._StoreFalseAction)):
            argv.append(flag)
        elif isinstance(a, argparse._CountAction):
            argv.extend([flag] * int(value))
        else:
            argv.extend([flag, str(value)])
    parser.error = lambda message: (_ for _ in ()).throw(
        ValueError(message))
    try:
        parser.parse_args(argv)
        valid, error = True, None
    except (ValueError, SystemExit) as exc:
        valid, error = False, str(exc)
    # shlex.join: a value with spaces/metachars must round-trip through
    # a shell into exactly this argv
    return {"cmd": "python -m veles_tpu " + shlex.join(argv),
            "argv": argv, "valid": valid, "error": error}


def serve(port: int = 0):
    """Serve the wizard: GET / (the page), GET /options (parser
    surface), POST /compose (assemble + validate). Returns the server;
    caller owns shutdown. Binds localhost only."""
    import json as _json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    from ..cmdline import make_parser
    page = _PAGE.format(options_json=_json.dumps(
        collect_options(make_parser())).replace("<", "\\u003c"))

    class Handler(BaseHTTPRequestHandler):
        def _send(self, body: bytes, ctype: str, code: int = 200):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/options":
                self._send(_json.dumps(collect_options(
                    make_parser())).encode(), "application/json")
            elif self.path in ("/", "/index.html"):
                self._send(page.encode(), "text/html; charset=utf-8")
            else:
                self._send(b"not found", "text/plain", 404)

        def do_POST(self):
            if self.path != "/compose":
                self._send(b"not found", "text/plain", 404)
                return
            n = int(self.headers.get("Content-Length", 0))
            try:
                state = _json.loads(self.rfile.read(n) or b"{}")
                out = compose(state)
                self._send(_json.dumps(out).encode(),
                           "application/json")
            except Exception as exc:      # noqa: BLE001
                self._send(_json.dumps(
                    {"valid": False, "error": str(exc)}).encode(),
                    "application/json", 400)

        def log_message(self, *a):        # quiet test runs
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    return httpd


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("-o", "--output", default="frontend.html")
    parser.add_argument("--serve", action="store_true",
                        help="serve the interactive wizard instead of "
                             "writing a static page")
    parser.add_argument("--port", type=int, default=8968)
    args = parser.parse_args(argv)
    if args.serve:
        httpd = serve(args.port)
        print("wizard at http://127.0.0.1:%d/"
              % httpd.server_address[1], flush=True)
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
        return 0
    print(generate(args.output))
    return 0


if __name__ == "__main__":      # pragma: no cover
    import sys
    sys.exit(main())
