"""bboxer: web tool for drawing bounding-box labels on an image folder.

Equivalent of the reference's veles/scripts/bboxer.py (collaborative
image labelling web app). One self-contained page: pick an image, drag
boxes on a canvas, assign a class label; annotations persist to a JSON
file next to the images (``bboxes.json``: {image: [{x, y, w, h,
label}]}), which an ImageLoader pipeline can consume as ground truth.

Usage: ``python -m veles_tpu.scripts.bboxer IMAGE_DIR [--port 8095]``
"""

from __future__ import annotations

import json
import mimetypes
import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler
from typing import Dict, List

from .._http import HTTPService, bytes_reply, json_reply, read_json_object
from ..logger import Logger

IMAGE_EXTS = (".png", ".jpg", ".jpeg", ".bmp", ".gif")

_PAGE = """<!doctype html><html><head><meta charset="utf-8">
<title>bboxer</title><style>
body { font-family: sans-serif; margin: 1em; }
#canvas { border: 1px solid #888; cursor: crosshair; }
#images span { margin-right: .8em; cursor: pointer; color: #04c; }
#images span.current { font-weight: bold; }
</style></head><body>
<h2>bboxer — drag to draw, enter label, saved instantly</h2>
<div id="images"></div>
<p>label: <input id="label" value="object">
<button onclick="clearBoxes()">clear image boxes</button></p>
<canvas id="canvas"></canvas>
<script>
let current = null, boxes = {}, img = new Image(), drag = null;
const canvas = document.getElementById('canvas');
const ctx = canvas.getContext('2d');
async function load() {
  const r = await fetch('list'); const data = await r.json();
  boxes = data.boxes;
  const div = document.getElementById('images'); div.innerHTML = '';
  for (const name of data.images) {
    const s = document.createElement('span');
    s.textContent = name + ' (' + (boxes[name]||[]).length + ')';
    s.onclick = () => show(name);
    if (name === current) s.className = 'current';
    div.appendChild(s);
  }
  if (!current && data.images.length) show(data.images[0]);
}
function show(name) {
  current = name;
  img = new Image();
  img.onload = () => { canvas.width = img.width;
    canvas.height = img.height; redraw(); load(); };
  img.src = 'image?name=' + encodeURIComponent(name);
}
function redraw() {
  ctx.drawImage(img, 0, 0);
  ctx.strokeStyle = '#f00'; ctx.fillStyle = '#f00'; ctx.font = '12px sans-serif';
  for (const b of boxes[current] || []) {
    ctx.strokeRect(b.x, b.y, b.w, b.h);
    ctx.fillText(b.label, b.x + 2, b.y + 12);
  }
  if (drag) ctx.strokeRect(drag.x, drag.y, drag.w, drag.h);
}
canvas.onmousedown = e => {
  drag = {x: e.offsetX, y: e.offsetY, w: 0, h: 0}; };
canvas.onmousemove = e => { if (!drag) return;
  drag.w = e.offsetX - drag.x; drag.h = e.offsetY - drag.y; redraw(); };
canvas.onmouseup = async e => {
  if (!drag) return;
  const b = {x: Math.min(drag.x, drag.x + drag.w),
             y: Math.min(drag.y, drag.y + drag.h),
             w: Math.abs(drag.w), h: Math.abs(drag.h),
             label: document.getElementById('label').value};
  drag = null;
  if (b.w > 2 && b.h > 2) {
    await fetch('boxes', {method: 'POST', body: JSON.stringify(
      {image: current, box: b})});
    (boxes[current] = boxes[current] || []).push(b);
  }
  redraw(); load();
};
async function clearBoxes() {
  await fetch('boxes', {method: 'POST', body: JSON.stringify(
    {image: current, clear: true})});
  boxes[current] = []; redraw(); load();
}
load();
</script></body></html>"""


class BBoxerServer(Logger):
    """Annotation server over one image directory."""

    def __init__(self, image_dir: str, port: int = 0) -> None:
        super().__init__()
        self.image_dir = os.path.abspath(image_dir)
        if not os.path.isdir(self.image_dir):
            raise NotADirectoryError(self.image_dir)
        self.store_path = os.path.join(self.image_dir, "bboxes.json")
        self._lock = threading.Lock()
        self.boxes: Dict[str, List[dict]] = {}
        if os.path.exists(self.store_path):
            with open(self.store_path) as fin:
                self.boxes = json.load(fin)
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                server.debug("http: " + fmt, *args)

            def do_GET(self):
                url = urllib.parse.urlparse(self.path)
                if url.path in ("/", "/index.html"):
                    bytes_reply(self, 200, _PAGE.encode(), "text/html")
                elif url.path == "/list":
                    json_reply(self, 200, {"images": server.images(),
                                           "boxes": server.boxes_copy()})
                elif url.path == "/image":
                    name = urllib.parse.parse_qs(url.query).get(
                        "name", [""])[0]
                    data = server.read_image(name)
                    if data is None:
                        self.send_error(404)
                        return
                    ctype = mimetypes.guess_type(name)[0] or \
                        "application/octet-stream"
                    bytes_reply(self, 200, data, ctype)
                else:
                    self.send_error(404)

            def do_POST(self):
                if urllib.parse.urlparse(self.path).path != "/boxes":
                    self.send_error(404)
                    return
                try:
                    body = read_json_object(self)
                    image = str(body["image"])
                except (ValueError, KeyError) as e:
                    json_reply(self, 400, {"error": str(e)})
                    return
                if image not in server.images():
                    json_reply(self, 404, {"error": "unknown image"})
                    return
                if body.get("clear"):
                    server.set_boxes(image, [])
                else:
                    box = body.get("box")
                    if not isinstance(box, dict):
                        json_reply(self, 400, {"error": "box required"})
                        return
                    server.add_box(image, box)
                json_reply(self, 200, {"ok": True,
                                       "count": server.count(image)})

        self._service = HTTPService(Handler, port, "bboxer")
        self.port = self._service.port

    # -- state ---------------------------------------------------------------
    def boxes_copy(self) -> Dict[str, List[dict]]:
        """Snapshot under the lock: /list serializes while POSTs mutate."""
        with self._lock:
            return {k: list(v) for k, v in self.boxes.items()}

    def count(self, image: str) -> int:
        with self._lock:
            return len(self.boxes.get(image, []))

    def images(self) -> List[str]:
        return sorted(
            f for f in os.listdir(self.image_dir)
            if f.lower().endswith(IMAGE_EXTS))

    def read_image(self, name: str):
        if name not in self.images():       # whitelist: no path escapes
            return None
        with open(os.path.join(self.image_dir, name), "rb") as fin:
            return fin.read()

    def add_box(self, image: str, box: dict) -> None:
        clean = {"x": float(box.get("x", 0)), "y": float(box.get("y", 0)),
                 "w": float(box.get("w", 0)), "h": float(box.get("h", 0)),
                 "label": str(box.get("label", "object"))}
        with self._lock:
            self.boxes.setdefault(image, []).append(clean)
            self._save()

    def set_boxes(self, image: str, boxes: List[dict]) -> None:
        with self._lock:
            self.boxes[image] = boxes
            self._save()

    def _save(self) -> None:
        # atomic: a crash mid-write must never destroy prior annotations
        tmp = self.store_path + ".tmp"
        with open(tmp, "w") as fout:
            json.dump(self.boxes, fout, indent=1)
        os.replace(tmp, self.store_path)

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "BBoxerServer":
        self._service.start_serving()
        self.info("bboxer on http://127.0.0.1:%d/ (%d images)",
                  self.port, len(self.images()))
        return self

    def stop(self) -> None:
        self._service.stop_serving()


def main(argv=None) -> int:
    import argparse
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("image_dir")
    parser.add_argument("--port", type=int, default=8095)
    args = parser.parse_args(argv)
    server = BBoxerServer(args.image_dir, port=args.port).start()
    try:
        import time
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":      # pragma: no cover
    import sys
    sys.exit(main())
