"""Overload-control plane: QoS classes, adaptive admission, brownout.

The request plane survives *failures* (router failover, journal
replay, token-level resume) but until this module nothing defended it
when offered load exceeds capacity: every request was equal priority,
one global ``request_timeout`` governed every deadline, shedding was a
binary 503 and a storm of client retries amplified exactly the
overload that caused it. This module is the host-side policy layer —
pure bookkeeping, no jax — that the scheduler, the engines, the
GenerationAPI and the FleetRouter consult:

* **QoS classes** — requests carry ``priority`` (``interactive`` |
  ``batch``). With ``root.common.serving.qos`` on, the
  ``SlotScheduler`` admits interactive requests past queued batch
  work, and the engines preempt batch rows at a step boundary via the
  token-level resume path (``fold_resume`` + ``advanced_prng_key``)
  so preempted work finishes bit-identical to an uninterrupted
  decode — preemption is lossless, never wasteful.

* **Adaptive admission** (:class:`AIMDController`) — the FleetRouter
  throttles BATCH admission with an additive-increase /
  multiplicative-decrease rate keyed on the observed TTFT p99 vs an
  SLO target (the PR 11 histograms). Interactive traffic is never
  AIMD-throttled: the controller exists to protect it.

* **Brownout ladder** (:class:`BrownoutLadder`) — hysteresis-guarded
  graceful degradation: level 1 caps ``n_new``, level 2 disables
  speculative decoding (downgraded to the equivalent plain mode),
  level 3 sheds batch outright. Entry and exit each require
  ``patience`` consecutive observations beyond their (asymmetric)
  thresholds, so a noisy p99 cannot flap the fleet between levels.

* **Retry token bucket** (:class:`RetryTokenBucket`) — a router-wide
  budget on failover retries, capping retry amplification during a
  storm: when the bucket is dry the router answers with the last
  attempt's error instead of hammering the surviving replicas.

* **Dynamic Retry-After** (:func:`retry_after_hint`,
  :func:`dynamic_retry_after`) — shed answers derive their backoff
  hint from live queue pressure instead of a static constant, clamped
  to ``[base, RETRY_AFTER_MAX]`` so storming clients back off
  proportionally. The pressure provider is registered only while a
  QoS-enabled engine runs (feature-off lock: with the knob off, every
  shed answer is byte-identical to the static hints).

Every knob defaults OFF; with defaults the scheduler order, dispatch
counts and outputs are bit-identical to the pre-QoS plane
(test-enforced by tests/test_overload.py).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..telemetry.counters import histograms, inc

#: the two service classes; requests that say nothing are
#: ``interactive`` — unlabeled traffic is latency-sensitive by
#: default, batch is an explicit opt-in to being throttled/preempted
QOS_PRIORITIES = ("interactive", "batch")

#: dynamic Retry-After clamp ceiling (seconds) — a hint must stay
#: actionable; "come back in 10 minutes" is a disguised outage
RETRY_AFTER_MAX = 30.0


def request_priority(req: Dict) -> str:
    """The request's service class, defaulting unlabeled traffic to
    ``interactive`` (see :data:`QOS_PRIORITIES`)."""
    p = req.get("priority")
    return p if p in QOS_PRIORITIES else "interactive"


def qos_enabled() -> bool:
    """THE serving-side QoS switch (``root.common.serving.qos``,
    default off). Gates priority-aware admission, batch preemption
    and the dynamic Retry-After pressure provider — off means the
    request plane behaves bit-identically to the pre-QoS code."""
    try:
        from ..config import root
        return bool(root.common.serving.get("qos", False))
    except Exception:       # noqa: BLE001 — config not importable
        return False


def qos_preempt_enabled() -> bool:
    """Whether QoS may preempt batch rows mid-decode
    (``root.common.serving.qos_preempt``, default on — only consulted
    when :func:`qos_enabled` already is)."""
    try:
        from ..config import root
        return bool(root.common.serving.get("qos_preempt", True))
    except Exception:       # noqa: BLE001
        return True


# -- dynamic Retry-After ------------------------------------------------------
def retry_after_hint(depth: int, capacity: int,
                     lo: float = 1.0,
                     hi: float = RETRY_AFTER_MAX) -> float:
    """Backoff hint proportional to queue pressure: ``lo`` at an
    empty queue, ``hi`` at (or past) ``capacity`` queued requests.
    Pure function — the planes feed it their live depth."""
    cap = max(1, int(capacity))
    frac = min(1.0, max(0, int(depth)) / float(cap))
    return lo + (hi - lo) * frac


_pressure_lock = threading.Lock()
_pressure_provider: Optional[Callable[[], Tuple[int, int]]] = None


def set_pressure_provider(fn: Callable[[], Tuple[int, int]]) -> None:
    """Register the live ``() -> (queue_depth, capacity)`` source
    shed answers derive their Retry-After from. A QoS-enabled engine
    registers its scheduler here at start; last writer wins (one
    provider per process is enough — any engine's pressure is the
    process's pressure)."""
    global _pressure_provider
    with _pressure_lock:
        _pressure_provider = fn


def clear_pressure_provider(fn: Callable[[], Tuple[int, int]]) -> None:
    """Unregister ``fn`` if it is still the current provider (an
    engine stopping must not clobber a sibling's registration)."""
    global _pressure_provider
    with _pressure_lock:
        if _pressure_provider is fn:
            _pressure_provider = None


def dynamic_retry_after(base: Optional[float]) -> Optional[float]:
    """The one Retry-After derivation every shed answer goes through
    (``Ticket.error_payload``, ``health.shed``): with a pressure
    provider registered, scale the static ``base`` hint by live queue
    depth, clamped to ``[base, RETRY_AFTER_MAX]`` — an idle queue
    answers exactly ``base``, so values only ever change under real
    pressure (and never at all with QoS off, when no provider is
    registered). Never raises: a broken provider answers ``base``."""
    if base is None:
        return None
    fn = _pressure_provider
    if fn is None:
        return base
    try:
        depth, capacity = fn()
        hint = retry_after_hint(int(depth), int(capacity), lo=base)
    except Exception:       # noqa: BLE001 — hint only, never the answer
        return base
    return min(RETRY_AFTER_MAX, max(float(base), float(hint)))


# -- adaptive admission -------------------------------------------------------
class AIMDController:
    """Additive-increase / multiplicative-decrease admission rate for
    BATCH traffic, keyed on an observed latency quantile vs an SLO
    target. ``rate`` is the fraction of batch requests admitted
    (1.0 = all). The grant decision is a DETERMINISTIC credit
    accumulator, not a coin flip — at rate r, exactly ``floor(n*r)``
    of any n consecutive batch arrivals are admitted, so tests and
    drills reproduce bit-for-bit."""

    def __init__(self, slo_ms: float = 500.0,
                 metric: str = "veles_serving_ttft_seconds",
                 quantile: float = 0.99,
                 floor: float = 0.05, additive: float = 0.05,
                 multiplicative: float = 0.5,
                 interval: float = 0.5) -> None:
        self.slo_ms = float(slo_ms)
        self.metric = str(metric)
        self.quantile = float(quantile)
        self.floor = float(floor)
        self.additive = float(additive)
        self.multiplicative = float(multiplicative)
        self.interval = float(interval)
        self.rate = 1.0
        self._credit = 0.0
        self._last_obs = 0.0
        self._lock = threading.Lock()

    def observed_ms(self) -> Optional[float]:
        """The controller's live signal: the configured quantile of
        the configured histogram, in milliseconds (None before any
        sample — the controller holds at its current rate)."""
        q = histograms.quantile(self.metric, self.quantile)
        return None if q is None else q * 1000.0

    def observe(self, now: Optional[float] = None,
                value_ms: Optional[float] = None) -> float:
        """Poll the signal (at most once per ``interval``) and adjust:
        above SLO → multiplicative decrease toward ``floor``; at or
        below → additive increase toward 1.0. Returns the current
        rate. ``value_ms`` injects the signal directly (tests, and
        the ladder sharing one poll)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if value_ms is None:
                if now - self._last_obs < self.interval:
                    return self.rate
                self._last_obs = now
                value_ms = self.observed_ms()
            if value_ms is None:
                return self.rate
            if value_ms > self.slo_ms:
                self.rate = max(self.floor,
                                self.rate * self.multiplicative)
            else:
                self.rate = min(1.0, self.rate + self.additive)
            return self.rate

    def grant(self) -> bool:
        """Admit-or-throttle for one batch arrival at the current
        rate (deterministic thinning via credit accumulation)."""
        with self._lock:
            self._credit += self.rate
            if self._credit >= 1.0:
                self._credit -= 1.0
                return True
            return False


class BrownoutLadder:
    """Hysteresis-guarded graceful degradation. Levels::

        0  normal      — nothing degraded
        1  cap_n_new   — batch generation budgets capped
        2  no_spec     — speculative decoding downgraded to its
                         plain equivalent (greedy / sample)
        3  shed_batch  — batch requests shed outright (503);
                         interactive still served

    A level is ENTERED after ``patience`` consecutive observations
    above ``slo_ms * enter`` and EXITED after ``patience`` consecutive
    observations below ``slo_ms * exit`` — the asymmetric band
    (enter > exit) plus the patience counters are the hysteresis that
    keeps a noisy p99 from flapping the fleet between levels."""

    LEVELS = ("normal", "cap_n_new", "no_spec", "shed_batch")

    def __init__(self, slo_ms: float = 500.0, enter: float = 1.5,
                 exit: float = 0.8, patience: int = 3,
                 cap_n_new: int = 32) -> None:
        if exit >= enter:
            raise ValueError(
                "brownout exit threshold %.3g must sit below the "
                "enter threshold %.3g (the hysteresis band)"
                % (exit, enter))
        self.slo_ms = float(slo_ms)
        self.enter = float(enter)
        self.exit = float(exit)
        self.patience = max(1, int(patience))
        self.cap_n_new = max(1, int(cap_n_new))
        self.level = 0
        self.transitions = 0
        self._hot = 0
        self._cool = 0
        self._lock = threading.Lock()

    def observe(self, value_ms: Optional[float]) -> int:
        """Feed one latency observation (ms); returns the (possibly
        changed) level. ``None`` (no samples yet) holds the level."""
        with self._lock:
            if value_ms is None:
                return self.level
            if value_ms > self.slo_ms * self.enter:
                self._hot += 1
                self._cool = 0
                if self._hot >= self.patience \
                        and self.level < len(self.LEVELS) - 1:
                    self.level += 1
                    self.transitions += 1
                    self._hot = 0
                    inc("veles_qos_brownout_transitions_total")
            elif value_ms < self.slo_ms * self.exit:
                self._cool += 1
                self._hot = 0
                if self._cool >= self.patience and self.level > 0:
                    self.level -= 1
                    self.transitions += 1
                    self._cool = 0
                    inc("veles_qos_brownout_transitions_total")
            else:
                # inside the hysteresis band: hold, reset streaks
                self._hot = 0
                self._cool = 0
            return self.level

    def degrade(self, body: Dict) -> bool:
        """Apply the current level's degradation to a request body
        IN PLACE (level 1+: cap ``n_new``; level 2+: speculative →
        its plain equivalent — temperature 0 speculative IS greedy
        and sampled speculative keeps its sampling distribution as
        ``mode=sample``, so answers stay within contract while the
        draft/verify cost disappears). Returns True when anything
        was changed (the caller counts degraded requests). Level 3
        shedding is an ADMISSION decision, not a mutation — see
        :meth:`OverloadGovernor.admit`."""
        changed = False
        if self.level >= 1:
            n_new = body.get("n_new")
            if isinstance(n_new, int) and n_new > self.cap_n_new:
                body["n_new"] = self.cap_n_new
                changed = True
        if self.level >= 2 and body.get("mode") == "speculative":
            t = body.get("temperature", 0.0)
            body["mode"] = ("sample"
                            if isinstance(t, (int, float)) and t > 0
                            else "greedy")
            changed = True
        return changed


class RetryTokenBucket:
    """Router-wide failover-retry budget: ``rate`` tokens/second up
    to ``burst``. Every failover retry takes one token; a dry bucket
    denies the retry, capping the amplification factor a storm of
    failing attempts can impose on surviving replicas. Thread-safe;
    the clock is injectable for tests."""

    def __init__(self, rate: float = 10.0, burst: float = 20.0,
                 clock: Optional[Callable[[], float]] = None) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self._clock = clock or time.monotonic
        self._tokens = self.burst
        self._last = self._clock()
        self._lock = threading.Lock()

    def take(self) -> bool:
        with self._lock:
            now = self._clock()
            self._tokens = min(
                self.burst,
                self._tokens + max(0.0, now - self._last) * self.rate)
            self._last = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def available(self) -> float:
        with self._lock:
            now = self._clock()
            return min(
                self.burst,
                self._tokens + max(0.0, now - self._last) * self.rate)


class OverloadGovernor:
    """The FleetRouter's overload policy bundle: one AIMD controller,
    one brownout ladder and one retry bucket sharing a single
    (interval-throttled) poll of the SLO histograms. The router asks
    :meth:`admit` before dispatching a request, :meth:`degrade` to
    apply brownout mutations, and :meth:`allow_retry` before each
    failover retry; :meth:`snapshot` feeds the router's /metrics
    gauges."""

    def __init__(self, slo_ms: float = 500.0,
                 metric: str = "veles_serving_ttft_seconds",
                 quantile: float = 0.99,
                 aimd_floor: float = 0.05, aimd_add: float = 0.05,
                 aimd_mult: float = 0.5, interval: float = 0.5,
                 brownout_enter: float = 1.5,
                 brownout_exit: float = 0.8,
                 brownout_patience: int = 3,
                 brownout_cap_n_new: int = 32,
                 retry_rate: float = 10.0,
                 retry_burst: float = 20.0) -> None:
        self.aimd = AIMDController(
            slo_ms=slo_ms, metric=metric, quantile=quantile,
            floor=aimd_floor, additive=aimd_add,
            multiplicative=aimd_mult, interval=interval)
        self.ladder = BrownoutLadder(
            slo_ms=slo_ms, enter=brownout_enter, exit=brownout_exit,
            patience=brownout_patience, cap_n_new=brownout_cap_n_new)
        self.retries = RetryTokenBucket(rate=retry_rate,
                                        burst=retry_burst)
        self._obs_lock = threading.Lock()
        self._last_obs = 0.0

    def observe(self, now: Optional[float] = None,
                value_ms: Optional[float] = None) -> None:
        """One throttled poll feeding BOTH the AIMD rate and the
        ladder (they must see the same signal, or they could disagree
        about which regime the fleet is in)."""
        now = time.monotonic() if now is None else now
        with self._obs_lock:
            if value_ms is None:
                if now - self._last_obs < self.aimd.interval:
                    return
                self._last_obs = now
                value_ms = self.aimd.observed_ms()
        self.aimd.observe(now=now, value_ms=value_ms)
        self.ladder.observe(value_ms)

    def admit(self, body: Dict) -> Optional[str]:
        """Admission verdict for one request: None to admit, else the
        shed reason. Interactive traffic is ALWAYS admitted — the
        whole apparatus exists to protect it; batch absorbs the
        throttling (AIMD thinning, then level-3 outright shedding)."""
        self.observe()
        if request_priority(body) != "batch":
            return None
        if self.ladder.level >= 3:
            inc("veles_qos_throttled_total")
            return ("brownout level %d (%s): batch requests shed"
                    % (self.ladder.level,
                       self.ladder.LEVELS[self.ladder.level]))
        if not self.aimd.grant():
            inc("veles_qos_throttled_total")
            return ("batch admission throttled (AIMD rate %.2f vs "
                    "TTFT p99 over %.0f ms SLO)"
                    % (self.aimd.rate, self.aimd.slo_ms))
        return None

    def degrade(self, body: Dict) -> None:
        """Apply brownout mutations to an ADMITTED request body,
        counting each degraded request once."""
        if self.ladder.degrade(body):
            inc("veles_qos_degraded_requests_total")

    def allow_retry(self) -> bool:
        """One failover retry's token — False caps the storm (the
        router answers with the last attempt's error instead)."""
        if self.retries.take():
            return True
        inc("veles_qos_retry_denied_total")
        return False

    def retry_after(self, base: float = 1.0) -> float:
        """Shed-answer backoff hint scaled by how throttled batch
        admission currently is (rate 1.0 → ``base``; at the AIMD
        floor → :data:`RETRY_AFTER_MAX`)."""
        pressure = 1.0 - self.aimd.rate
        return min(RETRY_AFTER_MAX,
                   max(base, base + (RETRY_AFTER_MAX - base)
                       * pressure))

    def snapshot(self) -> Dict[str, float]:
        """Live gauges for /metrics (documented in
        docs/observability.md)."""
        return {"veles_qos_admit_rate": round(self.aimd.rate, 4),
                "veles_qos_brownout_level": float(self.ladder.level),
                "veles_qos_retry_tokens": round(
                    self.retries.available(), 2)}


def governor_from_config() -> Optional[OverloadGovernor]:
    """Build the router's governor from ``root.common.router.*``
    knobs, or None when ``root.common.router.qos`` (default off) is
    not set — the feature-off router runs the exact pre-QoS path."""
    try:
        from ..config import root
        cfg = root.common.router
        if not bool(cfg.get("qos", False)):
            return None
        return OverloadGovernor(
            slo_ms=float(cfg.get("slo_ttft_ms", 500.0)),
            metric=str(cfg.get("slo_metric",
                               "veles_serving_ttft_seconds")),
            quantile=float(cfg.get("slo_quantile", 0.99)),
            aimd_floor=float(cfg.get("aimd_floor", 0.05)),
            aimd_add=float(cfg.get("aimd_add", 0.05)),
            aimd_mult=float(cfg.get("aimd_mult", 0.5)),
            interval=float(cfg.get("aimd_interval", 0.5)),
            brownout_enter=float(cfg.get("brownout_enter", 1.5)),
            brownout_exit=float(cfg.get("brownout_exit", 0.8)),
            brownout_patience=int(cfg.get("brownout_patience", 3)),
            brownout_cap_n_new=int(cfg.get("brownout_cap_n_new", 32)),
            retry_rate=float(cfg.get("retry_rate", 10.0)),
            retry_burst=float(cfg.get("retry_burst", 20.0)))
    except Exception:       # noqa: BLE001 — config not importable
        return None
