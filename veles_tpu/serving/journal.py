"""Durable request journal: the router's write-ahead log.

The fleet router (PR 12) answered every request *exactly once* — but
only while the router process lived: its admission state was pure
memory, so a router SIGKILL lost every queued and in-flight request
outright. This module is the missing durability layer, built on the
same disk discipline as :mod:`~veles_tpu.resilience.checkpoint_chain`:

- **append before dispatch**: every admitted request is appended as
  one JSONL record ``{op: "admit", request_id, enqueued_at, body}``
  — flushed and ``fsync``'d — BEFORE the first replica attempt, so
  an accepted request exists on disk or was never acknowledged;
- **terminal on answer**: the answer (success and shed alike)
  appends ``{op: "done", request_id, status, outcome}``; a request
  with an ``admit`` but no ``done`` is by definition unanswered;
- **per-record hash**: each record carries a truncated SHA-256 of
  its own payload, so a torn append (power cut mid-line) or bitrot
  is detected per record — :meth:`RequestJournal.replay` quarantines
  such records with a counted warning
  (``veles_journal_salvaged_total``), mirroring the
  ``spans.read_jsonl`` salvage rule: a damaged journal degrades,
  it never refuses to start;
- **rotation + compaction**: past ``rotate_every`` appends the live
  (unanswered) entries are rewritten into a fresh segment with the
  checkpoint chain's tmp → ``fsync`` → ``os.replace`` commit and a
  SHA-256 sidecar manifest, and the old segments are deleted — the
  journal's size is bounded by the in-flight window, not by
  traffic history.

Replay contract (``veles-tpu route --journal DIR``): on restart the
router loads :meth:`pending` — unanswered admits, deduplicated by
``request_id`` (idempotent however many times a crash-loop re-ran),
ordered by ``enqueued_at`` — re-dispatches each one, and sheds the
ones already past their deadline with a terminal 503 record carrying
the id. Chaos surface: the ``router.journal`` fault point fires at
every append and every replay read (``corrupt`` damages the record
bytes; ``raise`` at append refuses the admission rather than accept
it un-journaled).
"""

from __future__ import annotations

import glob
import hashlib
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ..logger import Logger
from ..resilience.checkpoint_chain import commit_file, write_manifest
from ..resilience.faults import fire as fire_fault
from ..telemetry.counters import inc

#: journal segment naming: journal-<seq>.jsonl, replayed in seq order
SEGMENT_PREFIX = "journal-"
SEGMENT_SUFFIX = ".jsonl"


def _record_hash(rec: Dict[str, Any]) -> str:
    """Truncated SHA-256 of the record's canonical JSON (without the
    hash field itself) — 12 hex chars detect torn writes and bitrot
    per record without doubling the journal's size."""
    body = {k: v for k, v in rec.items() if k != "h"}
    payload = json.dumps(body, sort_keys=True).encode()
    return hashlib.sha256(payload).hexdigest()[:12]


def _parse_record(line: str) -> Optional[Dict[str, Any]]:
    """One journal line → record, or None when the line is torn,
    non-JSON, not a journal record, or fails its own hash."""
    try:
        rec = json.loads(line)
    except ValueError:
        return None
    if not isinstance(rec, dict) or "op" not in rec \
            or "request_id" not in rec:
        return None
    if rec.get("h") != _record_hash(rec):
        return None
    return rec


class RequestJournal(Logger):
    """Write-ahead request log over a directory of JSONL segments.
    Thread-safe: the router's handler threads append concurrently.
    ``fsync=False`` trades the power-cut guarantee for speed (tests,
    tmpfs); the default is durable."""

    def __init__(self, directory: str, rotate_every: int = 4096,
                 fsync: bool = True, name: str = "journal") -> None:
        super().__init__()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.rotate_every = max(16, int(rotate_every))
        self.fsync = bool(fsync)
        self.name = name
        self._lock = threading.Lock()
        self._fh = None
        self._appended = 0          # records in the ACTIVE segment
        segs = self.segments()
        self._seq = (self._seg_seq(segs[-1]) if segs else 0)

    # -- segment bookkeeping -------------------------------------------------
    def segments(self) -> List[str]:
        """Journal segment paths, oldest first (seq order)."""
        out = []
        for path in glob.glob(os.path.join(
                self.directory, SEGMENT_PREFIX + "*" + SEGMENT_SUFFIX)):
            if path.endswith(".tmp"):
                continue
            out.append(path)
        return sorted(out, key=self._seg_seq)

    @staticmethod
    def _seg_seq(path: str) -> int:
        base = os.path.basename(path)
        try:
            return int(base[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])
        except ValueError:
            return 0

    def _active_path(self) -> str:
        return os.path.join(self.directory, "%s%06d%s"
                            % (SEGMENT_PREFIX, self._seq,
                               SEGMENT_SUFFIX))

    def _open_locked(self):
        if self._fh is None:
            self._fh = open(self._active_path(), "a")
        return self._fh

    # -- append (the durability boundary) ------------------------------------
    def append(self, op: str, request_id: str, **fields: Any) -> None:
        """Durably append one record. Raises
        :class:`~veles_tpu.resilience.faults.FaultInjected` when an
        armed ``router.journal`` clause says ``raise`` (the caller
        sheds the admission rather than accept it un-journaled); an
        armed ``corrupt`` clause damages the written bytes — replay's
        salvage pass is the proof that does not kill the journal."""
        rec = dict(fields, op=str(op), request_id=str(request_id))
        rec["h"] = _record_hash(rec)
        data = (json.dumps(rec, sort_keys=True) + "\n").encode()
        corrupting = fire_fault("router.journal")
        if corrupting is not None:
            data = corrupting.corrupt(data)
        with self._lock:
            fh = self._open_locked()
            fh.write(data.decode("utf-8", "replace"))
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
            self._appended += 1
            rotate = self._appended >= self.rotate_every
        inc("veles_journal_appends_total")
        if rotate:
            # the record above is already durable: a compaction
            # failure (disk full, injected replay-side fault) must
            # not convert this ACCEPTED append into a caller-visible
            # refusal — the next rotation threshold retries it
            try:
                self.compact()
            except Exception as e:  # noqa: BLE001 — append is durable
                self.warning("%s: rotation compaction failed (%s: "
                             "%s); the journal keeps appending to "
                             "the current segment", self.name,
                             type(e).__name__, e)

    def admit(self, request_id: str, body: Dict[str, Any],
              enqueued_at: float,
              trace_id: Optional[str] = None) -> None:
        """Journal an accepted request BEFORE its first dispatch.
        ``trace_id`` (the fleet tracing key the router mints at the
        same admission) rides the record top-level — a journal dump
        cross-references a merged fleet trace without digging through
        each record's body."""
        fields: Dict[str, Any] = {"body": body,
                                  "enqueued_at": float(enqueued_at)}
        if trace_id:
            fields["trace_id"] = str(trace_id)
        self.append("admit", request_id, **fields)

    def done(self, request_id: str, status: int,
             outcome: str = "answered",
             trace_id: Optional[str] = None,
             attempts: Optional[int] = None) -> None:
        """Journal the answer (success and shed alike) — the record
        that makes replay idempotent by ``request_id``. ``trace_id``
        and ``attempts`` (how many replica tries the answer took)
        carry the fleet-tracing correlation into the terminal record
        too."""
        fields: Dict[str, Any] = {"status": int(status),
                                  "outcome": str(outcome)}
        if trace_id:
            fields["trace_id"] = str(trace_id)
        if attempts is not None:
            fields["attempts"] = int(attempts)
        self.append("done", request_id, **fields)

    # -- read back -----------------------------------------------------------
    def replay(self) -> Tuple[Dict[str, Dict[str, Any]],
                              Dict[str, Dict[str, Any]]]:
        """Read every segment oldest→newest into
        ``(admits, terminals)`` keyed by ``request_id`` (idempotent:
        duplicate admits of one id collapse to the first). Torn or
        corrupt records — including injected ``router.journal``
        corruption — are quarantined with ONE counted warning
        (``veles_journal_salvaged_total``), never a refused start."""
        admits: Dict[str, Dict[str, Any]] = {}
        terminals: Dict[str, Dict[str, Any]] = {}
        bad = 0
        for path in self.segments():
            try:
                with open(path, errors="replace") as fin:
                    lines = fin.readlines()
            except OSError as e:
                bad += 1
                self.warning("%s: segment %s unreadable (%s)",
                             self.name, path, e)
                continue
            for line in lines:
                line = line.strip()
                if not line:
                    continue
                corrupting = fire_fault("router.journal")
                if corrupting is not None:
                    line = corrupting.corrupt(
                        line.encode()).decode("utf-8", "replace")
                rec = _parse_record(line)
                if rec is None:
                    bad += 1
                    continue
                rid = rec["request_id"]
                if rec["op"] == "admit":
                    admits.setdefault(rid, rec)
                elif rec["op"] == "done":
                    terminals[rid] = rec
        if bad:
            inc("veles_journal_salvaged_total", bad)
            self.warning(
                "%s: quarantined %d torn/corrupt journal record(s) in "
                "%s (mid-write truncation or bitrot; the survivors "
                "replay normally)", self.name, bad, self.directory)
        return admits, terminals

    def pending(self) -> List[Dict[str, Any]]:
        """Unanswered admits, ordered by ``enqueued_at`` — what a
        restarted router must re-dispatch (or shed past-deadline,
        with the id)."""
        admits, terminals = self.replay()
        live = [rec for rid, rec in admits.items()
                if rid not in terminals]
        return sorted(live, key=lambda r: (r.get("enqueued_at", 0.0),
                                           r["request_id"]))

    def pending_count(self) -> int:
        return len(self.pending())

    # -- rotation ------------------------------------------------------------
    def compact(self) -> int:
        """Rewrite the live (unanswered) entries into a fresh segment
        with the checkpoint chain's atomic commit + SHA-256 sidecar
        manifest, then delete every older segment (and sidecar). The
        journal's footprint is the in-flight window, not history.
        Returns the number of live entries kept."""
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            old = self.segments()
            live = self.pending()
            self._seq += 1
            path = self._active_path()
            tmp = path + ".tmp"
            with open(tmp, "w") as fout:
                for rec in live:
                    fout.write(json.dumps(rec, sort_keys=True) + "\n")
                fout.flush()
                os.fsync(fout.fileno())
            commit_file(tmp, path)
            write_manifest(path, prefix="journal", entries=len(live))
            for victim in old:
                for f in (victim, victim + ".manifest.json"):
                    try:
                        os.unlink(f)
                    except OSError:
                        pass
            self._appended = len(live)
        inc("veles_journal_compactions_total")
        self.info("%s: compacted -> %s (%d live entr%s, %d old "
                  "segment(s) dropped)", self.name,
                  os.path.basename(path), len(live),
                  "y" if len(live) == 1 else "ies", len(old))
        return len(live)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
