"""Iteration-level scheduler for the continuous-batching engine.

Pure host-side bookkeeping (no jax): a bounded request queue, the
``max_slots`` slot table, the page-pool admission ledger, prefill-
bucket selection and deadline enforcement. The engine calls
:meth:`SlotScheduler.take_admissions` at every step boundary — queued
requests move into free slots the moment one opens AND the page pool
can hold their prompt, so the chip never idles while the queue is
non-empty, and a ticket older than its deadline is answered 503 +
Retry-After instead of silently sitting in the queue.

Since the paged-pool rework, admission is on PAGE availability, not
raw slot count: a request is admitted when a slot (``beam_width``
slots for ``mode=beam``) is free and the allocator can RESERVE its
own worst case — ``ceil(max(bucket, prompt + n_new [+ gamma + 1]) /
page_size)`` pages per row, never ``max_context`` — so short
requests pack many-to-a-pool and a row cannot hit exhaustion
mid-decode in normal operation. Decode-time growth (:meth:`grow`) is
the engine's accounting safety net; a row it cannot cover (or an
injected ``serve.page_alloc`` fault) is shed with 503 + Retry-After
while everyone else keeps decoding.
"""

from __future__ import annotations

import itertools
import os
import queue as _queue_mod
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..resilience.faults import FaultInjected, fire as fire_fault
from ..telemetry.counters import inc, observe
from .overload import dynamic_retry_after, request_priority
from .pages import PagePool, pages_for

_request_ids = itertools.count(1)

#: THE decode modes whose emitted-token prefix a failover retry can
#: resume: their per-slot PRNG stream advances exactly one split per
#: emitted token, so a resumed prefill re-enters it mid-decode.
#: Single source of truth — the engine's step plane, the router's
#: fold logic and Ticket.set_progress all import this tuple.
RESUME_MODES = ("greedy", "sample")


def new_request_id() -> str:
    """Process-unique serving request id, assigned at API admission
    and threaded through the whole Ticket lifecycle (span tags,
    flight-recorder events, the response body). The pid prefix keeps
    ids distinct across a fleet of engine replicas whose /metrics a
    ``veles-tpu metrics aggregate`` merges."""
    return "req-%d-%d" % (os.getpid(), next(_request_ids))


def new_trace_id() -> str:
    """Process-unique fleet trace id — the request_id family's
    naming (pid prefix, shared counter) applied to the CROSS-process
    correlation key: the fleet router mints one per accepted request
    and forwards it with every attempt, so however many replicas (and
    retries) serve the request, every span, flight-recorder event and
    journal record of its story carries the same ``trace_id`` — what
    ``veles-tpu trace fleet --request ID`` assembles a timeline
    from. A request that never crosses a router gets its own
    request_id as its trace_id (Ticket default), so single-replica
    traces need no router to exist."""
    return "trace-%d-%d" % (os.getpid(), next(_request_ids))


def request_tracing_enabled() -> bool:
    """THE per-request tracing switch (``root.common.trace.requests``,
    default on). Gates only the HOST-SIDE span/flight emission at
    ticket terminal — never device work, so dispatch counts are
    bit-identical on and off (locked by tests/test_request_tracing.py).
    The SLO histograms record regardless: p99 TTFT must be answerable
    on a fleet that runs with tracing off."""
    try:
        from ..config import root
        return bool(root.common.trace.get("requests", True))
    except Exception:        # noqa: BLE001 — config not importable
        return True


class Ticket:
    """One request's rendezvous between an HTTP handler thread and a
    serving worker (the generation twin of ``restful_api._Ticket``).
    The worker fills ``result`` (or ``error`` + ``code``) and sets
    ``event``; ``retry_after`` asks the handler to attach a
    ``Retry-After`` header (503 shed/expiry answers); ``deadline`` is
    the absolute wall time after which the request must no longer be
    served from the queue.

    The ticket is also the request-plane SLO record: it carries a
    process-unique ``request_id`` and host-side lifecycle timestamps
    (``enqueued`` → ``admitted`` → ``prefill_done`` → ``first_token``
    → terminal), stamped by the planes at step boundaries only.
    :meth:`succeed`/:meth:`fail` are EXACTLY-ONCE: the first terminal
    call records the per-request histograms (queue wait, TTFT, TPOT,
    end-to-end — ``telemetry/counters.py`` HISTOGRAMS), emits the
    request's lifecycle spans tagged with its id, and notes a
    terminal flight-recorder event; any later call is a no-op
    returning False — a ticket swept by both the tick path and the
    failure path can never double-count."""

    __slots__ = ("event", "result", "error", "code", "retry_after",
                 "deadline", "enqueued", "request_id", "trace_id",
                 "attempt", "mode",
                 "admitted", "prefill_done", "first_token",
                 "n_tokens", "outcome", "progress", "_terminal_lock",
                 "stream", "_stream_q")

    def __init__(self, deadline: Optional[float] = None,
                 request_id: Optional[str] = None,
                 mode: str = "greedy",
                 trace_id: Optional[str] = None,
                 attempt: int = 1,
                 stream: bool = False) -> None:
        self._terminal_lock = threading.Lock()
        self.event = threading.Event()
        self.result = None
        self.error: Optional[str] = None
        self.code: int = 500
        self.retry_after: Optional[float] = None
        self.deadline = deadline
        self.enqueued = time.time()
        self.request_id = request_id or new_request_id()
        #: fleet-wide correlation key: adopted from the router's body
        #: when one arrives, else the request's own id — every
        #: lifecycle span/flight event carries it, so a single
        #: replica's trace joins a fleet trace seamlessly
        self.trace_id = trace_id or self.request_id
        #: which routing attempt this ticket serves (1-based; the
        #: router numbers retries, a direct request is attempt 1)
        self.attempt = max(1, int(attempt or 1))
        self.mode = str(mode)
        self.admitted: Optional[float] = None
        self.prefill_done: Optional[float] = None
        self.first_token: Optional[float] = None
        self.n_tokens = 0
        self.outcome: Optional[str] = None
        #: tokens emitted before a mid-decode failure/handoff — the
        #: token-level resume record a failover retry continues from
        self.progress: Optional[List[int]] = None
        #: token streaming (``stream=true`` requests): the serving
        #: plane pushes emitted tokens at step boundaries and the HTTP
        #: handler drains them onto the wire as SSE events; terminal
        #: settles enqueue a ``None`` sentinel so the drain loop ends
        #: the moment the answer exists
        self.stream = bool(stream)
        self._stream_q: Optional["_queue_mod.SimpleQueue"] = (
            _queue_mod.SimpleQueue() if self.stream else None)

    # -- lifecycle stamps (host-side, step boundaries only) ------------------
    def mark_admitted(self) -> None:
        """Stamp queue exit (slot admission / window-batch pop); first
        stamp wins — a beam group's sibling slots share one ticket."""
        if self.admitted is not None:
            return
        self.admitted = time.time()
        if request_tracing_enabled():
            try:
                from ..telemetry.recorder import flight
                flight.note("request", request_id=self.request_id,
                            trace_id=self.trace_id,
                            attempt=self.attempt,
                            phase="admitted", mode=self.mode)
            except Exception:       # noqa: BLE001 — observers only
                pass

    def mark_prefill_done(self) -> None:
        if self.prefill_done is None:
            self.prefill_done = time.time()

    def mark_first_token(self) -> None:
        if self.first_token is None:
            self.first_token = time.time()

    def set_progress(self, tokens) -> None:
        """Attach the emitted-token prefix BEFORE a terminal
        :meth:`fail` — the failure answer then carries
        ``{resume: {tokens, tokens_done}}`` so a router retry can
        continue the decode from ``tokens_done`` instead of token 0.
        Only the plain decode modes resume (greedy/sample own a
        per-slot PRNG stream a resumed prefill can re-enter exactly;
        speculative/beam and the window plane retry from scratch), so
        other modes never attach progress. No-op after terminal."""
        if self.mode not in RESUME_MODES:
            return
        if not self.event.is_set():
            self.progress = [int(t) for t in tokens]

    # -- token streaming ------------------------------------------------------
    def push_tokens(self, tokens) -> None:
        """Hand freshly emitted tokens to the streaming drain loop (a
        no-op for buffered tickets). Stamps first-token time: the
        moment a token enters this queue it is one queue hop from the
        client's socket, so the TTFT histogram now measures a real
        client-visible first token — not an internal prefill sync a
        buffered response would sit on for the whole generation."""
        if self._stream_q is None:
            return
        toks = [int(t) for t in tokens]
        if not toks:
            return
        self.mark_first_token()
        self._stream_q.put(toks)

    def next_stream_item(self, timeout: float):
        """Blocking drain step for the HTTP streaming handler: a token
        list, the ``None`` terminal sentinel, or raises
        ``queue.Empty`` on timeout."""
        assert self._stream_q is not None
        return self._stream_q.get(timeout=timeout)

    # -- terminal (exactly once) ---------------------------------------------
    def fail(self, error: str, code: int = 500,
             retry_after: Optional[float] = None,
             outcome: Optional[str] = None) -> bool:
        """Answer with an error; True only on the FIRST terminal call
        (callers count shed/expiry on that True, so a ticket seen by
        two sweeps is still counted once). The terminal transition is
        LOCKED, not a bare is_set() check: a wedged tick thread's late
        sweep racing a stop()-side abort must not double-record the
        histograms or let both callers count the shed."""
        with self._terminal_lock:
            if self.event.is_set():
                return False
            self.error = error
            self.code = code
            self.retry_after = retry_after
            self._account(outcome
                          or ("shed" if code == 503 else "error"))
            self.event.set()
            if self._stream_q is not None:
                self._stream_q.put(None)
        return True

    def succeed(self, result) -> bool:
        """Answer with a result; True only on the first terminal call.
        Dict results are stamped with the ``request_id`` so both
        decode planes answer with the id the trace/flight events
        carry."""
        with self._terminal_lock:
            if self.event.is_set():
                return False
            if isinstance(result, dict):
                result.setdefault("request_id", self.request_id)
                self.n_tokens = len(result.get("tokens") or ())
            self.result = result
            self._account("retired")
            self.event.set()
            if self._stream_q is not None:
                self._stream_q.put(None)
        return True

    def error_payload(self) -> Dict:
        """THE failure response body both HTTP planes send for a
        terminal-failed ticket: the error plus this request's id (and
        the shed's ``retry_after`` hint when one was set), so a fleet
        router retrying the request can correlate a shed/expiry with
        the attempt it belongs to — success bodies already carry the
        id via :meth:`succeed`."""
        body: Dict = {"error": self.error,
                      "request_id": self.request_id}
        if self.retry_after is not None:
            # dynamic backoff (docs/services.md "Overload & QoS"):
            # with a QoS pressure provider registered, the hint
            # scales with live queue depth so storming clients back
            # off proportionally; with QoS off, exactly the static
            # hint the terminal call set
            body["retry_after"] = self.retry_after_hint()
        if self.progress is not None:
            # the token-level resume record: this ATTEMPT's emitted
            # tokens (a resumed attempt reports only its own new
            # tokens — the router accumulates prefixes across
            # attempts), continuing the same per-slot PRNG stream
            body["resume"] = {"tokens": list(self.progress),
                              "tokens_done": len(self.progress)}
        return body

    def retry_after_hint(self) -> Optional[float]:
        """The ``Retry-After`` value this ticket's failure answer
        should carry — the static hint :meth:`fail` set, scaled by
        live queue pressure when a QoS pressure provider is
        registered (serving/overload.py)."""
        return dynamic_retry_after(self.retry_after)

    def _account(self, outcome: str) -> None:
        """Terminal SLO accounting — histograms always, span/flight
        emission under the tracing switch. Never raises: a broken
        observer must not lose the request's answer. Deliberately
        runs INSIDE the terminal lock, before ``event.set()``:
        answered must imply accounted (the bench SLO proof and the
        tests read the histograms the moment ``serve()`` returns),
        and the cost is bounded — once per REQUEST at a step
        boundary (≤ 4 small JSONL lines when a trace sink is open),
        never on the per-token path."""
        now = time.time()
        self.outcome = outcome
        try:
            if self.admitted is not None:
                observe("veles_serving_queue_wait_seconds",
                        max(0.0, self.admitted - self.enqueued))
            elif outcome in ("expired", "shed"):
                # died in the queue: its whole life WAS queue wait
                observe("veles_serving_queue_wait_seconds",
                        max(0.0, now - self.enqueued))
            if self.first_token is not None:
                observe("veles_serving_ttft_seconds",
                        max(0.0, self.first_token - self.enqueued))
                if outcome == "retired" and self.n_tokens > 1:
                    observe("veles_serving_tpot_seconds",
                            max(0.0, now - self.first_token)
                            / (self.n_tokens - 1))
            if outcome == "retired":
                observe("veles_serving_e2e_seconds",
                        max(0.0, now - self.enqueued))
            if not request_tracing_enabled():
                return
            from ..telemetry.recorder import flight
            from ..telemetry.spans import emit
            rid = self.request_id
            # every lifecycle span carries the fleet correlation pair
            # — trace_id + attempt — so a cross-process assembly
            # (veles-tpu trace fleet) stitches this replica's leg of
            # the request into the router's route.attempt bracket
            tags = {"request_id": rid, "trace_id": self.trace_id,
                    "attempt": self.attempt}
            if self.admitted is not None:
                emit("request.queue", self.enqueued,
                     self.admitted - self.enqueued, **tags)
                if self.prefill_done is not None:
                    emit("request.prefill", self.admitted,
                         self.prefill_done - self.admitted, **tags)
            if self.first_token is not None:
                emit("request.decode", self.first_token,
                     now - self.first_token, tokens=self.n_tokens,
                     **tags)
            emit("request", self.enqueued, now - self.enqueued,
                 outcome=outcome, mode=self.mode,
                 tokens=self.n_tokens, **tags)
            flight.note("request", request_id=rid,
                        trace_id=self.trace_id, attempt=self.attempt,
                        phase="done",
                        outcome=outcome, mode=self.mode,
                        tokens=self.n_tokens,
                        dur=round(now - self.enqueued, 6))
        except Exception:       # noqa: BLE001 — observability only
            pass


def split_expired(pairs: List[Tuple[Dict, Ticket]],
                  now: Optional[float] = None
                  ) -> Tuple[List[Tuple[Dict, Ticket]], List[Ticket]]:
    """Partition ``(req, ticket)`` pairs into (still live, expired
    tickets) by deadline — the check every dequeue point applies."""
    now = time.time() if now is None else now
    live, expired = [], []
    for req, ticket in pairs:
        if ticket.deadline is not None and now > ticket.deadline:
            expired.append(ticket)
        else:
            live.append((req, ticket))
    return live, expired


def shed_expired(tickets: List[Ticket]) -> None:
    """THE one deadline answer both decode planes give: 503 +
    Retry-After, counted — a ticket never rots in a queue past its
    useful life. Counting keys off :meth:`Ticket.fail`'s first-
    terminal True, so a ticket swept by BOTH the tick path and the
    failure path (a tick dying between ``take_admissions`` and its
    shed, then the loop's ``expire_queued`` sweep) still counts its
    expiry — and its queue-wait histogram sample — exactly once."""
    for ticket in tickets:
        if ticket.fail("request expired in serving queue", code=503,
                       retry_after=1.0, outcome="expired"):
            inc("veles_serving_expired_total")
            inc("veles_shed_requests_total")


class BeamGroup:
    """Host state shared by the ``beam_width`` hypothesis slots of one
    beam request. The engine fills the search state (current tokens,
    scores, finished flags) after the prefill expansion and advances
    it one top-k step per tick; the group retires as a unit."""

    __slots__ = ("req", "ticket", "slots", "live", "cur", "scores",
                 "finished", "toks", "step", "t_p")

    def __init__(self, req: Dict, ticket: Ticket) -> None:
        self.req = req
        self.ticket = ticket
        self.slots: List["Slot"] = []
        self.live = 0               # hypothesis slots not yet retired
        self.cur = None             # (W,) int32 current tokens
        self.scores = None          # (W,) f64 cumulative log-probs
        self.finished = None        # (W,) bool — eos frozen
        self.toks = None            # (W, n_new) emitted token matrix
        self.step = 0               # decoded positions past the first
        self.t_p = len(req["prompt"])


class Slot:
    """Host state of one occupied KV-cache row. ``pages`` are the page
    ids this row holds (freed at retirement); ``mode`` selects which
    fixed-shape program advances it (``greedy``/``sample`` ride the
    decode step, ``speculative`` the draft/verify round, ``beam`` the
    group top-k step); ``group`` links beam hypothesis rows."""

    __slots__ = ("idx", "req", "ticket", "t_p", "bucket", "tokens",
                 "n_new", "eos_id", "temperature", "mode", "pages",
                 "group", "rounds", "acc", "prefilled", "shared")

    def __init__(self, idx: int, req: Dict, ticket: Ticket,
                 bucket: int, pages: Optional[List[int]] = None,
                 group: Optional[BeamGroup] = None) -> None:
        self.idx = idx
        self.req = req
        self.ticket = ticket
        self.t_p = len(req["prompt"])
        self.bucket = bucket
        self.tokens: List[int] = []
        self.n_new = int(req["n_new"])
        self.eos_id = req.get("eos_id")
        self.temperature = float(req.get("temperature", 0.0))
        self.mode = str(req.get("mode", "greedy"))
        self.pages = list(pages or [])
        self.group = group
        self.rounds = 0     # speculative: draft/verify rounds run
        self.acc = 0        # speculative: total accepted draft tokens
        #: chunked prefill cursor: positions already written, or None
        #: once the prompt is fully prefilled (monolithic prefills
        #: never set it) — rows with a cursor are excluded from the
        #: decode step until their final chunk lands
        self.prefilled: Optional[int] = None
        #: leading page-table entries adopted READ-ONLY from the
        #: prefix cache — the decode step's write-back masks them to
        #: the sink, so a writer can never mutate a shared page
        self.shared = 0

    def record(self, token: int) -> bool:
        """Append one emitted token; True when the row is finished
        (its own ``n_new`` reached, or ``eos_id`` emitted — the moment
        continuous batching frees the slot for the next request,
        instead of riding out the longest co-tenant)."""
        self.tokens.append(int(token))
        if self.eos_id is not None and int(token) == self.eos_id:
            return True
        return len(self.tokens) >= self.n_new


class SlotScheduler:
    """Bounded queue + slot table + page ledger. All methods are
    thread-safe; the engine's worker waits on :attr:`cv` and the HTTP
    threads notify it on :meth:`push`. ``page_pool=None`` keeps the
    legacy slots-only admission (unit tests of the queue geometry)."""

    def __init__(self, max_slots: int, buckets: Tuple[int, ...],
                 max_context: int,
                 page_pool: Optional[PagePool] = None,
                 beam_width: int = 4, spec_gamma: int = 4,
                 slot_kind: str = "paged") -> None:
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = int(max_slots)
        #: what a slot's per-request memory IS: "paged" rows hold a
        #: page table over the KV pool, "state" rows (the O(1) lane,
        #: serving/recurrent.py) hold a fixed recurrent-state tensor
        #: and never touch the page ledger. Stats/metrics key off this
        #: so a pageless replica's rows never enter the fleet's
        #: veles_serving_pages_* math
        self.slot_kind = str(slot_kind)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.max_context = int(max_context)
        if self.buckets[-1] > self.max_context:
            raise ValueError(
                "largest prefill bucket %d exceeds max_context %d"
                % (self.buckets[-1], self.max_context))
        self.page_pool = page_pool
        self.beam_width = max(1, int(beam_width))
        #: the engine's fixed speculation round width — the default
        #: for requests that omit ``gamma``, so page reservation uses
        #: the round size the spec program will actually run
        self.spec_gamma = max(1, int(spec_gamma))
        #: concurrent beam groups the fixed-shape beam program holds
        self.beam_groups = self.max_slots // self.beam_width
        self._beams_active = 0
        self.cv = threading.Condition()
        self._queue: deque = deque()
        self._free: List[int] = list(range(self.max_slots))
        self.slots: List[Optional[Slot]] = [None] * self.max_slots
        #: QoS switch (set by the owning engine from
        #: ``root.common.serving.qos``): True makes admission
        #: priority-aware — interactive requests jump queued batch
        #: work (see :meth:`_promote_interactive_locked`). False (the
        #: default) keeps strict FIFO, bit-identical to the pre-QoS
        #: scheduler.
        self.qos = False

    # -- admission geometry --------------------------------------------------
    def bucket_for(self, t_p: int) -> Optional[int]:
        """Smallest prefill bucket holding a ``t_p``-token prompt (the
        jit cache stays bounded by len(buckets) prefill programs plus
        the fixed decode/round/beam steps, not by distinct prompt
        lengths)."""
        for b in self.buckets:
            if t_p <= b:
                return b
        return None

    def _worst_positions(self, t_p: int, n_new: int, mode: str,
                         gamma: int) -> int:
        """Cache positions a request can ever touch — what the page
        ledger must be able to hold for it to complete."""
        if mode == "speculative":
            return t_p + n_new + int(gamma) + 1
        if mode == "beam":
            return t_p + max(n_new - 1, 1)
        return t_p + n_new

    def reject_reason(self, t_p: int, n_new: int, mode: str = "greedy",
                      gamma: Optional[int] = None) -> Optional[str]:
        """None when the request fits the slot pool; otherwise why not
        (the caller falls back to the window-coalescing path, which
        compiles per exact shape and has no context ceiling)."""
        bucket = self.bucket_for(t_p)
        if bucket is None:
            return ("prompt length %d exceeds the largest serving "
                    "bucket %d" % (t_p, self.buckets[-1]))
        worst = self._worst_positions(
            t_p, n_new, mode,
            self.spec_gamma if gamma is None else gamma)
        if worst > self.max_context:
            return ("prompt %d + generation window %d exceeds "
                    "max_context %d (mode=%s)"
                    % (t_p, worst - t_p, self.max_context, mode))
        width = self.beam_width if mode == "beam" else 1
        if width > self.max_slots:
            return ("beam width %d exceeds the pool's %d slots"
                    % (width, self.max_slots))
        if self.page_pool is not None:
            need = width * pages_for(max(bucket, worst),
                                     self.page_pool.page_size)
            if need > self.page_pool.pages:
                return ("request needs %d pages at worst, the pool "
                        "holds %d" % (need, self.page_pool.pages))
        return None

    # -- queue ----------------------------------------------------------------
    def push(self, req: Dict, ticket: Ticket,
             max_queue: Optional[int] = None) -> bool:
        """Enqueue; False when the bound is hit (caller sheds 503)."""
        with self.cv:
            if max_queue is not None and len(self._queue) >= max_queue:
                return False
            self._queue.append((req, ticket))
            self.cv.notify_all()
        return True

    def queue_depth(self) -> int:
        with self.cv:
            return len(self._queue)

    def busy_count(self) -> int:
        with self.cv:
            return self.max_slots - len(self._free)

    def expire_queued(self, now: Optional[float] = None) -> List[Ticket]:
        """Remove every expired ticket from the queue (any position) —
        the failure-path sweep: when ticks cannot run, deadlines must
        still be honored instead of callers hanging to their full
        timeout."""
        with self.cv:
            live, expired = split_expired(list(self._queue), now)
            self._queue = deque(live)
        return expired

    # -- page ledger -----------------------------------------------------------
    #
    # SHARD-AGNOSTIC by construction: every count in this ledger —
    # pages_for(...) at validate/admission, grow()'s shortfall, the
    # pool's free list — is in LOGICAL pages (page_size positions of
    # one slot's cache). Under tensor-parallel serving the device
    # pool's kv-head axis shards over the ("model",) mesh
    # (pages.per_shard_kv_heads), which divides every page's BYTES
    # per chip but never its position count, so identical knobs admit
    # identical request mixes at tp=1 and tp=N — asserted by
    # tests/test_tp_serving.py's logical-gauge comparisons.
    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """Allocation with the ``serve.page_alloc`` fault point armed —
        the injection surface for page-exhaustion chaos. Raises
        :class:`FaultInjected` on an injected fault (callers shed);
        returns None on real exhaustion (admission waits for
        retirements, growth sheds)."""
        if self.page_pool is None:
            return []
        fire_fault("serve.page_alloc")
        return self.page_pool.alloc(n)

    def grow(self, slot: Slot, positions: int) -> bool:
        """Extend ``slot``'s page list to cover ``positions`` cache
        rows. True when covered (possibly without allocating); False
        means exhaustion or an injected ``serve.page_alloc`` fault —
        the engine sheds the row with 503 + Retry-After and frees its
        pages while the rest of the pool keeps decoding."""
        if self.page_pool is None:
            return True
        need = pages_for(positions, self.page_pool.page_size) \
            - len(slot.pages)
        if need <= 0:
            return True
        try:
            got = self._alloc_pages(need)
        except FaultInjected:
            return False
        if got is None:
            return False
        slot.pages.extend(got)
        return True

    # -- step-boundary transitions -------------------------------------------
    def take_admissions(self, now: Optional[float] = None
                        ) -> Tuple[List[Slot], List[Ticket]]:
        """Move queued requests into free slots (FIFO), dropping
        expired tickets. Admission is on page availability: the head
        request waits (keeping FIFO order) while the allocator cannot
        hold its prompt; an injected ``serve.page_alloc`` fault sheds
        it 503 + Retry-After instead. ``mode=beam`` requests take
        ``beam_width`` slots (one per hypothesis) plus one page set
        per slot. Returns (newly filled slots — the engine prefills
        each, expired tickets — the engine answers 503)."""
        now = time.time() if now is None else now
        admissions: List[Slot] = []
        expired: List[Ticket] = []
        with self.cv:
            if self.qos and len(self._queue) > 1:
                self._promote_interactive_locked()
            while self._queue:
                req, ticket = self._queue[0]
                if ticket.deadline is not None and now > ticket.deadline:
                    self._queue.popleft()
                    expired.append(ticket)
                    continue
                mode = str(req.get("mode", "greedy"))
                width = self.beam_width if mode == "beam" else 1
                if len(self._free) < width:
                    break
                if mode == "beam" and (
                        self._beams_active >= max(1, self.beam_groups)):
                    break
                bucket = self.bucket_for(len(req["prompt"]))
                if bucket is None:
                    # a poisoned head (checked=True submit bypassing
                    # accepts(), or a raw push) must be answered and
                    # dropped, not crash-loop every tick pre-pop
                    self._queue.popleft()
                    ticket.fail("prompt length %d exceeds the largest "
                                "serving bucket %d"
                                % (len(req["prompt"]),
                                   self.buckets[-1]), code=400)
                    continue
                # reserve the request's OWN worst case (prompt +
                # its n_new, never max_context): admission cost is
                # the request's actual footprint, so short requests
                # pack many-to-a-pool, and a row can never hit page
                # exhaustion mid-decode — growth past this is the
                # accounting safety net, not the steady state
                worst = max(bucket, self._worst_positions(
                    len(req["prompt"]), int(req["n_new"]), mode,
                    int(req.get("gamma", self.spec_gamma))))
                per_row = (0 if self.page_pool is None else
                           pages_for(worst, self.page_pool.page_size))
                rows_pages: List[List[int]] = []
                shed = starved = False
                for _ in range(width):
                    try:
                        got = self._alloc_pages(per_row)
                    except FaultInjected as e:
                        self._queue.popleft()
                        for back in rows_pages:
                            self.page_pool.free(back)
                        if ticket.fail(
                                "serving page pool exhausted: %s" % e,
                                code=503, retry_after=1.0):
                            inc("veles_shed_requests_total")
                        shed = True
                        break
                    if got is None:
                        # real exhaustion: keep FIFO order and wait
                        # for retirements to free pages
                        for back in rows_pages:
                            self.page_pool.free(back)
                        starved = True
                        break
                    rows_pages.append(got)
                if shed:
                    continue
                if starved:
                    break
                self._queue.popleft()
                ticket.mark_admitted()
                group = (BeamGroup(req, ticket) if mode == "beam"
                         else None)
                for w in range(width):
                    idx = self._free.pop(0)
                    slot = Slot(idx, req, ticket, bucket,
                                pages=rows_pages[w] if rows_pages
                                else [], group=group)
                    self.slots[idx] = slot
                    if group is not None:
                        group.slots.append(slot)
                        group.live += 1
                    admissions.append(slot)
                if group is not None:
                    self._beams_active += 1
            # even with no admission, purge expired tickets from ANY
            # queue position — a dead ticket behind a live head must
            # not rot to its handler's silent 504 while the pool is
            # full
            live, exp = split_expired(list(self._queue), now)
            self._queue = deque(live)
            expired.extend(exp)
        return admissions, expired

    def _promote_interactive_locked(self) -> None:
        """QoS admission order (``self.qos`` on, under ``cv``): a
        stable two-lane reorder — interactive tickets move ahead of
        queued batch work, each class keeping its own FIFO order —
        after which the admission loop runs UNCHANGED, so the
        page-wait / beam-cap semantics are identical in both modes.
        Batch is deferred, never dropped: it admits the moment no
        interactive request is waiting. Counts how many batch
        requests an interactive arrival actually jumped."""
        q = list(self._queue)
        hot = [p for p in q if request_priority(p[0]) == "interactive"]
        cold = [p for p in q if request_priority(p[0]) != "interactive"]
        if not hot or not cold or q == hot + cold:
            return
        last_hot = max(i for i, p in enumerate(q)
                       if request_priority(p[0]) == "interactive")
        jumped = sum(1 for p in q[:last_hot]
                     if request_priority(p[0]) != "interactive")
        if jumped:
            inc("veles_qos_batch_deferrals_total", jumped)
        self._queue = deque(hot + cold)

    def retire(self, slot: Slot) -> None:
        """Free the row — the very next :meth:`take_admissions` can
        hand it (and its pages) to a queued request. Idempotent: a
        slot already retired (e.g. by a shutdown abort racing a wedged
        worker's late ``_finish``) is left alone, so an index can
        never enter the free list twice."""
        with self.cv:
            if self.slots[slot.idx] is not slot:
                return
            self.slots[slot.idx] = None
            self._free.append(slot.idx)
            self._free.sort()
            if self.page_pool is not None and slot.pages:
                self.page_pool.free(slot.pages)
                slot.pages = []
            if slot.group is not None:
                slot.group.live -= 1
                if slot.group.live == 0:
                    self._beams_active -= 1
            self.cv.notify_all()

    def active(self) -> List[Slot]:
        with self.cv:
            return [s for s in self.slots if s is not None]

    def active_beams(self) -> List[BeamGroup]:
        """Distinct live beam groups, ordered by their first slot."""
        with self.cv:
            seen: List[BeamGroup] = []
            for s in self.slots:
                if s is not None and s.group is not None \
                        and s.group not in seen:
                    seen.append(s.group)
            return seen

    def drain(self, reason: str, code: int = 503,
              retry_after: Optional[float] = 5.0) -> int:
        """Fail every queued ticket (shutdown / drain-by-handoff);
        returns the number of FIRST-terminal settles — a ticket some
        other sweep already answered is popped but never re-counted."""
        with self.cv:
            pending = list(self._queue)
            self._queue.clear()
        settled = 0
        for _req, ticket in pending:
            if ticket.fail(reason, code=code, retry_after=retry_after):
                settled += 1
        return settled
