"""Iteration-level scheduler for the continuous-batching engine.

Pure host-side bookkeeping (no jax): a bounded request queue, the
``max_slots`` slot table, prefill-bucket selection and deadline
enforcement. The engine calls :meth:`SlotScheduler.take_admissions` at
every step boundary — queued requests move into free slots the moment
one opens, so the chip never idles while the queue is non-empty, and a
ticket older than its deadline is answered 503 + Retry-After instead
of silently sitting in the queue.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..telemetry.counters import inc


class Ticket:
    """One request's rendezvous between an HTTP handler thread and a
    serving worker (the generation twin of ``restful_api._Ticket``).
    The worker fills ``result`` (or ``error`` + ``code``) and sets
    ``event``; ``retry_after`` asks the handler to attach a
    ``Retry-After`` header (503 shed/expiry answers); ``deadline`` is
    the absolute wall time after which the request must no longer be
    served from the queue."""

    __slots__ = ("event", "result", "error", "code", "retry_after",
                 "deadline", "enqueued")

    def __init__(self, deadline: Optional[float] = None) -> None:
        self.event = threading.Event()
        self.result = None
        self.error: Optional[str] = None
        self.code: int = 500
        self.retry_after: Optional[float] = None
        self.deadline = deadline
        self.enqueued = time.time()

    def fail(self, error: str, code: int = 500,
             retry_after: Optional[float] = None) -> None:
        self.error = error
        self.code = code
        self.retry_after = retry_after
        self.event.set()

    def succeed(self, result) -> None:
        self.result = result
        self.event.set()


def split_expired(pairs: List[Tuple[Dict, Ticket]],
                  now: Optional[float] = None
                  ) -> Tuple[List[Tuple[Dict, Ticket]], List[Ticket]]:
    """Partition ``(req, ticket)`` pairs into (still live, expired
    tickets) by deadline — the check every dequeue point applies."""
    now = time.time() if now is None else now
    live, expired = [], []
    for req, ticket in pairs:
        if ticket.deadline is not None and now > ticket.deadline:
            expired.append(ticket)
        else:
            live.append((req, ticket))
    return live, expired


def shed_expired(tickets: List[Ticket]) -> None:
    """THE one deadline answer both decode planes give: 503 +
    Retry-After, counted — a ticket never rots in a queue past its
    useful life."""
    for ticket in tickets:
        inc("veles_serving_expired_total")
        inc("veles_shed_requests_total")
        ticket.fail("request expired in serving queue", code=503,
                    retry_after=1.0)


class Slot:
    """Host state of one occupied KV-cache row."""

    __slots__ = ("idx", "req", "ticket", "t_p", "bucket", "tokens",
                 "n_new", "eos_id", "temperature")

    def __init__(self, idx: int, req: Dict, ticket: Ticket,
                 bucket: int) -> None:
        self.idx = idx
        self.req = req
        self.ticket = ticket
        self.t_p = len(req["prompt"])
        self.bucket = bucket
        self.tokens: List[int] = []
        self.n_new = int(req["n_new"])
        self.eos_id = req.get("eos_id")
        self.temperature = float(req.get("temperature", 0.0))

    def record(self, token: int) -> bool:
        """Append one emitted token; True when the row is finished
        (its own ``n_new`` reached, or ``eos_id`` emitted — the moment
        continuous batching frees the slot for the next request,
        instead of riding out the longest co-tenant)."""
        self.tokens.append(int(token))
        if self.eos_id is not None and int(token) == self.eos_id:
            return True
        return len(self.tokens) >= self.n_new


class SlotScheduler:
    """Bounded queue + slot table. All methods are thread-safe; the
    engine's worker waits on :attr:`cv` and the HTTP threads notify it
    on :meth:`push`."""

    def __init__(self, max_slots: int, buckets: Tuple[int, ...],
                 max_context: int) -> None:
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.max_slots = int(max_slots)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.max_context = int(max_context)
        if self.buckets[-1] > self.max_context:
            raise ValueError(
                "largest prefill bucket %d exceeds max_context %d"
                % (self.buckets[-1], self.max_context))
        self.cv = threading.Condition()
        self._queue: deque = deque()
        self._free: List[int] = list(range(self.max_slots))
        self.slots: List[Optional[Slot]] = [None] * self.max_slots

    # -- admission geometry --------------------------------------------------
    def bucket_for(self, t_p: int) -> Optional[int]:
        """Smallest prefill bucket holding a ``t_p``-token prompt (the
        jit cache stays bounded by len(buckets) prefill programs plus
        the one decode step, not by distinct prompt lengths)."""
        for b in self.buckets:
            if t_p <= b:
                return b
        return None

    def reject_reason(self, t_p: int, n_new: int) -> Optional[str]:
        """None when the request fits the slot pool; otherwise why not
        (the caller falls back to the window-coalescing path, which
        compiles per exact shape and has no context ceiling)."""
        if self.bucket_for(t_p) is None:
            return ("prompt length %d exceeds the largest serving "
                    "bucket %d" % (t_p, self.buckets[-1]))
        if t_p + n_new > self.max_context:
            return ("prompt %d + n_new %d exceeds max_context %d"
                    % (t_p, n_new, self.max_context))
        return None

    # -- queue ----------------------------------------------------------------
    def push(self, req: Dict, ticket: Ticket,
             max_queue: Optional[int] = None) -> bool:
        """Enqueue; False when the bound is hit (caller sheds 503)."""
        with self.cv:
            if max_queue is not None and len(self._queue) >= max_queue:
                return False
            self._queue.append((req, ticket))
            self.cv.notify_all()
        return True

    def queue_depth(self) -> int:
        with self.cv:
            return len(self._queue)

    def busy_count(self) -> int:
        with self.cv:
            return self.max_slots - len(self._free)

    def expire_queued(self, now: Optional[float] = None) -> List[Ticket]:
        """Remove every expired ticket from the queue (any position) —
        the failure-path sweep: when ticks cannot run, deadlines must
        still be honored instead of callers hanging to their full
        timeout."""
        with self.cv:
            live, expired = split_expired(list(self._queue), now)
            self._queue = deque(live)
        return expired

    # -- step-boundary transitions -------------------------------------------
    def take_admissions(self, now: Optional[float] = None
                        ) -> Tuple[List[Slot], List[Ticket]]:
        """Move queued requests into free slots (FIFO), dropping
        expired tickets. Returns (newly filled slots — the engine
        prefills each, expired tickets — the engine answers 503)."""
        now = time.time() if now is None else now
        admissions: List[Slot] = []
        expired: List[Ticket] = []
        with self.cv:
            while self._queue and self._free:
                req, ticket = self._queue.popleft()
                if ticket.deadline is not None and now > ticket.deadline:
                    expired.append(ticket)
                    continue
                idx = self._free.pop(0)
                slot = Slot(idx, req, ticket,
                            self.bucket_for(len(req["prompt"])))
                self.slots[idx] = slot
                admissions.append(slot)
            # even with no free slot, purge expired tickets from ANY
            # queue position — a dead ticket behind a live head must
            # not rot to its handler's silent 504 while the pool is
            # full
            live, exp = split_expired(list(self._queue), now)
            self._queue = deque(live)
            expired.extend(exp)
        return admissions, expired

    def retire(self, slot: Slot) -> None:
        """Free the row — the very next :meth:`take_admissions` can
        hand it to a queued request. Idempotent: a slot already retired
        (e.g. by a shutdown abort racing a wedged worker's late
        ``_finish``) is left alone, so an index can never enter the
        free list twice."""
        with self.cv:
            if self.slots[slot.idx] is not slot:
                return
            self.slots[slot.idx] = None
            self._free.append(slot.idx)
            self._free.sort()
            self.cv.notify_all()

    def active(self) -> List[Slot]:
        with self.cv:
            return [s for s in self.slots if s is not None]

    def drain(self, reason: str, code: int = 503,
              retry_after: Optional[float] = 5.0) -> int:
        """Fail every queued ticket (shutdown); returns the count."""
        with self.cv:
            pending = list(self._queue)
            self._queue.clear()
        for _req, ticket in pending:
            ticket.fail(reason, code=code, retry_after=retry_after)
        return len(pending)
