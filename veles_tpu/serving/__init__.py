"""Continuous-batching serving plane.

The window-coalescing worker in ``restful_api.GenerationAPI`` only
batches requests that arrive within 20 ms of each other AND share an
exact shape key — stochastic decodes never batch, every distinct
prompt length compiles a fresh program, and a batch runs to its
longest member's ``n_new`` before anyone is answered. This package
replaces that with iteration-level scheduling over a persistent slot
pool (the shape-stable cached-decode formulation of PAPERS.md's
"Compiler-First State Space Duality and Portable O(1) Autoregressive
Caching for Inference"):

- :mod:`engine` — :class:`~veles_tpu.serving.engine.ContinuousEngine`:
  ONE fixed-shape jitted decode step over a ``max_slots``-row KV-cache
  pool (``nn/sampling.py``'s ``_block_prefill``/``_block_step`` cache
  layout, padded to ``max_context``), prefill padded to a small set of
  length buckets so the jit cache is bounded by ``len(buckets) + 1``
  programs — not by distinct prompt lengths;
- :mod:`scheduler` — :class:`~veles_tpu.serving.scheduler.SlotScheduler`:
  admits queued requests into free slots at step boundaries, retires a
  row the moment it emits ``eos_id`` or reaches its own ``n_new``, and
  answers tickets older than their deadline with 503 + Retry-After
  instead of letting them rot in the queue.

Per-slot PRNG streams derive each row's noise purely from
``(seed, request)`` — a request's tokens are independent of which
strangers share the batch, so ``mode=sample`` batches too (the same
id-exactness bar the greedy CI gate sets).

Operator guide: docs/services.md "Continuous batching".
"""

from __future__ import annotations

import threading
from typing import Dict

from .scheduler import (SlotScheduler, Ticket,        # noqa: F401
                        new_request_id,
                        request_tracing_enabled)
from .engine import (ContinuousEngine,                # noqa: F401
                     advanced_prng_key, fold_resume)
from .pages import PagePool, PrefixCache, StateCache  # noqa: F401
from .recurrent import (RecurrentEngine,               # noqa: F401
                        generate_recurrent,
                        split_recurrent_stack)
from .journal import RequestJournal                   # noqa: F401
from .router import (CircuitBreaker, FleetRouter,     # noqa: F401
                     ROUTER_COUNTERS, Replica, ReplicaSupervisor)
from .overload import (AIMDController,                # noqa: F401
                       BrownoutLadder, OverloadGovernor,
                       QOS_PRIORITIES, RetryTokenBucket,
                       dynamic_retry_after, governor_from_config,
                       request_priority, retry_after_hint)

#: every counter the lossless request plane increments (durable
#: journal + token-level failover resume + drain-by-handoff) —
#: registered with HELP strings in telemetry/counters.py DESCRIPTIONS
#: and asserted zero in non-fleet runs by ``python bench.py gate``'s
#: lossless section
LOSSLESS_COUNTERS = (
    "veles_journal_appends_total",
    "veles_journal_replayed_total",
    "veles_journal_salvaged_total",
    "veles_journal_compactions_total",
    "veles_resume_attempts_total",
    "veles_resume_tokens_total",
    "veles_handoff_requests_total",
)

#: every counter the prefix-sharing request plane increments (radix
#: prefix cache + copy-on-write + LRU eviction over the page pool) —
#: registered with HELP strings in telemetry/counters.py DESCRIPTIONS
#: and asserted zero in non-serving runs by ``python bench.py gate``'s
#: prefix section
PREFIX_COUNTERS = (
    "veles_prefix_hits_total",
    "veles_prefix_misses_total",
    "veles_prefix_shared_pages_total",
    "veles_prefix_cow_copies_total",
    "veles_prefix_evictions_total",
)

#: every counter the serving plane increments — registered with HELP
#: strings in telemetry/counters.py DESCRIPTIONS and asserted zero in
#: non-serving runs by ``python bench.py gate``'s serving section
SERVING_COUNTERS = (
    "veles_serving_admitted_total",
    "veles_serving_retired_total",
    "veles_serving_prefill_dispatches_total",
    "veles_serving_decode_dispatches_total",
    "veles_serving_tokens_total",
    "veles_serving_queue_wait_seconds_total",
    "veles_serving_expired_total",
    "veles_serving_compile_seconds_total",
    "veles_serving_pages_alloc_total",
    "veles_serving_pages_free_total",
    "veles_serving_pages_exhausted_total",
    "veles_serving_spec_rounds_total",
    "veles_serving_beam_steps_total",
)

#: every counter the O(1)-state serving lane increments (recurrent
#: slot pool + state-checkpoint prefix cache, serving/recurrent.py) —
#: registered with HELP strings in telemetry/counters.py DESCRIPTIONS
#: and asserted zero in non-recurrent runs by ``python bench.py
#: gate``'s o1state section
O1_COUNTERS = (
    "veles_o1_state_checkpoints_total",
    "veles_o1_state_restores_total",
    "veles_o1_state_restored_tokens_total",
    "veles_o1_state_rescans_total",
    "veles_o1_state_evictions_total",
)

#: every counter the tensor-parallel serving plane increments
#: (shard_mapped decode/prefill/pagecopy over the ("model",) mesh
#: slice, engine.py ``tp=`` knob) — registered with HELP strings in
#: telemetry/counters.py DESCRIPTIONS and asserted zero in tp=1 runs
#: by ``python bench.py gate``'s tp section
TP_COUNTERS = (
    "veles_tp_engines_total",
    "veles_tp_dispatches_total",
)

#: every counter the overload-hardened request plane increments (QoS
#: preempt-and-resume + AIMD admission + brownout ladder + retry
#: storm control, serving/overload.py) — registered with HELP strings
#: in telemetry/counters.py DESCRIPTIONS and asserted zero in QoS-off
#: runs by ``python bench.py gate``'s overload section
QOS_COUNTERS = (
    "veles_qos_preemptions_total",
    "veles_qos_preempted_tokens_total",
    "veles_qos_batch_deferrals_total",
    "veles_qos_throttled_total",
    "veles_qos_brownout_transitions_total",
    "veles_qos_degraded_requests_total",
    "veles_qos_retry_denied_total",
)

#: every latency histogram the request-plane SLO layer records
#: (serving/scheduler.py Ticket terminal accounting) — registered
#: with HELP + bucket bounds in telemetry/counters.py HISTOGRAMS and
#: asserted ZERO samples in non-serving runs by ``python bench.py
#: gate``'s serving section
SERVING_HISTOGRAMS = (
    "veles_serving_queue_wait_seconds",
    "veles_serving_ttft_seconds",
    "veles_serving_tpot_seconds",
    "veles_serving_e2e_seconds",
)

#: process-global registry of live engines (web_status /metrics renders
#: one occupancy gauge set per engine, like the side-plane lanes)
_engines: Dict[str, "ContinuousEngine"] = {}
_engines_lock = threading.Lock()


def register_engine(engine: "ContinuousEngine") -> None:
    with _engines_lock:
        _engines[engine.name] = engine


def unregister_engine(engine: "ContinuousEngine") -> None:
    with _engines_lock:
        if _engines.get(engine.name) is engine:
            del _engines[engine.name]


def engines() -> Dict[str, "ContinuousEngine"]:
    """name → live engine snapshot (web_status gauge rendering)."""
    with _engines_lock:
        return dict(_engines)


def parse_buckets(spec) -> tuple:
    """Prefill bucket lengths from config/CLI: a sequence of ints or a
    comma-separated string ("16,32,64"); sorted, deduplicated."""
    if isinstance(spec, str):
        spec = [s for s in (part.strip() for part in spec.split(","))
                if s]
    buckets = sorted({int(b) for b in spec})
    if not buckets or buckets[0] < 1:
        from ..error import VelesError
        raise VelesError("serving buckets must be positive ints, got %r"
                         % (spec,))
    return tuple(buckets)
