"""Fault-tolerant serving fleet: the replica router.

ROADMAP item 1's topology made buildable: N engine replicas (each a
``GenerationAPI`` front over its own ``ContinuousEngine``) behind ONE
HTTP router that keeps the fleet answering while individual replicas
die, drain, or saturate. The reference platform's headline capability
was surviving scale-out — ~100 nodes under a master that tolerated
slave death (manualrst_veles_distributed_training.rst:6); this module
is that story for the serving side, assembled from parts that already
exist:

- **health-gated admission** — a background probe scrapes every
  replica's ``/readyz`` and ``/metrics`` (reusing
  :mod:`~veles_tpu.telemetry.fleet` parsing) and ranks replicas by
  slot occupancy, so the router spills load away from saturated
  replicas and never routes to a not-ready (or draining) one;
- **per-replica circuit breakers** — consecutive attempt failures
  open the breaker for a backoff interval computed by
  :class:`~veles_tpu.resilience.retry.RetryPolicy`'s seeded-jitter
  curve (fleet-wide probe herds decorrelate, seeded runs reproduce);
  after the interval ONE half-open probe request is allowed through —
  success closes the breaker, failure re-opens it for longer;
- **idempotent failover** — every routed request carries a
  process-unique ``request_id`` (minted here, adopted by the
  replica's Ticket, echoed in every response body — success, shed
  and expiry alike); an attempt that dies mid-decode (replica crash,
  timeout, 5xx) is retried on another replica under a bounded retry
  budget, and a first-terminal answer latch guarantees EXACTLY-ONCE
  response accounting: a slow-then-successful first attempt can
  never double-answer — the late result is dropped and counted
  (``veles_router_duplicate_answers_total``);
- **graceful drain** — SIGTERM (wired by the ``veles-tpu route``
  CLI) and the ``POST /drain`` admin endpoint flip ``/readyz`` to
  draining, stop admission (503 + Retry-After), finish in-flight
  requests, then exit — same contract the engine API honors;
- **supervised respawn** — :class:`ReplicaSupervisor` generalizes
  the PR 9 elastic ``Supervisor`` spawn/classify/respawn plane from
  training generations to long-lived serving replicas: training
  reaps the whole generation when one host dies (survivors are
  wedged in collectives), a serving fleet respawns ONLY the hole —
  with seeded backoff — while the router routes around it (AOT
  serve-artifacts make the respawned replica's cold start cheap).

Retryability policy: connection-level failures (refused, reset,
timeout, torn response) and every HTTP 5xx fail over; 2xx–4xx are
the replica's answer and are delivered as-is (a 400 is the client's
problem on every replica — retrying it is a retry storm, not
resilience).

Chaos surface: ``router.replica_request`` fires before every proxied
attempt (raise = the attempt fails like a dead replica);
``serve.replica_death`` (fired replica-side in the GenerationAPI
request path) makes a live replica ACTUALLY tear its HTTP front down
mid-decode. CLI: ``veles-tpu route URL [URL ...]``; operator guide:
docs/services.md "Serving fleet".
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

from .._http import (HTTPService, bytes_reply, handle_alerts,
                     handle_metrics_history, handle_trace_spans,
                     json_reply, read_json_object)
from ..config import root
from ..error import VelesError
from ..logger import Logger
from ..resilience import health
from ..resilience.faults import FaultInjected, fire as fire_fault
from ..resilience.retry import RetryPolicy
from ..telemetry import fleet
from ..telemetry.counters import (METRICS_CONTENT_TYPE, inc,
                                  metrics_text)
from .journal import RequestJournal
#: RESUME_MODES: the single source (scheduler.py) of which decode
#: modes' emitted-token prefix a failover retry can resume —
#: everything else retries from scratch
from .scheduler import RESUME_MODES as _RESUMABLE_MODES
from .scheduler import (new_request_id, new_trace_id,
                        request_tracing_enabled)
from ..telemetry.spans import emit as emit_span

#: every counter the fleet router increments — registered with HELP
#: strings in telemetry/counters.py DESCRIPTIONS and asserted zero in
#: non-fleet runs by ``python bench.py gate``'s fleet section
ROUTER_COUNTERS = (
    "veles_router_requests_total",
    "veles_router_attempts_total",
    "veles_router_failovers_total",
    "veles_router_replica_errors_total",
    "veles_router_breaker_opens_total",
    "veles_router_duplicate_answers_total",
    "veles_router_respawns_total",
)


def _resume_budget(body: Dict) -> Tuple[List[int], Optional[int]]:
    """Parse a request body's client-supplied resume prefix and TOTAL
    generation budget (``n_new`` is the REMAINING budget when a
    prefix rides along), popping ``resume_tokens`` from the body —
    the retry loops recompute both per attempt so a dropped prefix
    (409) widens the retry back to a full redo, never delivers
    short. Unparsable resume/n_new disables router-side resume
    handling entirely (empty prefix, None budget): the body forwards
    as-is and the replica answers the 400. SINGLE SOURCE for
    :meth:`FleetRouter.route` and :meth:`FleetRouter.route_stream` —
    this arithmetic was review-hardened once and two copies must not
    drift."""
    try:
        prefix = [int(t) for t in (body.get("resume_tokens") or ())]
        total_new = int(body.get("n_new", 16)) + len(prefix)
    except (TypeError, ValueError):
        return [], None
    body.pop("resume_tokens", None)
    return prefix, total_new


def normalize_endpoint(url: str) -> str:
    """Roster entry → replica base URL: bare ``host:port`` gets
    ``http://``, trailing slashes and a trailing ``/metrics`` (the
    scrape-roster spelling) are dropped — so the router and
    ``veles-tpu metrics aggregate`` accept the same endpoint list."""
    url = str(url).strip()
    if "://" not in url:
        url = "http://" + url
    url = url.rstrip("/")
    if url.endswith("/metrics"):
        url = url[:-len("/metrics")]
    return url


def router_config() -> Dict[str, Any]:
    """The router knob block ``root.common.router.*`` (CLI flags of
    ``veles-tpu route`` override per invocation)."""
    node = root.common.router
    return {
        "probe_interval": float(node.get("probe_interval", 1.0) or 1.0),
        "probe_timeout": float(node.get("probe_timeout", 2.0) or 2.0),
        "failure_threshold": int(node.get("failure_threshold", 3) or 3),
        "retry_budget": int(node.get("retry_budget", 2)),
        "attempt_timeout": float(node.get("attempt_timeout", 10.0)
                                 or 10.0),
        "request_timeout": float(node.get("request_timeout", 120.0)
                                 or 120.0),
        # no falsy-zero rewrite here: drain_grace = 0 legitimately
        # means "abort stragglers immediately"
        "drain_grace": float(node.get("drain_grace", 30.0)),
        # durable request journal (serving/journal.py): empty = the
        # PR 12 memory-only admission plane
        "journal": str(node.get("journal", "") or ""),
    }


class CircuitBreaker:
    """Per-replica failure gate: ``failure_threshold`` consecutive
    failures open it; while open, :meth:`allow` refuses for a backoff
    interval riding :meth:`RetryPolicy.backoff`'s seeded-jitter curve
    (the interval grows with every re-open); after the interval ONE
    half-open probe is admitted — success closes the breaker and
    resets the curve, failure re-opens it for longer. Thread-safe;
    ``clock`` is injectable for deterministic tests."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failure_threshold: int = 3,
                 backoff: Optional[RetryPolicy] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.failure_threshold = max(1, int(failure_threshold))
        self.backoff = backoff if backoff is not None else RetryPolicy(
            base_delay=0.5, max_delay=30.0, name="breaker")
        self._clock = clock
        self._lock = threading.Lock()
        self.state = self.CLOSED
        self.failures = 0          # consecutive, resets on success
        self.trips = 0             # times opened — drives the curve
        self.open_until = 0.0
        self._probing = False      # half-open: one probe in flight

    def allow(self) -> bool:
        """May a request be routed here right now? Claims the single
        half-open probe slot when it grants one — the caller MUST
        follow through with an attempt (and settle it), or the slot
        stays claimed until the next open interval."""
        with self._lock:
            if self.state == self.CLOSED:
                return True
            if self.state == self.OPEN:
                if self._clock() < self.open_until:
                    return False
                self.state = self.HALF_OPEN
                self._probing = False
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self.state = self.CLOSED
            self.failures = 0
            self.trips = 0
            self._probing = False

    def record_failure(self) -> bool:
        """Account one failed attempt; True when THIS failure opened
        (or re-opened) the breaker — the caller counts the
        transition."""
        with self._lock:
            self.failures += 1
            if self.state == self.HALF_OPEN or (
                    self.state == self.CLOSED
                    and self.failures >= self.failure_threshold):
                self.state = self.OPEN
                self.trips += 1
                # the attempt index is capped so the delay saturates
                # at max_delay instead of 2**trips overflowing
                self.open_until = self._clock() + self.backoff.backoff(
                    min(self.trips, 16))
                self._probing = False
                return True
            if self.state == self.OPEN:
                self._probing = False
            return False


class Replica:
    """One roster entry: the endpoint, its breaker, and the latest
    probe snapshot (readiness + occupancy) the admission ranking
    reads. Probe fields are written by the router's probe thread and
    read by handler threads — single-attribute writes, no torn
    state worth a lock."""

    def __init__(self, url: str,
                 breaker: Optional[CircuitBreaker] = None) -> None:
        self.url = normalize_endpoint(url)
        self.breaker = breaker if breaker is not None \
            else CircuitBreaker()
        self.up = False
        self.ready = False
        self.draining = False
        self.slots = 0
        self.slots_busy = 0
        self.queue_depth = 0
        #: mesh-slice width behind this endpoint (1 = solo chip): a
        #: tensor-parallel replica publishes {"tp": {"devices": N}} on
        #: /readyz — the roster counts it as ONE replica spanning N
        #: chips, never as N replicas
        self.tp_devices = 1
        self.probe_error: Optional[str] = None
        self.last_probe = 0.0

    def occupancy(self) -> float:
        """Busy fraction of the replica's slot pool (0 when unknown)
        — the spill ranking's primary key."""
        return self.slots_busy / self.slots if self.slots else 0.0

    def snapshot(self) -> Dict[str, Any]:
        return {
            "url": self.url, "up": self.up, "ready": self.ready,
            "draining": self.draining, "slots": self.slots,
            "slots_busy": self.slots_busy,
            "queue_depth": self.queue_depth,
            "tp_devices": self.tp_devices,
            "occupancy": round(self.occupancy(), 4),
            "breaker": self.breaker.state,
            "probe_error": self.probe_error,
        }


class _Answer:
    """First-terminal answer latch for one routed request — the
    router-side twin of ``Ticket``'s exactly-once transition: however
    many attempts eventually complete, exactly one :meth:`offer`
    wins; every loser is reported False (the caller counts it as a
    dropped duplicate). The embedded condition doubles as the
    routing loop's wakeup for attempt settles."""

    def __init__(self) -> None:
        self.cv = threading.Condition()
        self.done = False
        self.status: Optional[int] = None
        self.body: Optional[Dict] = None
        self.retry_after: Optional[str] = None
        self.replica: Optional[Replica] = None
        self.request_id: Optional[str] = None
        self.trace_id: Optional[str] = None
        #: replica attempts the routing loop dispatched for this
        #: request — stamped into the journal's terminal record
        self.attempts: int = 0
        #: why routing gave up, when ``done`` stays False
        self.reason: Optional[str] = None

    def offer(self, status: int, body: Dict,
              retry_after: Optional[str] = None,
              replica: Optional[Replica] = None) -> bool:
        with self.cv:
            first = not self.done
            if first:
                self.done = True
                self.status = int(status)
                self.body = body
                self.retry_after = retry_after
                self.replica = replica
            self.cv.notify_all()
            return first


class _Attempt:
    """One proxied attempt's settle state. Breaker/counter accounting
    happens exactly once per attempt, on the FIRST settle — whether
    that is the attempt thread reporting its outcome or the routing
    loop declaring an attempt timeout and moving on (the thread may
    still land a late answer through the latch afterwards)."""

    def __init__(self, replica: Replica, answered: _Answer) -> None:
        self.replica = replica
        self._answered = answered
        self._lock = threading.Lock()
        self.settled = False
        self.failed = False
        self.reason: Optional[str] = None
        #: a failed attempt's {tokens, tokens_done} resume record (a
        #: 5xx dying gasp / drain handoff) — the routing loop folds it
        #: into the next attempt's resume_tokens
        self.resume_payload: Optional[Dict] = None
        #: the replica answered 409 to a resume attempt: drop the
        #: accumulated prefix and retry from scratch
        self.drop_resume = False

    def _settle(self, failed: bool, reason: Optional[str],
                benign: bool = False) -> bool:
        with self._lock:
            if self.settled:
                return False
            self.settled = True
            self.failed = failed
            self.reason = reason
        if failed and not benign:
            inc("veles_router_replica_errors_total")
            if self.replica.breaker.record_failure():
                inc("veles_router_breaker_opens_total")
        elif not failed:
            self.replica.breaker.record_success()
        with self._answered.cv:
            self._answered.cv.notify_all()
        return True

    def fail(self, reason: str) -> bool:
        return self._settle(True, reason)

    def fail_benign(self, reason: str) -> bool:
        """Settle as failed WITHOUT breaker/error accounting — for a
        healthy answer that merely refuses this attempt's shape (a
        409 resume rejection is the replica being honest, not the
        replica being dead)."""
        return self._settle(True, reason, benign=True)

    def succeed(self) -> bool:
        return self._settle(False, None)


class FleetRouter(Logger):
    """HTTP front fanning a GenerationAPI-compatible surface out over
    N replica endpoints (module doc has the full story). Surfaces on
    the router port:

    - ``POST <path>`` (default ``/generate``) — route with failover;
    - ``GET /healthz`` / ``/readyz`` — the router's own health plane
      (``/readyz`` flips to draining during a drain);
    - ``GET /metrics`` — the router's counters + fleet gauges;
    - ``GET /fleet/metrics`` — live fleet-wide aggregation over the
      roster (telemetry/fleet.py merge, quantiles recomputed);
    - ``GET /roster`` — the replica roster as JSON (readiness,
      occupancy, breaker state); saved to a file it feeds
      ``veles-tpu metrics aggregate --endpoints-file`` directly;
    - ``POST /drain`` — graceful drain (also wired to SIGTERM by the
      CLI).
    """

    def __init__(self, endpoints: Sequence[str], port: int = 0,
                 path: str = "/generate",
                 probe_interval: Optional[float] = None,
                 probe_timeout: Optional[float] = None,
                 failure_threshold: Optional[int] = None,
                 retry_budget: Optional[int] = None,
                 attempt_timeout: Optional[float] = None,
                 request_timeout: Optional[float] = None,
                 journal_dir: Optional[str] = None,
                 journal_fsync: bool = True,
                 name: str = "router") -> None:
        super().__init__()
        cfg = router_config()
        urls = [normalize_endpoint(u) for u in endpoints]
        if not urls:
            raise VelesError("a fleet router needs at least one "
                             "replica endpoint")
        if len(set(urls)) != len(urls):
            raise VelesError("duplicate replica endpoints: %s" % urls)
        self.name = name
        self.path = path
        self.port = int(port)
        self.probe_interval = float(
            cfg["probe_interval"] if probe_interval is None
            else probe_interval)
        self.probe_timeout = float(
            cfg["probe_timeout"] if probe_timeout is None
            else probe_timeout)
        self.retry_budget = max(0, int(
            cfg["retry_budget"] if retry_budget is None
            else retry_budget))
        self.attempt_timeout = float(
            cfg["attempt_timeout"] if attempt_timeout is None
            else attempt_timeout)
        self.request_timeout = float(
            cfg["request_timeout"] if request_timeout is None
            else request_timeout)
        threshold = int(cfg["failure_threshold"]
                        if failure_threshold is None
                        else failure_threshold)
        self.replicas = [
            Replica(u, CircuitBreaker(failure_threshold=threshold))
            for u in urls]
        self._service: Optional[HTTPService] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._replay_thread: Optional[threading.Thread] = None
        self._closing = False
        self._draining = False
        self._inflight = 0
        self._cv = threading.Condition()
        self._wake = threading.Event()
        self.requests_routed = 0
        # durable request journal (serving/journal.py): every
        # accepted request is on disk before its first dispatch and
        # marked terminal on answer — a router SIGKILL loses zero
        # accepted requests (start() replays the unanswered tail)
        jdir = (cfg["journal"] if journal_dir is None
                else (journal_dir or ""))
        self.journal: Optional[RequestJournal] = (
            RequestJournal(jdir, fsync=journal_fsync,
                           name=name + ".journal") if jdir else None)
        #: admits minus terminals since start (plus the replay
        #: backlog) — the journal-pending gauge without re-reading
        #: the segments on every /metrics scrape
        self._journal_outstanding = 0
        # overload governor (serving/overload.py, docs/services.md
        # "Overload & QoS"): None unless root.common.router.qos —
        # the feature-off router runs the exact pre-QoS path
        from .overload import governor_from_config
        self.governor = governor_from_config()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._service is not None:
            return self
        self._closing = False
        self._draining = False
        self.probe_all()               # admission state before traffic
        self._wake.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, daemon=True,
            name=self.name + ".probe")
        self._probe_thread.start()
        self._service = HTTPService(self._make_handler(), self.port,
                                    self.name + ".http")
        self.port = self._service.port
        self._service.start_serving()
        # watchtower sampler (telemetry/timeseries.py): the router's
        # gauges() carries the fleet-level sums the probe loop keeps
        # fresh, so fleet series ride the same ring as local ones.
        # No-op unless root.common.telemetry.watch.enabled.
        from ..telemetry import timeseries
        timeseries.add_gauge_provider("router.%s" % self.name,
                                      self.gauges)
        timeseries.maybe_start()
        health.mark_ready("router.%s" % self.name)
        health.heartbeats.beat("router.%s" % self.name)
        self.info("%s: routing %s on http://127.0.0.1:%d%s "
                  "(retry budget %d, breaker threshold %d%s)",
                  self.name,
                  [r.url for r in self.replicas], self.port, self.path,
                  self.retry_budget,
                  self.replicas[0].breaker.failure_threshold,
                  ", journal %s" % self.journal.directory
                  if self.journal else "")
        if self.journal is not None:
            self._replay_thread = threading.Thread(
                target=self._replay_journal, daemon=True,
                name=self.name + ".replay")
            self._replay_thread.start()
        return self

    def stop(self) -> None:
        from ..telemetry import timeseries
        timeseries.remove_gauge_provider("router.%s" % self.name)
        self._closing = True
        self._wake.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        if self._replay_thread is not None:
            self._replay_thread.join(timeout=10)
            self._replay_thread = None
        if self._service is not None:
            self._service.stop_serving()
            self._service = None
        if self.journal is not None:
            self.journal.close()
        health.forget("router.%s" % self.name)

    # -- journal replay ------------------------------------------------------
    def _replay_journal(self) -> None:
        """Re-dispatch every journaled-but-unanswered request from
        before the restart: ordered by ``enqueued_at``, idempotent by
        ``request_id`` (the journal's terminal records dedupe
        however many crash-loops re-ran), expired entries shed with
        a terminal 503 record carrying the id. Torn records were
        already quarantined (counted) by the journal's salvage pass —
        a damaged journal degrades, it never refuses to start."""
        try:
            pending = self.journal.pending()
        except Exception:       # noqa: BLE001 — degrade, don't die
            self.exception("%s: journal replay scan failed; serving "
                           "new traffic only", self.name)
            return
        if not pending:
            return
        with self._cv:
            self._journal_outstanding += len(pending)
        self.info("%s: replaying %d journaled request(s) from before "
                  "the restart", self.name, len(pending))
        t_replay = time.time()
        replayed = shed = 0
        try:
            replayed, shed = self._replay_pending(pending)
        finally:
            if request_tracing_enabled():
                # the journal-tail replay as one timeline event: a
                # restarted router's first seconds explain themselves
                emit_span("route.replay", t_replay,
                          time.time() - t_replay,
                          pending=len(pending), replayed=replayed,
                          shed=shed)

    def _replay_pending(self, pending) -> Tuple[int, int]:
        replayed = shed = 0
        for rec in pending:
            if self._closing or self._draining:
                # still journaled — the next start retries
                return replayed, shed
            rid = rec["request_id"]
            tid = rec.get("trace_id")
            body = rec.get("body")
            enqueued = float(rec.get("enqueued_at", 0.0) or 0.0)
            if not isinstance(body, dict):
                self.journal.done(rid, 400, "unreplayable",
                                  trace_id=tid)
                with self._cv:
                    self._journal_outstanding -= 1
                continue
            if time.time() > enqueued + self.request_timeout:
                # past its useful life: the shed a live router would
                # have answered, recorded with the id
                inc("veles_shed_requests_total")
                self.journal.done(rid, 503, "expired", trace_id=tid)
                shed += 1
                self.warning("%s: journaled request %s expired before "
                             "replay (enqueued %.0fs ago)", self.name,
                             rid, time.time() - enqueued)
                with self._cv:
                    self._journal_outstanding -= 1
                continue
            inc("veles_journal_replayed_total")
            try:
                # the replayed body resumes under its ORIGINAL
                # trace_id (the admit record's) — one trace tells the
                # whole story across the router restart. A journaled
                # stream=true request replays BUFFERED: its client is
                # gone, so replay only completes the work and records
                # the terminal — there is nobody to stream to.
                body = dict(body, request_id=rid)
                body.pop("stream", None)
                answered = self.route(body)
                status = answered.status if answered.done else 503
                outcome = ("replayed" if answered.done
                           else "unanswered: %s"
                           % (answered.reason or ""))
                self.journal.done(rid, int(status), outcome,
                                  trace_id=tid,
                                  attempts=answered.attempts)
                replayed += 1
            except Exception:   # noqa: BLE001 — replay must survive
                # one poisonous entry must not abandon the rest of
                # the backlog; it stays pending for the next start
                self.exception("%s: replay of %s failed; continuing "
                               "with the remaining backlog",
                               self.name, rid)
                continue
            with self._cv:
                self._journal_outstanding -= 1
        return replayed, shed

    # -- graceful drain ------------------------------------------------------
    def begin_drain(self) -> bool:
        """Stop admission and flip the router's ``/readyz`` to
        draining; in-flight requests keep being served. True when
        this call started the drain."""
        with self._cv:
            if self._draining:
                return False
            self._draining = True
        health.mark_draining("router.%s" % self.name)
        self.info("%s: draining — admission stopped, %d in flight",
                  self.name, self._inflight)
        return True

    def drain(self, grace: Optional[float] = None) -> bool:
        """SIGTERM-grade shutdown: :meth:`begin_drain`, wait up to
        ``grace`` seconds (default ``root.common.router.drain_grace``
        = 30) for in-flight requests, then :meth:`stop`. True when
        the drain emptied in time."""
        self.begin_drain()
        if grace is None:
            grace = router_config()["drain_grace"]
        deadline = time.time() + grace
        with self._cv:
            while self._inflight and time.time() < deadline:
                self._cv.wait(timeout=min(
                    0.2, max(0.01, deadline - time.time())))
            drained = self._inflight == 0
        self.info("%s: drain %s", self.name,
                  "complete" if drained else "grace expired")
        self.stop()
        return drained

    @property
    def draining(self) -> bool:
        return self._draining

    # -- health-gated admission ----------------------------------------------
    def _probe_loop(self) -> None:
        while not self._closing:
            if self._wake.wait(timeout=self.probe_interval):
                return
            self.probe_all()

    def probe_all(self) -> None:
        """One probe sweep: every replica's ``/readyz`` (admission
        gate) + ``/metrics`` (occupancy ranking, parsed by the fleet
        module), probed CONCURRENTLY so the sweep is bounded by the
        slowest single replica, not the sum — a hung replica must
        not stretch everyone else's staleness past
        ``probe_interval``. Also the router's own liveness beat."""
        threads = [threading.Thread(target=self._probe, args=(r,),
                                    daemon=True,
                                    name=self.name + ".probe1")
                   for r in self.replicas]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        health.heartbeats.beat("router.%s" % self.name)

    def _probe(self, replica: Replica) -> None:
        replica.last_probe = time.time()
        try:
            req = urllib.request.Request(replica.url + "/readyz")
            with urllib.request.urlopen(
                    req, timeout=self.probe_timeout) as r:
                code, payload = r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            # 503 IS a readiness answer (not ready / draining)
            code = e.code
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                payload = {}
        except Exception as e:  # noqa: BLE001 — a down replica is data
            replica.up = False
            replica.ready = False
            replica.draining = False
            replica.probe_error = "%s: %s" % (type(e).__name__, e)
            return
        replica.up = True
        replica.ready = code == 200
        replica.draining = payload.get("status") == "draining"
        replica.probe_error = None
        # replica = mesh slice: a TP engine rides its slice shape on
        # the /readyz payload (resilience/health.py set_info) — the
        # probe the router already makes learns the chip span for free
        try:
            tp_info = payload.get("tp")
            replica.tp_devices = max(1, int(
                (tp_info or {}).get("devices", 1)))
        except (TypeError, ValueError):
            replica.tp_devices = 1
        body, _err = fleet.scrape(replica.url,
                                  timeout=self.probe_timeout)
        if body is not None:
            gauges = fleet.parse_metrics_text(body)["gauges"]
            replica.slots = int(gauges.get("veles_serving_slots", 0))
            replica.slots_busy = int(
                gauges.get("veles_serving_slots_busy", 0))
            replica.queue_depth = int(
                gauges.get("veles_serving_queue_depth",
                           gauges.get("veles_generate_queue_depth",
                                      0)))
            if replica.tp_devices == 1:
                # older front without the readyz info key: the
                # veles_serving_tp gauge carries the same fact
                replica.tp_devices = max(1, int(
                    gauges.get("veles_serving_tp", 1)))

    def pick(self, exclude: Sequence[Replica] = ()) -> Optional[Replica]:
        """Least-occupied READY replica whose breaker admits a
        request — never a not-ready/draining one, never one already
        tried for this request. Breaker side effects make the order
        matter: candidates are ranked first, then asked, and the
        first to grant wins (a granted half-open probe slot is always
        used)."""
        ranked = sorted(
            (r for r in self.replicas
             if r not in exclude and r.ready),
            key=lambda r: (r.occupancy(), r.queue_depth, r.url))
        for replica in ranked:
            if replica.breaker.allow():
                return replica
        return None

    # -- routing -------------------------------------------------------------
    def _request_budget(self, body: Dict) -> float:
        """Per-request routing budget: a sane ``deadline_ms`` CAPS
        the global request_timeout (deadline propagation's router
        leg — the replica applies the same cap to its ticket, so one
        number bounds the whole client→router→replica→sweep chain); a
        client can only tighten, never extend. Garbage values fall
        back to the global (the replica's _parse answers the 400)."""
        budget = self.request_timeout
        dl = body.get("deadline_ms")
        if dl is None or isinstance(dl, bool):
            return budget
        try:
            dl = float(dl)
        except (TypeError, ValueError):
            return budget
        if dl > 0:
            budget = min(budget, dl / 1000.0)
        return budget

    def _attempt(self, replica: Replica, data: bytes, rid: str,
                 answered: _Answer, state: _Attempt,
                 timeout: float, prefix: Sequence[int] = (),
                 base_k: int = 0) -> None:
        try:
            fire_fault("router.replica_request")
        except FaultInjected as e:
            state.fail("injected replica failure: %s" % e)
            return
        try:
            req = urllib.request.Request(
                replica.url + self.path, data=data,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout) as r:
                status = r.status
                body = json.loads(r.read() or b"{}")
                retry_after = r.headers.get("Retry-After")
        except urllib.error.HTTPError as e:
            status = e.code
            try:
                body = json.loads(e.read() or b"{}")
            except ValueError:
                body = {"error": "replica answered %d" % e.code}
            retry_after = e.headers.get("Retry-After")
        except Exception as e:      # noqa: BLE001 — the failure class
            # connection refused/reset, timeout, torn response: the
            # replica is (acting) dead — fail over (from scratch: a
            # dropped connection carries no progress)
            state.fail("%s: %s" % (type(e).__name__, e))
            return
        if status >= 500:
            # a dying gasp / drain handoff 503 carries the attempt's
            # emitted-token prefix — the routing loop folds it into
            # the NEXT attempt's resume_tokens so the failover
            # re-enters the decode at tokens_done, not token 0.
            # Validated ELEMENT-wise here: a garbage gasp from a
            # misbehaving replica must degrade to a from-scratch
            # retry, never throw inside route()/the replay thread
            resume = (body or {}).get("resume")
            if isinstance(resume, dict) \
                    and isinstance(resume.get("tokens"), list):
                try:
                    state.resume_payload = {
                        "tokens": [int(t) for t in resume["tokens"]]}
                except (TypeError, ValueError):
                    pass
            state.fail("replica %s answered %d (%s)"
                       % (replica.url, status,
                          (body or {}).get("error", "")))
            return
        if status == 409 and prefix:
            # the replica cannot honor this resume (no continuous
            # engine, geometry overflow): drop the prefix, the loop
            # retries from scratch — a 409 is an answer about the
            # RESUME, not about the replica's health, so it neither
            # advances the breaker nor burns the replica's roster
            # slot (the loop re-admits it for the scratch retry)
            state.drop_resume = True
            state.fail_benign("replica %s cannot resume (%s)"
                              % (replica.url,
                                 (body or {}).get("error", "")))
            return
        if status == 200 and (prefix or base_k) \
                and isinstance(body.get("tokens"), list):
            # stitch the resumed answer: the replica decoded only the
            # remaining budget — prepend the prefix, then drop the
            # first base_k tokens (a CLIENT-supplied resume base is
            # the client's own context: they asked for the remaining
            # n_new, not a re-delivery of what they already hold; a
            # dropped-and-redone base is sliced off the full redo the
            # same way, id-exact for seeded modes)
            stitched = [int(t) for t in prefix] + body["tokens"]
            body = dict(body, tokens=stitched[base_k:])
            if len(prefix) > base_k:
                body["resumed_from"] = len(prefix)
        # 2xx–4xx: the replica's answer, deliver as-is (first wins).
        # Offer BEFORE settling: settle notifies the routing loop,
        # and a loop that wakes to a settled-but-unanswered attempt
        # would dispatch a spurious extra attempt
        first = answered.offer(status, body, retry_after=retry_after,
                               replica=replica)
        state.succeed()
        if not first:
            inc("veles_router_duplicate_answers_total")
            self.warning("%s: duplicate answer for %s from %s "
                         "dropped (an earlier attempt already "
                         "answered)", self.name, rid, replica.url)

    def route(self, body: Dict) -> _Answer:
        """Route one parsed request body with health-gated selection,
        breaker-aware failover and the exactly-once answer latch.
        A failed attempt whose answer carried resume progress (a
        dying gasp, a drain handoff) makes the next attempt a
        token-level RESUME: ``resume_tokens`` + the remaining
        ``n_new`` ride the retry body, and the final answer is
        stitched back to the full sequence. Returns the latch —
        ``done`` False means no replica could answer inside the
        budget (the HTTP face sheds 503).

        Tracing: the router mints a ``trace_id`` at admission (or
        adopts the caller's) and forwards it — with the 1-based
        ``attempt`` number — in every attempt body, so every
        replica-side span and flight event of this request carries
        the fleet-wide key. The routing decisions themselves become
        spans (gated by ``root.common.trace.requests``, like the
        replica lifecycle spans): ``route.request`` brackets the
        whole route, ``route.attempt`` each replica try (endpoint,
        outcome, status, ``tokens_done`` carried into a resume),
        ``route.probe`` a half-open breaker's recovery attempt, and
        ``route.backoff`` the open interval a failure scheduled —
        failover/breaker/resume decisions are timeline events, not
        just counter increments."""
        rid = body.get("request_id") or new_request_id()
        tid = body.get("trace_id") or new_trace_id()
        body = dict(body, request_id=rid, trace_id=tid)
        mode = str(body.get("mode", "greedy"))
        resumable = mode in _RESUMABLE_MODES
        trace_on = request_tracing_enabled()
        # total generation budget: a client/replayed body may itself
        # carry a resume prefix (its n_new is then the REMAINING
        # budget) — _resume_budget pops it, shared with route_stream
        prefix, total_new = _resume_budget(body)
        #: the CLIENT's own resume base: sliced off the final answer
        #: (they asked for the remaining n_new, not a re-delivery)
        base_k = len(prefix)
        inc("veles_router_requests_total")
        answered = _Answer()
        answered.request_id = rid
        answered.trace_id = tid
        t_req = time.time()
        budget = self._request_budget(body)
        deadline = t_req + budget
        tried: List[Replica] = []
        n_attempts = 0
        last_reason = "no ready replica"
        while len(tried) <= self.retry_budget:
            remaining = deadline - time.time()
            if remaining <= 0:
                last_reason = ("request budget %.0fs exhausted"
                               % budget)
                break
            if tried and self.governor is not None \
                    and not self.governor.allow_retry():
                # the router-wide retry token bucket: a storm of
                # failing attempts must not amplify into a storm of
                # failover retries — deny and answer with the last
                # attempt's error
                last_reason = ("failover retry denied by the "
                               "router retry budget (storm control)")
                break
            replica = self.pick(exclude=tried)
            if replica is None:
                break
            # a granted half-open slot IS the breaker's recovery
            # probe — this attempt doubles as it (route.probe span)
            probing = replica.breaker.state \
                == CircuitBreaker.HALF_OPEN
            trips_before = replica.breaker.trips
            if tried:
                inc("veles_router_failovers_total")
                self.info("%s: failing %s over to %s (%s)%s",
                          self.name, rid, replica.url, last_reason,
                          " resuming at token %d" % len(prefix)
                          if prefix else "")
            tried.append(replica)
            inc("veles_router_attempts_total")
            n_attempts += 1
            tokens_done = len(prefix)
            attempt_body = dict(body, attempt=n_attempts)
            if total_new is not None:
                # n_new is recomputed from the TOTAL budget every
                # attempt: a dropped prefix (409) must widen the
                # retry back to a full redo, never deliver short
                attempt_body["n_new"] = total_new - len(prefix)
                if prefix:
                    attempt_body["resume_tokens"] = list(prefix)
                    inc("veles_resume_attempts_total")
            data = json.dumps(attempt_body).encode()
            state = _Attempt(replica, answered)
            t_att = time.time()
            threading.Thread(
                target=self._attempt,
                args=(replica, data, rid, answered, state,
                      max(0.1, remaining), tuple(prefix), base_k),
                daemon=True,
                name="%s.attempt" % self.name).start()
            # wait for THIS attempt to settle, anyone to answer, or
            # the per-attempt patience to run out (the thread keeps
            # running — a late success still wins the latch first-
            # come; the loop just stops waiting for it)
            wait_until = min(deadline,
                             time.time() + self.attempt_timeout)
            with answered.cv:
                while (not answered.done and not state.settled
                        and time.time() < wait_until):
                    answered.cv.wait(timeout=min(
                        0.05, max(0.005, wait_until - time.time())))
            # declare the timeout BEFORE emitting the attempt span,
            # so the span reads the outcome the loop acted on
            if not answered.done and not state.settled:
                if state.fail("attempt timed out after %.1fs on %s"
                              % (self.attempt_timeout, replica.url)):
                    last_reason = state.reason or "attempt timeout"
            if trace_on:
                self._note_attempt(replica, state, answered, rid,
                                   tid, n_attempts, t_att,
                                   tokens_done, probing, trips_before)
            if answered.done:
                break
            if state.settled and state.failed:
                last_reason = state.reason or "replica failure"
                if state.drop_resume:
                    # the 409 replica is healthy — give its roster
                    # slot back so the from-scratch retry may land
                    # on it again
                    prefix = []
                    if replica in tried:
                        tried.remove(replica)
                elif resumable and total_new is not None \
                        and state.resume_payload is not None:
                    gained = [int(t) for t in
                              state.resume_payload["tokens"]]
                    if gained and len(prefix) + len(gained) \
                            < total_new:
                        prefix = prefix + gained
                continue
        answered.attempts = n_attempts
        if not answered.done:
            answered.reason = last_reason
        if trace_on:
            now = time.time()
            tags: Dict[str, Any] = {
                "request_id": rid, "trace_id": tid, "mode": mode,
                "attempts": n_attempts,
                "outcome": ("answered" if answered.done
                            else "unanswered")}
            if answered.done:
                tags["status"] = int(answered.status)
            else:
                tags["reason"] = last_reason
            # the ROOT span of the fleet trace: one lane-topping
            # bracket per routed request, on the router's clock
            emit_span("route.request", t_req, now - t_req, **tags)
        return answered

    # -- streaming proxy ------------------------------------------------------
    class _ClientGone(Exception):
        """The CLIENT's socket died mid-stream. Distinct from replica
        failures on purpose: a closed browser tab must neither advance
        a healthy replica's circuit breaker nor trigger failover
        re-decodes — the routing loop just stops."""

    @staticmethod
    def _sse_events(resp):
        """Parse an SSE byte stream into JSON event dicts (lines the
        replica's ``data:`` framing carries; torn/non-JSON lines are
        skipped — the stream's health is judged by its terminal
        event, not by cosmetic damage)."""
        for line in resp:
            line = line.strip()
            if not line.startswith(b"data:"):
                continue
            try:
                ev = json.loads(line[5:].strip())
            except ValueError:
                continue
            if isinstance(ev, dict):
                yield ev

    def route_stream(self, body: Dict, handler) -> Tuple[int, str, int]:
        """Proxy one ``stream=true`` request: SSE events pipe from the
        serving replica to the client AS THEY ARRIVE; an attempt that
        dies mid-stream (replica crash, 5xx gasp, torn stream) fails
        over with ``resume_tokens`` = everything already forwarded, so
        the retry RE-STREAMS ONLY THE REMAINDER — the client's wire
        sees every token exactly once and one terminal event. A 409
        resume refusal drops the prefix and retries from scratch,
        skipping tokens the client already holds. Attempts are
        SEQUENTIAL (events already on the client's wire bind the
        stream to one replica at a time — no hedging; the buffered
        path keeps its latch-raced attempts). Returns
        ``(status, outcome, attempts)`` for the journal's terminal
        record. Response headers commit lazily: a request no replica
        could even start is shed as plain JSON 503."""
        rid = body["request_id"]
        tid = body["trace_id"]
        mode = str(body.get("mode", "greedy"))
        resumable = mode in _RESUMABLE_MODES
        trace_on = request_tracing_enabled()
        body = dict(body)
        prefix, total_new = _resume_budget(body)
        base_k = len(prefix)
        inc("veles_router_requests_total")
        t_req = time.time()
        budget = self._request_budget(body)
        deadline = t_req + budget
        state = {"headers": False, "sent": 0}

        def event(payload):
            from .._http import sse_event, sse_headers
            try:
                if not state["headers"]:
                    sse_headers(handler)
                    state["headers"] = True
                sse_event(handler, payload)
            except (BrokenPipeError, ConnectionResetError,
                    OSError) as e:
                # client-write failure, NOT a replica failure
                raise FleetRouter._ClientGone(str(e)) from e

        def emit_gap(full_toks):
            """Keep the client's INCREMENTAL wire complete: forward
            any absolute positions of ``full_toks`` it has not seen
            as one token event (tokens a dying replica decoded but
            never streamed arrive via its gasp; a buffered-200
            replica delivers everything this way)."""
            gap = [int(t) for t in full_toks[base_k + state["sent"]:]]
            if gap:
                event({"tokens": gap, "i": state["sent"],
                       "request_id": rid, "trace_id": tid})
                state["sent"] += len(gap)

        def finish(status, outcome, n_attempts, tags=None):
            if trace_on:
                t: Dict[str, Any] = {
                    "request_id": rid, "trace_id": tid, "mode": mode,
                    "attempts": n_attempts, "outcome": outcome,
                    "stream": 1}
                t.update(tags or {})
                if outcome == "answered":
                    t["status"] = int(status)
                emit_span("route.request", t_req,
                          time.time() - t_req, **t)
            return int(status), outcome, n_attempts

        tried: List[Replica] = []
        n_attempts = 0
        last_reason = "no ready replica"
        while len(tried) <= self.retry_budget:
            remaining = deadline - time.time()
            if remaining <= 0:
                last_reason = ("request budget %.0fs exhausted"
                               % budget)
                break
            if tried and self.governor is not None \
                    and not self.governor.allow_retry():
                last_reason = ("failover retry denied by the "
                               "router retry budget (storm control)")
                break
            replica = self.pick(exclude=tried)
            if replica is None:
                break
            if tried:
                inc("veles_router_failovers_total")
                self.info("%s: failing stream %s over to %s (%s)%s",
                          self.name, rid, replica.url, last_reason,
                          " resuming at token %d" % len(prefix)
                          if prefix else "")
            tried.append(replica)
            inc("veles_router_attempts_total")
            n_attempts += 1
            t_att = time.time()
            attempt_body = dict(body, attempt=n_attempts, stream=True)
            if total_new is not None:
                attempt_body["n_new"] = total_new - len(prefix)
                if prefix:
                    attempt_body["resume_tokens"] = list(prefix)
                    inc("veles_resume_attempts_total")
            attempt_tokens: List[int] = []
            failed_reason = None
            drop_resume = False
            done_event = None
            delivered = None      # (status, body) for a 4xx pass-through
            try:
                fire_fault("router.replica_request")
                req = urllib.request.Request(
                    replica.url + self.path,
                    data=json.dumps(attempt_body).encode(),
                    headers={"Content-Type": "application/json"})
                # the SOCKET timeout is per blocking read: a steadily
                # streaming replica never trips it, a wedged one
                # (accepted the connection, sends nothing) fails
                # after attempt_timeout so healthy replicas still get
                # tried inside the request budget — the buffered
                # path's per-attempt patience, stream-shaped
                resp = urllib.request.urlopen(
                    req, timeout=max(0.1, min(self.attempt_timeout,
                                              remaining)))
            except FaultInjected as e:
                failed_reason = "injected replica failure: %s" % e
            except urllib.error.HTTPError as e:
                status = e.code
                try:
                    err_body = json.loads(e.read() or b"{}")
                except ValueError:
                    err_body = {"error": "replica answered %d"
                                % status}
                if status == 409 and prefix:
                    drop_resume = True
                    failed_reason = ("replica %s cannot resume (%s)"
                                     % (replica.url,
                                        err_body.get("error", "")))
                elif status >= 500:
                    gasp = (err_body or {}).get("resume")
                    if resumable and isinstance(gasp, dict) \
                            and isinstance(gasp.get("tokens"), list):
                        try:
                            attempt_tokens = [int(t) for t in
                                              gasp["tokens"]]
                        except (TypeError, ValueError):
                            attempt_tokens = []
                    failed_reason = ("replica %s answered %d (%s)"
                                     % (replica.url, status,
                                        err_body.get("error", "")))
                else:
                    delivered = (status, err_body)
            except Exception as e:  # noqa: BLE001 — the failure class
                failed_reason = "%s: %s" % (type(e).__name__, e)
            else:
                # `with resp`: the upstream socket closes on EVERY
                # exit — terminal break, mid-stream failure, client
                # gone — never left to GC (one leaked fd per attempt
                # would EMFILE a long-lived router)
                with resp:
                    ctype = resp.headers.get("Content-Type", "")
                    if "event-stream" not in ctype:
                        # buffered 200 (replica streams disabled): one
                        # burst + terminal, stitched like the latch
                        # path
                        try:
                            full = json.loads(resp.read() or b"{}")
                        except ValueError:
                            full = {}
                        if isinstance(full.get("tokens"), list):
                            attempt_tokens = [int(t) for t in
                                              full["tokens"]]
                            done_event = dict(full, done=True)
                        else:
                            failed_reason = (
                                "replica %s answered a bodyless 200"
                                % replica.url)
                    else:
                        try:
                            for ev in self._sse_events(resp):
                                if ev.get("done"):
                                    done_event = ev
                                    break
                                toks = ev.get("tokens")
                                if not isinstance(toks, list):
                                    continue
                                abs_start = len(prefix) \
                                    + len(attempt_tokens)
                                attempt_tokens.extend(int(t)
                                                      for t in toks)
                                # forward only what the client has
                                # not seen (a scratch retry after a
                                # dropped resume re-emits the whole
                                # sequence)
                                skip = (base_k + state["sent"]) \
                                    - abs_start
                                out = [int(t)
                                       for t in toks[max(0, skip):]]
                                if out:
                                    event({"tokens": out,
                                           "i": state["sent"],
                                           "request_id": rid,
                                           "trace_id": tid})
                                    state["sent"] += len(out)
                        except FleetRouter._ClientGone as e:
                            # the CLIENT died, not the replica: no
                            # breaker advance, no failover re-decode —
                            # just stop (the replica settles its
                            # ticket on its own)
                            self.debug("%s: streaming client for %s "
                                       "disconnected (%s)", self.name,
                                       rid, e)
                            return finish(
                                499, "client disconnected mid-stream",
                                n_attempts)
                        except Exception as e:  # noqa: BLE001
                            failed_reason = (
                                "stream from %s died: %s: %s"
                                % (replica.url, type(e).__name__, e))
                        if done_event is None \
                                and failed_reason is None:
                            failed_reason = (
                                "stream from %s ended without a "
                                "terminal event" % replica.url)
            if done_event is not None and failed_reason is None \
                    and done_event.get("error") is not None:
                # the replica's dying gasp arrived AS the terminal
                # stream event: a failed attempt whose resume record
                # covers everything it decoded
                gasp = done_event.get("resume")
                if resumable and isinstance(gasp, dict) \
                        and isinstance(gasp.get("tokens"), list):
                    try:
                        gained = [int(t) for t in gasp["tokens"]]
                        if len(gained) >= len(attempt_tokens):
                            attempt_tokens = gained
                    except (TypeError, ValueError):
                        pass
                failed_reason = ("replica %s failed mid-stream (%s)"
                                 % (replica.url,
                                    done_event.get("error")))
                done_event = None
            if trace_on:
                try:
                    emit_span(
                        "route.attempt", t_att, time.time() - t_att,
                        endpoint=replica.url, attempt=n_attempts,
                        request_id=rid, trace_id=tid, stream=1,
                        tokens_done=len(prefix),
                        outcome=("answered" if done_event is not None
                                 or delivered is not None
                                 else "failed"),
                        **({"reason": failed_reason}
                           if failed_reason else {}))
                except Exception:   # noqa: BLE001 — observability only
                    pass
            if delivered is not None:
                # 2xx–4xx non-stream answers are the replica's word
                replica.breaker.record_success()
                status, err_body = delivered
                try:
                    if state["headers"]:
                        event(dict(err_body, done=True, code=status))
                    else:
                        json_reply(handler, status, err_body)
                except (FleetRouter._ClientGone, BrokenPipeError,
                        ConnectionResetError, OSError):
                    pass        # the answer existed; client left
                return finish(status, "answered", n_attempts)
            if done_event is not None:
                replica.breaker.record_success()
                full_toks = prefix + attempt_tokens
                final = dict(done_event)
                final["tokens"] = full_toks[base_k:]
                final.setdefault("request_id", rid)
                final.setdefault("trace_id", tid)
                if len(prefix) > base_k:
                    final["resumed_from"] = len(prefix)
                try:
                    # complete the incremental wire first (tokens a
                    # buffered-200 replica or a tail-in-done-only
                    # stream never sent as token events), THEN the
                    # authoritative terminal
                    emit_gap(full_toks)
                    event(final)
                except FleetRouter._ClientGone:
                    self.debug("%s: streaming client for %s went "
                               "away before the terminal event",
                               self.name, rid)
                return finish(200, "answered", n_attempts)
            # failed attempt: breaker + resume accounting, then retry
            last_reason = failed_reason or "replica failure"
            if drop_resume:
                prefix = []
                if replica in tried:
                    tried.remove(replica)
            else:
                inc("veles_router_replica_errors_total")
                if replica.breaker.record_failure():
                    inc("veles_router_breaker_opens_total")
                if resumable and total_new is not None \
                        and attempt_tokens \
                        and len(prefix) + len(attempt_tokens) \
                        < total_new:
                    # a gasp may carry tokens the stream never
                    # delivered — forward them BEFORE resuming past
                    # them, so the client's incremental wire has no
                    # hole (the retry decodes only the remainder)
                    try:
                        emit_gap(prefix + attempt_tokens)
                    except FleetRouter._ClientGone as e:
                        self.debug("%s: streaming client for %s "
                                   "disconnected (%s)", self.name,
                                   rid, e)
                        return finish(
                            499, "client disconnected mid-stream",
                            n_attempts)
                    prefix = prefix + attempt_tokens
        # nobody could answer
        if state["headers"]:
            try:
                event({"done": True, "code": 503,
                       "error": "no replica could answer: %s"
                                % last_reason,
                       "request_id": rid, "retry_after": 1.0})
            except FleetRouter._ClientGone:
                pass
            return finish(503, "unanswered: %s" % last_reason,
                          n_attempts)
        health.shed(handler, retry_after=1.0,
                    reason="no replica could answer: %s" % last_reason,
                    request_id=rid)
        return finish(503, "unanswered: %s" % last_reason, n_attempts)

    def _note_attempt(self, replica: Replica, state: _Attempt,
                      answered: _Answer, rid: str, tid: str,
                      attempt_no: int, t0: float, tokens_done: int,
                      probing: bool, trips_before: int) -> None:
        """Retrospective span emission for one settled-or-abandoned
        attempt: ``route.attempt`` always (endpoint, outcome, http
        status when this replica answered, the resume prefix length
        carried in), ``route.probe`` when the attempt was a
        half-open breaker probe, and ``route.backoff`` when THIS
        failure opened the breaker (the span covers the scheduled
        open interval, so the failover gap is a visible timeline
        event). Never raises — observability only."""
        try:
            now = time.time()
            if answered.done and answered.replica is replica:
                outcome: str = "answered"
                status: Optional[int] = answered.status
            elif state.settled and state.failed:
                outcome, status = "failed", None
            elif state.settled:
                # settled-success without winning the latch: succeed()
                # runs only after offer(), which sets done+replica
                # together — so this replica cannot be the winner
                # here; its answer was the dropped duplicate
                outcome, status = "duplicate", None
            else:
                # still running when the loop moved on (late answers
                # may yet win the latch)
                outcome, status = "pending", None
            tags: Dict[str, Any] = {
                "endpoint": replica.url, "attempt": attempt_no,
                "request_id": rid, "trace_id": tid,
                "tokens_done": tokens_done, "outcome": outcome}
            if status is not None:
                tags["status"] = int(status)
            if state.reason:
                tags["reason"] = state.reason
            emit_span("route.attempt", t0, now - t0, **tags)
            if probing:
                emit_span("route.probe", t0, now - t0,
                          endpoint=replica.url, attempt=attempt_no,
                          request_id=rid, trace_id=tid,
                          outcome=outcome)
            breaker = replica.breaker
            if breaker.trips > trips_before \
                    and breaker.state == CircuitBreaker.OPEN:
                # the scheduled open interval, emitted at open time:
                # an interval on this host's wall clock equal to the
                # breaker's monotonic hold
                hold = max(0.0, breaker.open_until - breaker._clock())
                emit_span("route.backoff", now, hold,
                          endpoint=replica.url, request_id=rid,
                          trace_id=tid, trips=breaker.trips)
        except Exception:       # noqa: BLE001 — observability only
            pass

    # -- surfaces ------------------------------------------------------------
    def gauges(self) -> Dict[str, Any]:
        ready = sum(1 for r in self.replicas if r.ready)
        open_breakers = sum(1 for r in self.replicas
                            if r.breaker.state != CircuitBreaker.CLOSED)
        gauges = {
            "veles_router_replicas":
                (len(self.replicas), "Replica endpoints this router "
                                     "fans out over (a tensor-"
                                     "parallel mesh slice counts "
                                     "once, however many chips it "
                                     "spans)"),
            "veles_router_chips":
                (sum(max(1, r.tp_devices) for r in self.replicas),
                 "Accelerator chips behind the roster (each "
                 "replica's mesh-slice width, 1 for a solo engine)"),
            "veles_router_replicas_ready":
                (ready, "Replicas currently admitting (ready, per "
                        "the last /readyz probe)"),
            "veles_router_breakers_open":
                (open_breakers, "Replicas whose circuit breaker is "
                                "open or half-open"),
            "veles_router_inflight":
                (self._inflight, "Requests currently being routed"),
            "veles_router_draining":
                (1 if self._draining else 0,
                 "1 while the router is draining (admission "
                 "stopped, in-flight finishing)"),
            # fleet-level occupancy: sums of the probe-thread
            # snapshots across the roster — the series the
            # watchtower's fleet rules (queue_depth_high) and the
            # `veles-tpu watch` dashboard read from the router
            "veles_fleet_slots":
                (sum(r.slots for r in self.replicas),
                 "Decode slots across all roster replicas (last "
                 "probe)"),
            "veles_fleet_slots_busy":
                (sum(r.slots_busy for r in self.replicas),
                 "Busy decode slots across all roster replicas "
                 "(last probe)"),
            "veles_fleet_queue_depth":
                (sum(r.queue_depth for r in self.replicas),
                 "Queued requests across all roster replicas (last "
                 "probe)"),
        }
        if self.journal is not None:
            gauges["veles_router_journal_pending"] = (
                max(0, self._journal_outstanding),
                "Journaled requests admitted but not yet terminal "
                "(in flight or awaiting replay)")
            gauges["veles_router_journal_enabled"] = (
                1, "1 when the durable request journal is on")
        if self.governor is not None:
            snap = self.governor.snapshot()
            gauges["veles_qos_admit_rate"] = (
                snap["veles_qos_admit_rate"],
                "AIMD batch admission rate (1.0 = unthrottled, "
                "falls multiplicatively while TTFT p99 exceeds "
                "the SLO)")
            gauges["veles_qos_brownout_level"] = (
                snap["veles_qos_brownout_level"],
                "Brownout ladder level (0 normal, 1 cap n_new, "
                "2 no speculative, 3 shed batch)")
            gauges["veles_qos_retry_tokens"] = (
                snap["veles_qos_retry_tokens"],
                "Failover retry tokens currently available in the "
                "router-wide storm-control bucket")
        return gauges

    def roster(self) -> Dict[str, Any]:
        """The live replica roster — saved to a file this is directly
        consumable by ``veles-tpu metrics aggregate
        --endpoints-file`` (fleet scraping and routing share one
        roster)."""
        return {
            "router": self.name,
            "path": self.path,
            "draining": self._draining,
            "endpoints": [r.snapshot() for r in self.replicas],
        }

    def _make_handler(self):
        router = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                router.debug("http: " + fmt, *args)

            def do_GET(self):
                if health.handle_health(self, self.path):
                    return
                if handle_trace_spans(self, self.path,
                                      name="router.%s" % router.name):
                    return
                if handle_metrics_history(self, self.path,
                                          name="router.%s"
                                          % router.name):
                    return
                if handle_alerts(self, self.path):
                    return
                if self.path == "/metrics":
                    from ..telemetry.alerts import render_firing
                    text = metrics_text(router.gauges()) \
                        + render_firing()
                    bytes_reply(self, 200, text.encode(),
                                METRICS_CONTENT_TYPE)
                    return
                if self.path == "/fleet/metrics":
                    # live fleet-wide aggregation over the roster —
                    # counters/buckets summed, quantiles recomputed
                    # (telemetry/fleet.py), scraped on demand
                    agg = fleet.aggregate(
                        [r.url for r in router.replicas],
                        timeout=router.probe_timeout)
                    bytes_reply(self, 200,
                                fleet.render(agg).encode(),
                                METRICS_CONTENT_TYPE)
                    return
                if self.path == "/roster":
                    json_reply(self, 200, router.roster())
                    return
                self.send_error(404)

            def do_POST(self):
                if self.path == "/drain":
                    started = router.begin_drain()
                    threading.Thread(target=router.drain,
                                     daemon=True,
                                     name=router.name
                                     + ".drain").start()
                    json_reply(self, 200, {
                        "status": "draining",
                        "already_draining": not started,
                        "in_flight": router._inflight})
                    return
                if self.path != router.path:
                    self.send_error(404)
                    return
                if router._draining or router._closing:
                    health.shed(self, retry_after=5.0,
                                reason="router draining",
                                request_id=new_request_id())
                    return
                try:
                    body = read_json_object(self)
                except ValueError as e:
                    json_reply(self, 400,
                               {"error": "bad request: %s" % e})
                    return
                if not isinstance(body.get("stream", False), bool):
                    # the replica's _parse would answer this 400 —
                    # the router must not coerce a truthy non-bool
                    # ("false", 1) into an SSE stream the replica
                    # would have refused
                    json_reply(self, 400,
                               {"error": "bad request: 'stream' "
                                         "must be a boolean"})
                    return
                gov = router.governor
                if gov is not None:
                    # adaptive admission BEFORE the durability
                    # boundary: a throttled request was never
                    # accepted, so nothing to journal or replay.
                    # Interactive always passes; brownout mutations
                    # (n_new cap, speculative off) apply to whatever
                    # is admitted
                    reason = gov.admit(body)
                    if reason is not None:
                        health.shed(self,
                                    retry_after=gov.retry_after(),
                                    reason=reason,
                                    request_id=body.get("request_id")
                                    or new_request_id())
                        return
                    gov.degrade(body)
                # the durability boundary: the request exists in the
                # journal BEFORE its first dispatch, so a router
                # SIGKILL after this line loses nothing — restart
                # replays it. An injected append failure refuses the
                # admission (shed, with the id) rather than accept a
                # request durability cannot cover.
                rid = body.get("request_id") or new_request_id()
                # the trace_id is minted HERE, with the request_id,
                # so the journal's admit record carries it and a
                # replayed request resumes under its original trace
                tid = body.get("trace_id") or new_trace_id()
                body = dict(body, request_id=rid, trace_id=tid)
                if router.journal is not None:
                    try:
                        router.journal.admit(rid, body, time.time(),
                                             trace_id=tid)
                    except Exception as e:  # noqa: BLE001 — fail closed
                        # durability contract: cannot journal ⇒ do
                        # not accept — an injected append fault and a
                        # real I/O error (ENOSPC, read-only dir)
                        # shed alike, never acknowledge un-journaled
                        health.shed(self, retry_after=1.0,
                                    reason="request journal "
                                           "unavailable: %s" % e,
                                    request_id=rid)
                        return
                    with router._cv:
                        router._journal_outstanding += 1
                if body.get("stream"):
                    # streaming proxy: events pipe through as they
                    # arrive, mid-stream failover resumes from the
                    # forwarded prefix; the journal terminal mirrors
                    # the buffered path's
                    with router._cv:
                        router._inflight += 1
                    try:
                        status, outcome, attempts = \
                            router.route_stream(body, self)
                    finally:
                        with router._cv:
                            router._inflight -= 1
                            router.requests_routed += 1
                            router._cv.notify_all()
                    if router.journal is not None:
                        try:
                            router.journal.done(rid, int(status),
                                                outcome, trace_id=tid,
                                                attempts=attempts)
                            with router._cv:
                                router._journal_outstanding -= 1
                        except Exception as e:  # noqa: BLE001
                            router.warning(
                                "%s: journal terminal for %s failed "
                                "(%s: %s); the entry stays pending — "
                                "a restart replays it idempotently",
                                router.name, rid, type(e).__name__, e)
                    return
                with router._cv:
                    router._inflight += 1
                try:
                    answered = router.route(body)
                finally:
                    with router._cv:
                        router._inflight -= 1
                        router.requests_routed += 1
                        router._cv.notify_all()
                # the answer — success and shed alike — is terminal:
                # replay must never re-run it. (A route that RAISED
                # never reaches this line: the entry stays pending
                # and the next start replays it, idempotent by id.)
                if router.journal is not None:
                    try:
                        router.journal.done(
                            rid,
                            int(answered.status) if answered.done
                            else 503,
                            "answered" if answered.done
                            else "unanswered",
                            trace_id=tid,
                            attempts=answered.attempts)
                        with router._cv:
                            router._journal_outstanding -= 1
                    except Exception as e:  # noqa: BLE001
                        # a failed terminal append (injected fault,
                        # full disk) must NOT drop the answer we
                        # already computed — the client still gets
                        # its reply below; the entry stays pending
                        # (and counted in the gauge) so a restart
                        # re-runs it idempotently by id
                        router.warning(
                            "%s: journal terminal for %s failed "
                            "(%s: %s); the entry stays pending — a "
                            "restart replays it idempotently",
                            router.name, rid, type(e).__name__, e)
                if not answered.done:
                    health.shed(
                        self, retry_after=1.0,
                        reason="no replica could answer: %s"
                        % getattr(answered, "reason",
                                  "no ready replica"),
                        request_id=answered.request_id)
                    return
                headers = None
                if answered.retry_after:
                    headers = {"Retry-After": str(answered.retry_after)}
                reply = answered.body
                if isinstance(reply, dict):
                    # the client learns the fleet trace key with its
                    # answer — `veles-tpu trace fleet --request` takes
                    # either this or the request_id
                    reply = dict(reply)
                    reply.setdefault("trace_id", tid)
                json_reply(self, answered.status, reply,
                           headers=headers)

        return Handler


class ReplicaSupervisor(Logger):
    """Spawn/classify/respawn plane for long-lived serving replicas —
    the PR 9 elastic :class:`~veles_tpu.resilience.elastic.Supervisor`
    generalized from training generations: training reaps the WHOLE
    generation when one host dies (its survivors are wedged in
    collectives), a serving fleet respawns ONLY the hole while the
    router routes around it.

    ``spawn(index, incarnation)`` builds replica ``index``'s process
    (or in-process stand-in) and returns a handle exposing
    ``poll() -> Optional[int]`` (None while alive, else the exit
    code) and, optionally, ``kill()``. Exit classification:

    - ``0`` — a deliberate, drained shutdown: the replica stays down
      (scaling in is not a failure);
    - anything else (``faults.CRASH_EXIT_CODE``, a signal, an OOM
      kill) — a death: the replica is respawned after a
      :meth:`RetryPolicy.backoff` delay (seeded jitter, growing with
      consecutive deaths; a replica that comes back and dies again
      immediately backs off harder), counted in
      ``veles_router_respawns_total``, up to ``max_respawns`` —
      after which the supervisor gives up on that index and the
      router simply keeps routing around it.

    ``clock`` is injectable; :meth:`check` performs one non-blocking
    sweep so tests drive classification deterministically."""

    def __init__(self, spawn: Callable[[int, int], Any],
                 n_replicas: int, max_respawns: int = 8,
                 poll_interval: float = 0.2,
                 backoff: Optional[RetryPolicy] = None,
                 clock: Callable[[], float] = time.monotonic,
                 name: str = "fleet") -> None:
        super().__init__()
        if n_replicas < 1:
            raise VelesError("a supervised fleet needs >= 1 replica")
        self._spawn = spawn
        self.n_replicas = int(n_replicas)
        self.max_respawns = int(max_respawns)
        self.poll_interval = float(poll_interval)
        self.backoff = backoff if backoff is not None else RetryPolicy(
            base_delay=0.1, max_delay=5.0, name="respawn")
        self._clock = clock
        self.name = name
        self.handles: List[Any] = [None] * self.n_replicas
        self.incarnations = [0] * self.n_replicas
        #: deliberate exits (code 0) — never respawned
        self.stopped = [False] * self.n_replicas
        #: respawn budget exhausted — the router routes around it
        self.given_up = [False] * self.n_replicas
        #: index -> monotonic time its pending respawn fires
        self._restart_at: Dict[int, float] = {}
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._closing = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        with self._lock:
            for i in range(self.n_replicas):
                if self.handles[i] is None and not self.stopped[i] \
                        and not self.given_up[i] \
                        and i not in self._restart_at:
                    self._spawn_one(i)
        self._closing.clear()
        self._thread = threading.Thread(target=self._watch,
                                        daemon=True,
                                        name=self.name + ".supervise")
        self._thread.start()
        return self

    def stop(self, kill: bool = False) -> None:
        self._closing.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if kill:
            with self._lock:
                for handle in self.handles:
                    killer = getattr(handle, "kill", None)
                    if handle is not None and callable(killer):
                        try:
                            killer()
                        except OSError:
                            pass

    def _watch(self) -> None:
        while not self._closing.wait(timeout=self.poll_interval):
            self.check()

    # -- classify + respawn --------------------------------------------------
    def _spawn_one(self, i: int) -> None:
        self.incarnations[i] += 1
        self._restart_at.pop(i, None)
        self.handles[i] = self._spawn(i, self.incarnations[i])

    def check(self, now: Optional[float] = None) -> List[str]:
        """One supervision sweep: classify exits, schedule + perform
        respawns. Returns human-readable event strings (tests and the
        CLI log them)."""
        now = self._clock() if now is None else now
        events: List[str] = []
        with self._lock:
            for i in range(self.n_replicas):
                handle = self.handles[i]
                if handle is None:
                    due = self._restart_at.get(i)
                    if due is not None and now >= due:
                        try:
                            self._spawn_one(i)
                        except Exception as e:  # noqa: BLE001
                            # the respawn itself failed (port still
                            # held, artifact missing): back off and
                            # try again — the watch thread survives,
                            # and failed attempts still count toward
                            # the give-up budget
                            if self.incarnations[i] > self.max_respawns:
                                self.given_up[i] = True
                                events.append(
                                    "replica %d respawn failed (%s) — "
                                    "giving up" % (i, e))
                            else:
                                self._restart_at[i] = now \
                                    + self.backoff.backoff(
                                        min(self.incarnations[i], 16))
                                events.append(
                                    "replica %d respawn failed (%s) — "
                                    "retrying" % (i, e))
                            self.warning("%s: %s", self.name,
                                         events[-1])
                            continue
                        inc("veles_router_respawns_total")
                        events.append(
                            "respawned replica %d (incarnation %d)"
                            % (i, self.incarnations[i]))
                        self.info("%s: %s", self.name, events[-1])
                    continue
                code = handle.poll()
                if code is None:
                    continue
                self.handles[i] = None
                if code == 0:
                    self.stopped[i] = True
                    events.append("replica %d exited cleanly "
                                  "(drained)" % i)
                    self.info("%s: %s", self.name, events[-1])
                    continue
                deaths = self.incarnations[i]
                if deaths > self.max_respawns:
                    self.given_up[i] = True
                    events.append(
                        "replica %d died (exit %s) after %d "
                        "incarnations — giving up, the router "
                        "routes around it" % (i, code, deaths))
                    self.warning("%s: %s", self.name, events[-1])
                    continue
                delay = self.backoff.backoff(min(deaths, 16))
                self._restart_at[i] = now + delay
                events.append(
                    "replica %d died (exit %s) — respawn in %.2fs"
                    % (i, code, delay))
                self.warning("%s: %s", self.name, events[-1])
        return events

    def alive(self) -> int:
        with self._lock:
            return sum(1 for h in self.handles
                       if h is not None and h.poll() is None)
