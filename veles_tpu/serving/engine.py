"""Continuous-batching decode engine: a persistent slot-pool KV cache
driven by ONE fixed-shape jitted decode step.

Replaces the window-coalescing serving model (one batched decode per
exact shape key, everyone rides to the longest member's ``n_new``)
with iteration-level scheduling:

- the KV cache is a ``max_slots``-row pool, every row padded to
  ``max_context`` — the decode step's shapes never change, so it
  compiles exactly once;
- prefill pads prompts to a small set of length ``buckets`` — the jit
  cache is bounded by ``len(buckets) + 1`` programs, not by distinct
  prompt lengths (right-padding is safe under the causal mask: pad
  K/V rows are invisible to real positions and are overwritten by the
  decode steps before the read mask ever reaches them);
- the scheduler admits queued requests into free slots at step
  boundaries and a row retires the moment it emits ``eos_id`` or
  reaches its own ``n_new`` — short requests never wait for long
  co-riders and the chip never idles while the queue is non-empty;
- each slot carries its own PRNG stream derived purely from the
  request's ``seed`` (``jax.random.fold_in``-style independence via
  per-row ``split`` streams), so a request's tokens are id-exact vs
  its solo decode whatever strangers share the batch — stochastic
  decodes batch on the same bar the greedy CI gate sets.

The per-block cache layout and math are ``nn/sampling.py``'s
``_block_prefill`` / ``_block_step`` — the decode step vmaps the very
same single-row step over the pool, so the engine cannot drift from
the scan decoder numerically.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy

from ..error import VelesError
from ..logger import Logger
from ..nn.sampling import (_block_prefill, _block_step,
                           _count_decode_dispatches, _split_rows,
                           params_of, split_stack)
from ..resilience import health
from ..resilience.faults import FaultInjected, fire as fire_fault
from ..telemetry.counters import inc
from ..telemetry.spans import span

#: floor for the temperature divisor inside the one shared decode
#: program (greedy rows carry temperature 0; their categorical lane is
#: computed-and-discarded, so the clamp only has to keep it finite)
_TEMP_EPS = 1e-3


def make_request(prompt, n_new, temperature=0.0, seed=0, eos_id=None
                 ) -> Dict:
    """Normalized request dict (the subset of GenerationAPI's parsed
    request the engine consumes) — for tests and bench harnesses."""
    return {"prompt": [int(t) for t in prompt], "n_new": int(n_new),
            "temperature": float(temperature), "seed": int(seed),
            "eos_id": eos_id}


class ContinuousEngine(Logger):
    """In-flight batching over a persistent KV-cache slot pool.

    ``wf`` is a generation-capable workflow (``Embedding`` →
    ``TransformerBlock``×N → ``LMHead``, validated at construction).
    ``decode_block`` fuses that many decode steps into one dispatch
    (``lax.scan``) — admission/retirement granularity stays one
    *chunk*; 1 keeps pure per-token scheduling, larger values amortize
    dispatch overhead on hosts where it dominates.
    """

    def __init__(self, wf, max_slots: int = 8,
                 buckets: Tuple[int, ...] = (16, 32, 64, 128),
                 max_context: int = 640, decode_block: int = 1,
                 name: str = "serving") -> None:
        super().__init__()
        from .scheduler import SlotScheduler
        self.wf = wf
        self.name = name
        # raises VelesError on anything but a generation stack (a bare
        # workflow has no forwards at all — same rejection)
        self.stack = split_stack(list(getattr(wf, "forwards", ()) or ()))
        self.max_slots = int(max_slots)
        self.max_context = int(max_context)
        self.decode_block = max(1, int(decode_block))
        from . import parse_buckets
        self.buckets = parse_buckets(buckets)
        self.scheduler = SlotScheduler(self.max_slots, self.buckets,
                                       self.max_context)
        pos_emb = self.stack["pos_emb"]
        self._table_len = (None if pos_emb is None else
                           pos_emb.param_arrays()["table"].shape[0])
        self._progs: Dict = {}
        self._params = None
        self._caches = None
        self._keys = None
        self._tok = numpy.zeros(self.max_slots, numpy.int32)
        self._pos = numpy.zeros(self.max_slots, numpy.int32)
        self._temp = numpy.zeros(self.max_slots, numpy.float32)
        self._thread: Optional[threading.Thread] = None
        self._closing = False
        self.admitted = 0
        self.retired = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ContinuousEngine":
        if self._thread is not None:
            return self
        self._closing = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name + ".engine")
        self._thread.start()
        from . import register_engine
        register_engine(self)
        self.info("%s: continuous batching up (slots=%d buckets=%s "
                  "max_context=%d decode_block=%d)", self.name,
                  self.max_slots, list(self.buckets), self.max_context,
                  self.decode_block)
        return self

    def stop(self) -> None:
        with self.scheduler.cv:
            self._closing = True
            self.scheduler.cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.scheduler.drain("server shutting down")
        self._abort_active("server shutting down", code=503,
                           retry_after=5.0, count_shed=False)
        from . import unregister_engine
        unregister_engine(self)

    # -- intake --------------------------------------------------------------
    def accepts(self, req: Dict) -> Optional[str]:
        """None when the slot pool can serve ``req``; otherwise the
        reason (caller falls back to the window-coalescing path)."""
        t_p, n_new = len(req["prompt"]), int(req["n_new"])
        if t_p < 1:
            return "empty prompt"
        reason = self.scheduler.reject_reason(t_p, n_new)
        if reason:
            return reason
        if self._table_len is not None and t_p + n_new > self._table_len:
            return ("generation to %d positions exceeds the trained "
                    "PositionalEmbedding table (%d rows)"
                    % (t_p + n_new, self._table_len))
        if 0 < float(req.get("temperature", 0.0)) < _TEMP_EPS:
            # the shared decode program clamps the divisor at
            # _TEMP_EPS; a colder-than-that request would sample from
            # different logits here than solo sampling.generate does —
            # route it to the window plane, which divides exactly
            return ("temperature %g below the engine's %g resolution"
                    % (req["temperature"], _TEMP_EPS))
        bucket = self.scheduler.bucket_for(t_p)
        if self._kernel_straddle(t_p, bucket):
            # padding to the bucket would flip attention_core's
            # flash/reference choice vs the exact-length solo prefill
            # (choose_flash is length-gated) — different kernels drift
            # in the last bits and break the id-exactness contract, so
            # such a prompt rides the window plane instead
            return ("prompt %d pads to bucket %d across the "
                    "flash-attention crossover" % (t_p, bucket))
        return None

    def _kernel_straddle(self, t_p: int, bucket: int) -> bool:
        """True when any block's attention would pick a different
        kernel for the padded bucket length than for the exact prompt
        length (see ``ops.flash_attention.choose_flash``)."""
        if t_p == bucket:
            return False
        from ..ops.flash_attention import choose_flash
        d = self.stack["stem"].dim
        for blk in self.stack["blocks"]:
            hd = d // blk.n_heads
            if choose_flash(bucket, hd) != choose_flash(t_p, hd):
                return True
        return False

    def submit(self, req: Dict, ticket,
               max_queue: Optional[int] = None,
               checked: bool = False) -> bool:
        """Enqueue one request; False = queue bound hit (caller
        sheds). ``ticket`` follows the :class:`scheduler.Ticket`
        protocol (``fail`` / ``succeed`` / ``deadline``).
        ``checked=True`` skips :meth:`accepts` — for callers that just
        routed on its verdict."""
        if not checked:
            reason = self.accepts(req)
            if reason is not None:
                # direct submits (no API-side accepts() pre-check) get
                # a clean client-fault answer instead of a 500 at
                # admission
                ticket.fail(reason, code=400)
                return True
        # the closing check and the enqueue share the scheduler's
        # condition (an RLock): stop() flips _closing under the same
        # lock before draining, so a ticket can never slip into the
        # queue after the drain and strand its handler until 504
        with self.scheduler.cv:
            if self._closing:
                return False
            return self.scheduler.push(req, ticket, max_queue)

    def serve(self, reqs: List[Dict], timeout: float = 300.0
              ) -> List[List[int]]:
        """Synchronous convenience (tests / bench): submit every
        request, wait, return each token list; raises on any error."""
        from .scheduler import Ticket
        tickets = [Ticket() for _ in reqs]
        for req, ticket in zip(reqs, tickets):
            if not self.submit(req, ticket):
                raise VelesError("serving queue full")
        out = []
        for req, ticket in zip(reqs, tickets):
            if not ticket.event.wait(timeout):
                raise VelesError("serving timed out for %r" % (req,))
            if ticket.error is not None:
                raise VelesError("serving failed: %s" % ticket.error)
            out.append(ticket.result["tokens"])
        return out

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        return {
            "slots": self.max_slots,
            "slots_busy": self.scheduler.busy_count(),
            "queue_depth": self.scheduler.queue_depth(),
            "admitted": self.admitted,
            "retired": self.retired,
            "programs": len(self._progs),
        }

    @property
    def closing(self) -> bool:
        """True once :meth:`stop` has begun — :meth:`submit` returns
        False for a closing engine too, and the caller's shed answer
        should say shutdown, not queue-full."""
        return self._closing

    @property
    def programs_built(self) -> int:
        """Jitted programs this engine ever built — bounded by
        ``len(buckets) + 1`` (the bucketed prefills + the one decode
        step), never by distinct prompt lengths."""
        return len(self._progs)

    # -- worker --------------------------------------------------------------
    def _loop(self) -> None:
        hb = "serving.%s" % self.name
        fail_streak = 0
        try:
            while True:
                with self.scheduler.cv:
                    while (not self.scheduler._queue
                           and self.scheduler.busy_count() == 0
                           and not self._closing):
                        self.scheduler.cv.wait(timeout=5.0)
                        if not self._closing:
                            health.heartbeats.beat(hb)
                    if self._closing:
                        return
                health.heartbeats.beat(hb)
                try:
                    self._tick()
                    fail_streak = 0
                except Exception:     # noqa: BLE001 — serve, don't die
                    fail_streak += 1
                    self.exception("%s: serving tick failed", self.name)
                    self._abort_active("internal serving error",
                                       code=500, count_shed=False)
                    # donated buffers may be gone — rebuild lazily
                    self._caches = self._keys = self._params = None
                    # a tick that dies before take_admissions never
                    # reaches the deadline check there: sweep the queue
                    # so waiting callers still get their 503 instead of
                    # hanging to full timeout, and back off instead of
                    # busy-spinning while the failure persists
                    from .scheduler import shed_expired
                    shed_expired(self.scheduler.expire_queued())
                    if not self._closing:
                        time.sleep(min(1.0, 0.05 * (2 ** fail_streak)))
        finally:
            health.heartbeats.unregister(hb)

    def _tick(self) -> None:
        """One step boundary: admit into free slots, then run one
        decode chunk over the pool."""
        # the param device-view walk (per-array locks) is too heavy to
        # repeat per decode chunk, but a snapshot held forever would
        # serve stale weights after a host-side update. Middle ground:
        # re-read whenever the pool is IDLE (no in-flight rows) — a
        # param change lands at the next burst boundary, no request
        # ever decodes on torn half-old/half-new weights, and under
        # sustained load the walk is never on the per-token path
        # (weights are frozen while serving, as everywhere in serving).
        params = self._params
        if params is None or self.scheduler.busy_count() == 0:
            params = self._params = params_of(self.wf)
        self._ensure_pool(params)
        from .scheduler import shed_expired
        admissions, expired = self.scheduler.take_admissions()
        shed_expired(expired)
        for slot in admissions:
            try:
                self._admit(params, slot)
            except Exception as e:    # noqa: BLE001 — answer, don't die
                self.scheduler.retire(slot)
                slot.ticket.fail("%s: %s" % (type(e).__name__, e),
                                 code=500)
                # the prefill program DONATES the pool: a dispatch
                # that died may have consumed the co-tenants' caches
                # with it, and there is no cheap way to tell. Shed the
                # in-flight rows (503 + Retry-After) and rebuild the
                # pool rather than decode on possibly-dead buffers.
                self.exception("%s: admission failed; resetting the "
                               "slot pool", self.name)
                self._abort_active("serving pool reset after a failed "
                                   "admission", code=503,
                                   retry_after=1.0)
                self._caches = self._keys = self._params = None
                return
        if self.scheduler.busy_count():
            try:
                self._decode(params)
            except FaultInjected as e:
                # an injected decode fault DEGRADES: in-flight rows are
                # shed with Retry-After, the pool stays consistent (the
                # fault fires before the dispatch)
                self._abort_active(str(e), code=503, retry_after=1.0)

    def _ensure_pool(self, params) -> None:
        if self._caches is not None:
            return
        import jax.numpy as jnp
        stem, blocks = self.stack["stem"], self.stack["blocks"]
        dtype = params[stem.name]["table"].dtype
        d = stem.dim
        caches = []
        for blk in blocks:
            bkv = getattr(blk, "n_kv_heads", blk.n_heads)
            hd = d // blk.n_heads
            caches.append(
                (jnp.zeros((self.max_slots, self.max_context, bkv, hd),
                           dtype),
                 jnp.zeros((self.max_slots, self.max_context, bkv, hd),
                           dtype)))
        self._caches = tuple(caches)
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)

    # -- admission ------------------------------------------------------------
    def _admit(self, params, slot) -> None:
        import jax
        import jax.numpy as jnp
        t_p, bucket = slot.t_p, slot.bucket
        ids = numpy.zeros((1, bucket), numpy.int32)
        ids[0, :t_p] = slot.req["prompt"]
        prog = self._program("prefill", bucket)
        seed_key = jax.random.PRNGKey(int(slot.req.get("seed", 0)))
        wait = max(0.0, time.time() - slot.ticket.enqueued)
        with span("serving.prefill", bucket=bucket, slot=slot.idx,
                  t_p=t_p):
            first, self._keys, self._caches = prog(
                params, jnp.asarray(ids), numpy.int32(t_p),
                numpy.int32(slot.idx), numpy.float32(slot.temperature),
                seed_key, self._keys, self._caches)
            first = int(first)
        inc("veles_serving_prefill_dispatches_total")
        inc("veles_serving_admitted_total")
        inc("veles_serving_queue_wait_seconds_total", wait)
        self.admitted += 1
        self._tok[slot.idx] = first
        self._pos[slot.idx] = t_p
        self._temp[slot.idx] = slot.temperature
        if slot.record(first):
            self._finish(slot)

    # -- the decode chunk ------------------------------------------------------
    def _decode(self, params) -> None:
        import jax.numpy as jnp
        active = self.scheduler.active()
        fire_fault("serve.decode_step")
        with span("serving.decode_step", active=len(active),
                  chunk=self.decode_block):
            toks, self._keys, self._caches = self._program("step")(
                params, jnp.asarray(self._tok), jnp.asarray(self._pos),
                jnp.asarray(self._temp), self._keys, self._caches)
            toks = numpy.asarray(toks)          # (decode_block, S)
        inc("veles_serving_decode_dispatches_total")
        finished: List = []
        for h in range(toks.shape[0]):
            still = [s for s in active if s not in finished]
            if not still:
                break
            for slot in still:
                token = int(toks[h, slot.idx])
                self._tok[slot.idx] = token
                self._pos[slot.idx] += 1
                if slot.record(token):
                    finished.append(slot)
        for slot in finished:
            self._finish(slot)

    def _finish(self, slot) -> None:
        """Retire a row the moment it is done: free the slot (the next
        admission reuses it immediately) and answer the ticket."""
        inc("veles_serving_retired_total")
        inc("veles_serving_tokens_total", len(slot.tokens))
        self.retired += 1
        # co-resident rows at retirement — the window plane's
        # batched_with response key, kept so the schema does not
        # depend on which plane served the request
        batched_with = max(0, self.scheduler.busy_count() - 1)
        self._tok[slot.idx] = 0
        self._pos[slot.idx] = 0
        self._temp[slot.idx] = 0.0
        self.scheduler.retire(slot)
        slot.ticket.succeed({"tokens": list(slot.tokens),
                             "batched_with": batched_with,
                             "engine": "continuous"})

    def _abort_active(self, reason: str, code: int = 500,
                      retry_after: Optional[float] = None,
                      count_shed: bool = True) -> None:
        for slot in self.scheduler.active():
            if count_shed:
                inc("veles_shed_requests_total")
            self._tok[slot.idx] = 0
            self._pos[slot.idx] = 0
            self._temp[slot.idx] = 0.0
            self.scheduler.retire(slot)
            slot.ticket.fail(reason, code=code, retry_after=retry_after)

    # -- jitted programs -------------------------------------------------------
    def _program(self, kind: str, bucket: Optional[int] = None):
        key = (kind, bucket)
        prog = self._progs.get(key)
        if prog is None:
            prog = self._progs[key] = (
                self._build_prefill(bucket) if kind == "prefill"
                else self._build_decode())
        return prog

    def _build_prefill(self, bucket: int):
        """One program per bucket: pad-to-``bucket`` full-window pass
        through ``_block_prefill`` writing K/V into this slot's pool
        rows, plus the request's FIRST sampled token (from the last
        real position's logits) and its private PRNG carry."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()
        d = stem.dim

        @_count_decode_dispatches
        @functools.partial(jax.jit, donate_argnums=(6, 7))
        def prefill(params, ids, t_p, slot, temp, seed_key, keys,
                    caches):
            x = jnp.take(params[stem.name]["table"],
                         ids.astype(jnp.int32), axis=0, mode="clip")
            if pos_emb is not None:
                table = params[pos_emb.name]["table"]
                x = x + jnp.take(table, jnp.arange(ids.shape[-1]),
                                 axis=0, mode="clip")[None]
            new_caches = []
            for blk, (ck_pool, cv_pool) in zip(blocks, caches):
                bkv = getattr(blk, "n_kv_heads", blk.n_heads)
                hd = d // blk.n_heads
                ck = jnp.zeros((1, bucket, bkv, hd), x.dtype)
                cv = jnp.zeros((1, bucket, bkv, hd), x.dtype)
                x, ck, cv = _block_prefill(blk, params[blk.name], x,
                                           ck, cv)
                # pad rows land in the pool too; they are causal-masked
                # for every real position and the decode steps rewrite
                # position p before the read mask reaches it
                ck_pool = jax.lax.dynamic_update_slice(
                    ck_pool, ck, (slot, 0, 0, 0))
                cv_pool = jax.lax.dynamic_update_slice(
                    cv_pool, cv, (slot, 0, 0, 0))
                new_caches.append((ck_pool, cv_pool))
            x_last = jnp.take(x[0], t_p - 1, axis=0, mode="clip")
            logits = (jnp.dot(x_last, params[head.name]["weights"],
                              precision=prec)
                      + params[head.name]["bias"])
            k2 = jax.random.split(seed_key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            samp = jax.random.categorical(
                k2[1], logits / jnp.maximum(temp, _TEMP_EPS)
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, samp, greedy)
            keys = jax.lax.dynamic_update_slice(keys, k2[0][None],
                                                (slot, 0))
            return first, keys, tuple(new_caches)

        return prefill

    def _build_decode(self):
        """THE decode step: ``decode_block`` scan iterations of the
        vmapped single-row ``_block_step`` over every slot — one fixed
        shape, compiled exactly once. Per-row sampling draws from each
        slot's private key stream, so a row's noise is a pure function
        of its request's seed (id-exact vs solo decode whatever else
        rides the pool)."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()

        def embed_rows(params, tok, pos):
            x = jnp.take(params[stem.name]["table"],
                         tok.astype(jnp.int32), axis=0, mode="clip")
            if pos_emb is not None:
                x = x + jnp.take(params[pos_emb.name]["table"], pos,
                                 axis=0, mode="clip")
            return x                            # (S, D)

        @_count_decode_dispatches
        @functools.partial(jax.jit, donate_argnums=(4, 5))
        def step(params, tok, pos, temp, keys, caches):
            def body(carry, _):
                tok, pos, keys, caches = carry
                x = embed_rows(params, tok, pos)
                new_caches = []
                for blk, (ck, cv) in zip(blocks, caches):
                    p = params[blk.name]

                    def row(x_row, ck_row, cv_row, pos_row,
                            blk=blk, p=p):
                        y, ck2, cv2 = _block_step(
                            blk, p, x_row[None, None, :],
                            ck_row[None], cv_row[None], pos_row)
                        return y[0, 0], ck2[0], cv2[0]

                    x, ck, cv = jax.vmap(row)(x, ck, cv, pos)
                    new_caches.append((ck, cv))
                logits = (jnp.dot(x, params[head.name]["weights"],
                                  precision=prec)
                          + params[head.name]["bias"])   # (S, V)
                # _split_rows IS the id-exactness contract: the same
                # carry/subkey convention solo and batched generate use
                keys, subs = _split_rows(keys)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                samp = jax.vmap(jax.random.categorical)(
                    subs,
                    logits / jnp.maximum(temp, _TEMP_EPS)[:, None]
                ).astype(jnp.int32)
                nxt = jnp.where(temp > 0, samp, greedy)
                return (nxt, pos + 1, keys,
                        tuple(new_caches)), nxt

            (tok, pos, keys, caches), toks = jax.lax.scan(
                body, (tok, pos, keys, caches), None,
                length=self.decode_block)
            return toks, keys, caches            # toks (chunk, S)

        return step
