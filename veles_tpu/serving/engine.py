"""Continuous-batching decode engine: a persistent slot-pool KV cache
driven by ONE fixed-shape jitted decode step.

Replaces the window-coalescing serving model (one batched decode per
exact shape key, everyone rides to the longest member's ``n_new``)
with iteration-level scheduling:

- the KV cache is a ``max_slots``-row pool, every row padded to
  ``max_context`` — the decode step's shapes never change, so it
  compiles exactly once;
- prefill pads prompts to a small set of length ``buckets`` — the jit
  cache is bounded by ``len(buckets) + 1`` programs, not by distinct
  prompt lengths (right-padding is safe under the causal mask: pad
  K/V rows are invisible to real positions and are overwritten by the
  decode steps before the read mask ever reaches them);
- the scheduler admits queued requests into free slots at step
  boundaries and a row retires the moment it emits ``eos_id`` or
  reaches its own ``n_new`` — short requests never wait for long
  co-riders and the chip never idles while the queue is non-empty;
- each slot carries its own PRNG stream derived purely from the
  request's ``seed`` (``jax.random.fold_in``-style independence via
  per-row ``split`` streams), so a request's tokens are id-exact vs
  its solo decode whatever strangers share the batch — stochastic
  decodes batch on the same bar the greedy CI gate sets.

The per-block cache layout and math are ``nn/sampling.py``'s
``_block_prefill`` / ``_block_step`` — the decode step vmaps the very
same single-row step over the pool, so the engine cannot drift from
the scan decoder numerically.

Two optional planes ride the same programs (veles_tpu/quant/,
docs/services.md "Quantized serving"):

- **int8 weights** (``quant_weights``): the decode matmul weights are
  stored per-channel int8 and dequantized on read at the head of each
  program — XLA fuses the ``q·s`` into the consuming matmul, so the
  block math below the dequant is byte-for-byte the float engine's;
- **int8 KV cache** (``quant_kv``): the slot pool stores int8 rows
  with per-slot/-position f32 scales — half the pool HBM at the same
  ``max_slots``; each position is scaled once at write time, so there
  is no error accumulation across decode steps;
- **AOT artifact** (``artifact``): ``veles-tpu export serve-artifact``
  pre-exports every program via ``jax.export``; the engine
  deserializes them at :meth:`start`, so serving performs ZERO jit
  traces/compiles (``veles_compiles_total`` stays flat and
  ``veles_serving_compile_seconds_total`` reads 0). A corrupt or
  mismatched artifact falls back to live jit with a counted warning.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy

from ..error import VelesError
from ..logger import Logger
from ..nn.sampling import (_block_step, _count_decode_dispatches,
                           _embed_prompt, _head_logits,
                           _prefill_blocks, _split_rows, params_of,
                           split_stack)
from ..resilience import health
from ..resilience.faults import FaultInjected, fire as fire_fault
from ..telemetry.counters import inc
from ..telemetry.spans import span

#: floor for the temperature divisor inside the one shared decode
#: program (greedy rows carry temperature 0; their categorical lane is
#: computed-and-discarded, so the clamp only has to keep it finite)
_TEMP_EPS = 1e-3


def _same_leaves(a: Dict, b: Dict) -> bool:
    """True when two ``params_of`` trees carry IDENTICAL array objects.
    ``device_view()`` returns its cached jax array until a host-side
    update re-places it, so object identity is the cheap 'weights
    unchanged' test the quantization cache keys on."""
    if a.keys() != b.keys():
        return False
    for u in a:
        if a[u].keys() != b[u].keys():
            return False
        for k in a[u]:
            if a[u][k] is not b[u][k]:
                return False
    return True


def make_request(prompt, n_new, temperature=0.0, seed=0, eos_id=None
                 ) -> Dict:
    """Normalized request dict (the subset of GenerationAPI's parsed
    request the engine consumes) — for tests and bench harnesses."""
    return {"prompt": [int(t) for t in prompt], "n_new": int(n_new),
            "temperature": float(temperature), "seed": int(seed),
            "eos_id": eos_id}


class ContinuousEngine(Logger):
    """In-flight batching over a persistent KV-cache slot pool.

    ``wf`` is a generation-capable workflow (``Embedding`` →
    ``TransformerBlock``×N → ``LMHead``, validated at construction).
    ``decode_block`` fuses that many decode steps into one dispatch
    (``lax.scan``) — admission/retirement granularity stays one
    *chunk*; 1 keeps pure per-token scheduling, larger values amortize
    dispatch overhead on hosts where it dominates.
    """

    def __init__(self, wf, max_slots: int = 8,
                 buckets: Tuple[int, ...] = (16, 32, 64, 128),
                 max_context: int = 640, decode_block: int = 1,
                 quant_weights: Optional[bool] = None,
                 quant_kv: Optional[bool] = None,
                 artifact: Optional[str] = None,
                 name: str = "serving") -> None:
        super().__init__()
        from ..config import root
        from .scheduler import SlotScheduler
        self.wf = wf
        self.name = name
        # quantization policy (root.common.quant.*, CLI --quant-weights
        # /--quant-kv); both off = bit-identical to the float engine
        self.quant_weights = bool(
            root.common.quant.get("weights", False)
            if quant_weights is None else quant_weights)
        self.quant_kv = bool(
            root.common.quant.get("kv", False)
            if quant_kv is None else quant_kv)
        # AOT serving artifact (export/serve_artifact.py): loaded at
        # start(); empty/None = live jit
        self.artifact = str(
            root.common.serving.get("artifact", "")
            if artifact is None else (artifact or ""))
        self.artifact_mode = False
        #: live jit traces this engine paid for (0 in artifact mode)
        self.compiled_live = 0
        # raises VelesError on anything but a generation stack (a bare
        # workflow has no forwards at all — same rejection)
        self.stack = split_stack(list(getattr(wf, "forwards", ()) or ()))
        self.max_slots = int(max_slots)
        self.max_context = int(max_context)
        self.decode_block = max(1, int(decode_block))
        from . import parse_buckets
        self.buckets = parse_buckets(buckets)
        self.scheduler = SlotScheduler(self.max_slots, self.buckets,
                                       self.max_context)
        pos_emb = self.stack["pos_emb"]
        self._table_len = (None if pos_emb is None else
                           pos_emb.param_arrays()["table"].shape[0])
        self._progs: Dict = {}
        self._params = None
        self._quant_cache = None   # (float tree, its calibrated twin)
        self._caches = None
        self._keys = None
        self._tok = numpy.zeros(self.max_slots, numpy.int32)
        self._pos = numpy.zeros(self.max_slots, numpy.int32)
        self._temp = numpy.zeros(self.max_slots, numpy.float32)
        self._thread: Optional[threading.Thread] = None
        self._closing = False
        self.admitted = 0
        self.retired = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ContinuousEngine":
        if self._thread is not None:
            return self
        if self.artifact and not self.artifact_mode:
            self._load_artifact()
        self._closing = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name + ".engine")
        self._thread.start()
        from . import register_engine
        register_engine(self)
        self.info("%s: continuous batching up (slots=%d buckets=%s "
                  "max_context=%d decode_block=%d)", self.name,
                  self.max_slots, list(self.buckets), self.max_context,
                  self.decode_block)
        return self

    def stop(self) -> None:
        with self.scheduler.cv:
            self._closing = True
            self.scheduler.cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        self.scheduler.drain("server shutting down")
        self._abort_active("server shutting down", code=503,
                           retry_after=5.0, count_shed=False)
        from . import unregister_engine
        unregister_engine(self)

    # -- intake --------------------------------------------------------------
    def accepts(self, req: Dict) -> Optional[str]:
        """None when the slot pool can serve ``req``; otherwise the
        reason (caller falls back to the window-coalescing path)."""
        t_p, n_new = len(req["prompt"]), int(req["n_new"])
        if t_p < 1:
            return "empty prompt"
        reason = self.scheduler.reject_reason(t_p, n_new)
        if reason:
            return reason
        if self._table_len is not None and t_p + n_new > self._table_len:
            return ("generation to %d positions exceeds the trained "
                    "PositionalEmbedding table (%d rows)"
                    % (t_p + n_new, self._table_len))
        if 0 < float(req.get("temperature", 0.0)) < _TEMP_EPS:
            # the shared decode program clamps the divisor at
            # _TEMP_EPS; a colder-than-that request would sample from
            # different logits here than solo sampling.generate does —
            # route it to the window plane, which divides exactly
            return ("temperature %g below the engine's %g resolution"
                    % (req["temperature"], _TEMP_EPS))
        bucket = self.scheduler.bucket_for(t_p)
        if self._kernel_straddle(t_p, bucket):
            # padding to the bucket would flip attention_core's
            # flash/reference choice vs the exact-length solo prefill
            # (choose_flash is length-gated) — different kernels drift
            # in the last bits and break the id-exactness contract, so
            # such a prompt rides the window plane instead
            return ("prompt %d pads to bucket %d across the "
                    "flash-attention crossover" % (t_p, bucket))
        return None

    def _kernel_straddle(self, t_p: int, bucket: int) -> bool:
        """True when any block's attention would pick a different
        kernel for the padded bucket length than for the exact prompt
        length (see ``ops.flash_attention.choose_flash``)."""
        if t_p == bucket:
            return False
        from ..ops.flash_attention import choose_flash
        d = self.stack["stem"].dim
        for blk in self.stack["blocks"]:
            hd = d // blk.n_heads
            if choose_flash(bucket, hd) != choose_flash(t_p, hd):
                return True
        return False

    def submit(self, req: Dict, ticket,
               max_queue: Optional[int] = None,
               checked: bool = False) -> bool:
        """Enqueue one request; False = queue bound hit (caller
        sheds). ``ticket`` follows the :class:`scheduler.Ticket`
        protocol (``fail`` / ``succeed`` / ``deadline``).
        ``checked=True`` skips :meth:`accepts` — for callers that just
        routed on its verdict."""
        if not checked:
            reason = self.accepts(req)
            if reason is not None:
                # direct submits (no API-side accepts() pre-check) get
                # a clean client-fault answer instead of a 500 at
                # admission
                ticket.fail(reason, code=400)
                return True
        # the closing check and the enqueue share the scheduler's
        # condition (an RLock): stop() flips _closing under the same
        # lock before draining, so a ticket can never slip into the
        # queue after the drain and strand its handler until 504
        with self.scheduler.cv:
            if self._closing:
                return False
            return self.scheduler.push(req, ticket, max_queue)

    def serve(self, reqs: List[Dict], timeout: float = 300.0
              ) -> List[List[int]]:
        """Synchronous convenience (tests / bench): submit every
        request, wait, return each token list; raises on any error."""
        from .scheduler import Ticket
        tickets = [Ticket() for _ in reqs]
        for req, ticket in zip(reqs, tickets):
            if not self.submit(req, ticket):
                raise VelesError("serving queue full")
        out = []
        for req, ticket in zip(reqs, tickets):
            if not ticket.event.wait(timeout):
                raise VelesError("serving timed out for %r" % (req,))
            if ticket.error is not None:
                raise VelesError("serving failed: %s" % ticket.error)
            out.append(ticket.result["tokens"])
        return out

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        from ..quant import pool_nbytes
        return {
            "slots": self.max_slots,
            "slots_busy": self.scheduler.busy_count(),
            "queue_depth": self.scheduler.queue_depth(),
            "admitted": self.admitted,
            "retired": self.retired,
            "programs": len(self._progs),
            # quantization/AOT plane (veles_tpu/quant/): what the
            # /metrics mode gauges render on both surfaces
            "artifact_mode": int(self.artifact_mode),
            "quant_weights": int(self.quant_weights),
            "quant_kv": int(self.quant_kv),
            "compiled_live": self.compiled_live,
            "kv_pool_bytes": pool_nbytes(self._caches),
        }

    @property
    def closing(self) -> bool:
        """True once :meth:`stop` has begun — :meth:`submit` returns
        False for a closing engine too, and the caller's shed answer
        should say shutdown, not queue-full."""
        return self._closing

    @property
    def programs_built(self) -> int:
        """Jitted programs this engine ever built — bounded by
        ``len(buckets) + 1`` (the bucketed prefills + the one decode
        step), never by distinct prompt lengths."""
        return len(self._progs)

    # -- worker --------------------------------------------------------------
    def _loop(self) -> None:
        hb = "serving.%s" % self.name
        fail_streak = 0
        try:
            while True:
                with self.scheduler.cv:
                    while (not self.scheduler._queue
                           and self.scheduler.busy_count() == 0
                           and not self._closing):
                        self.scheduler.cv.wait(timeout=5.0)
                        if not self._closing:
                            health.heartbeats.beat(hb)
                    if self._closing:
                        return
                health.heartbeats.beat(hb)
                try:
                    self._tick()
                    fail_streak = 0
                except Exception:     # noqa: BLE001 — serve, don't die
                    fail_streak += 1
                    self.exception("%s: serving tick failed", self.name)
                    self._abort_active("internal serving error",
                                       code=500, count_shed=False)
                    # donated buffers may be gone — rebuild lazily
                    self._caches = self._keys = self._params = None
                    # a tick that dies before take_admissions never
                    # reaches the deadline check there: sweep the queue
                    # so waiting callers still get their 503 instead of
                    # hanging to full timeout, and back off instead of
                    # busy-spinning while the failure persists
                    from .scheduler import shed_expired
                    shed_expired(self.scheduler.expire_queued())
                    if not self._closing:
                        time.sleep(min(1.0, 0.05 * (2 ** fail_streak)))
        finally:
            health.heartbeats.unregister(hb)

    def _tick(self) -> None:
        """One step boundary: admit into free slots, then run one
        decode chunk over the pool."""
        # the param device-view walk (per-array locks) is too heavy to
        # repeat per decode chunk, but a snapshot held forever would
        # serve stale weights after a host-side update. Middle ground:
        # re-read whenever the pool is IDLE (no in-flight rows) — a
        # param change lands at the next burst boundary, no request
        # ever decodes on torn half-old/half-new weights, and under
        # sustained load the walk is never on the per-token path
        # (weights are frozen while serving, as everywhere in serving).
        params = self._params
        if params is None or self.scheduler.busy_count() == 0:
            params = self._params = self._prepare_params()
        self._ensure_pool(params)
        from .scheduler import shed_expired
        admissions, expired = self.scheduler.take_admissions()
        shed_expired(expired)
        for slot in admissions:
            try:
                self._admit(params, slot)
            except Exception as e:    # noqa: BLE001 — answer, don't die
                self.scheduler.retire(slot)
                slot.ticket.fail("%s: %s" % (type(e).__name__, e),
                                 code=500)
                # the prefill program DONATES the pool: a dispatch
                # that died may have consumed the co-tenants' caches
                # with it, and there is no cheap way to tell. Shed the
                # in-flight rows (503 + Retry-After) and rebuild the
                # pool rather than decode on possibly-dead buffers.
                self.exception("%s: admission failed; resetting the "
                               "slot pool", self.name)
                self._abort_active("serving pool reset after a failed "
                                   "admission", code=503,
                                   retry_after=1.0)
                self._caches = self._keys = self._params = None
                return
        if self.scheduler.busy_count():
            try:
                self._decode(params)
            except FaultInjected as e:
                # an injected decode fault DEGRADES: in-flight rows are
                # shed with Retry-After, the pool stays consistent (the
                # fault fires before the dispatch)
                self._abort_active(str(e), code=503, retry_after=1.0)

    def _prepare_params(self) -> Dict:
        """Fresh device-side params for the serving programs: the
        float tree, or its per-channel int8 twin under
        ``quant_weights``. Calibration is NOT repeated per idle
        boundary: ``device_view()`` returns the cached jax array until
        a host-side update re-places it, so leaf identity against the
        last-calibrated tree tells exactly when the weights actually
        changed — unchanged weights reuse the quantized twin (a
        one-request-at-a-time load would otherwise pay a full amax
        scan per request that the float engine does not), updated
        weights get fresh scales at the next burst boundary."""
        params = params_of(self.wf)
        if not self.quant_weights:
            return params
        cached = self._quant_cache
        if cached is not None and _same_leaves(cached[0], params):
            return cached[1]
        from ..quant import quantize_params
        qparams, _report = quantize_params(params)
        self._quant_cache = (params, qparams)
        return qparams

    def _ensure_pool(self, params) -> None:
        if self._caches is not None:
            return
        import jax.numpy as jnp
        from ..quant import block_pool
        stem, blocks = self.stack["stem"], self.stack["blocks"]
        dtype = self._pool_dtype(params)
        d = stem.dim
        caches = []
        for blk in blocks:
            bkv = getattr(blk, "n_kv_heads", blk.n_heads)
            hd = d // blk.n_heads
            caches.append(block_pool(self.max_slots, self.max_context,
                                     bkv, hd, dtype, self.quant_kv))
        self._caches = tuple(caches)
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)

    def _pool_dtype(self, params):
        """Float dtype of the activation path (the stem table's —
        also under quant_weights, which never touches ``table``)."""
        stem = self.stack["stem"]
        return params[stem.name]["table"].dtype

    # -- admission ------------------------------------------------------------
    def _admit(self, params, slot) -> None:
        import jax
        import jax.numpy as jnp
        t_p, bucket = slot.t_p, slot.bucket
        ids = numpy.zeros((1, bucket), numpy.int32)
        ids[0, :t_p] = slot.req["prompt"]
        prog = self._program("prefill", bucket)
        seed_key = jax.random.PRNGKey(int(slot.req.get("seed", 0)))
        wait = max(0.0, time.time() - slot.ticket.enqueued)
        with span("serving.prefill", bucket=bucket, slot=slot.idx,
                  t_p=t_p):
            first, self._keys, self._caches = prog(
                params, jnp.asarray(ids), numpy.int32(t_p),
                numpy.int32(slot.idx), numpy.float32(slot.temperature),
                seed_key, self._keys, self._caches)
            first = int(first)
        inc("veles_serving_prefill_dispatches_total")
        inc("veles_serving_admitted_total")
        inc("veles_serving_queue_wait_seconds_total", wait)
        self.admitted += 1
        self._tok[slot.idx] = first
        self._pos[slot.idx] = t_p
        self._temp[slot.idx] = slot.temperature
        if slot.record(first):
            self._finish(slot)

    # -- the decode chunk ------------------------------------------------------
    def _decode(self, params) -> None:
        import jax.numpy as jnp
        active = self.scheduler.active()
        fire_fault("serve.decode_step")
        with span("serving.decode_step", active=len(active),
                  chunk=self.decode_block):
            toks, self._keys, self._caches = self._program("step")(
                params, jnp.asarray(self._tok), jnp.asarray(self._pos),
                jnp.asarray(self._temp), self._keys, self._caches)
            toks = numpy.asarray(toks)          # (decode_block, S)
        inc("veles_serving_decode_dispatches_total")
        finished: List = []
        for h in range(toks.shape[0]):
            still = [s for s in active if s not in finished]
            if not still:
                break
            for slot in still:
                token = int(toks[h, slot.idx])
                self._tok[slot.idx] = token
                self._pos[slot.idx] += 1
                if slot.record(token):
                    finished.append(slot)
        for slot in finished:
            self._finish(slot)

    def _finish(self, slot) -> None:
        """Retire a row the moment it is done: free the slot (the next
        admission reuses it immediately) and answer the ticket."""
        inc("veles_serving_retired_total")
        inc("veles_serving_tokens_total", len(slot.tokens))
        self.retired += 1
        # co-resident rows at retirement — the window plane's
        # batched_with response key, kept so the schema does not
        # depend on which plane served the request
        batched_with = max(0, self.scheduler.busy_count() - 1)
        self._tok[slot.idx] = 0
        self._pos[slot.idx] = 0
        self._temp[slot.idx] = 0.0
        self.scheduler.retire(slot)
        slot.ticket.succeed({"tokens": list(slot.tokens),
                             "batched_with": batched_with,
                             "engine": "continuous"})

    def _abort_active(self, reason: str, code: int = 500,
                      retry_after: Optional[float] = None,
                      count_shed: bool = True) -> None:
        for slot in self.scheduler.active():
            if count_shed:
                inc("veles_shed_requests_total")
            self._tok[slot.idx] = 0
            self._pos[slot.idx] = 0
            self._temp[slot.idx] = 0.0
            self.scheduler.retire(slot)
            slot.ticket.fail(reason, code=code, retry_after=retry_after)

    # -- jitted programs -------------------------------------------------------
    def _program(self, kind: str, bucket: Optional[int] = None):
        key = (kind, bucket)
        prog = self._progs.get(key)
        if prog is None:
            # in artifact mode every program was installed at start();
            # reaching here means a bucket the artifact does not carry
            # — impossible once geometry validated, but a live build
            # is still the correct degradation
            jitted = (self._build_prefill(bucket) if kind == "prefill"
                      else self._build_decode())
            prog = self._progs[key] = self._instrument_live(jitted)
        return prog

    def _instrument_live(self, jitted):
        """Wrap a live jitted program: every call counts one
        ``veles_decode_dispatches_total`` (the round-5 regression
        lock's counter — same contract as
        ``sampling._count_decode_dispatches``). The first call
        explicitly lowers+compiles (``jit.lower(...).compile()``, the
        ``accelerated.cost_of`` pattern) and installs the compiled
        executable for every later dispatch, so
        ``veles_serving_compile_seconds_total`` brackets ONLY the
        trace+compile — the cold-start cost the AOT artifact path
        exists to delete — never the first dispatch's execution.
        Engine programs are fixed-shape, so one compile per program is
        exact, not a heuristic."""
        box: Dict[str, object] = {}

        def dispatch(*args):
            inc("veles_decode_dispatches_total")
            exe = box.get("exe")
            if exe is None:
                try:
                    t0 = time.time()
                    exe = jitted.lower(*args).compile()
                except AttributeError:      # non-pjit backends
                    exe = jitted
                else:
                    self.compiled_live += 1
                    inc("veles_compiles_total")
                    inc("veles_serving_compile_seconds_total",
                        time.time() - t0)
                box["exe"] = exe
            return exe(*args)

        dispatch._jitted = jitted
        return dispatch

    # -- AOT artifact (export/serve_artifact.py) ------------------------------
    def stack_signature(self) -> Dict:
        """Geometry the exported programs are shape-committed to: the
        abstract spec of (params tree, pool) plus every serving knob.
        Export stamps it into the artifact; load refuses on any
        mismatch — a program traced for different shapes would fail
        deep inside XLA with an opaque error (or worse, run on
        reinterpreted buffers). Purely abstract: under
        ``quant_weights`` the int8 spec comes from
        ``quantize_params_spec``, so building a signature never runs
        (or counts) a calibration pass."""
        import jax

        def spec(tree):
            return jax.tree_util.tree_map(
                lambda a: [list(a.shape), str(a.dtype)], tree)

        params = params_of(self.wf)
        if self.quant_weights:
            from ..quant import quantize_params_spec
            sig_params = quantize_params_spec(params)
        else:
            sig_params = params
        stem, blocks = self.stack["stem"], self.stack["blocks"]
        d = stem.dim
        pools = []
        for blk in blocks:
            bkv = getattr(blk, "n_kv_heads", blk.n_heads)
            pools.append([bkv, d // blk.n_heads])
        return {
            "params": spec(sig_params),
            "pools": pools,
            "pool_dtype": str(self._pool_dtype(params)),
            "max_slots": self.max_slots,
            "buckets": list(self.buckets),
            "max_context": self.max_context,
            "decode_block": self.decode_block,
            "quant_weights": bool(self.quant_weights),
            "quant_kv": bool(self.quant_kv),
        }

    def _load_artifact(self) -> bool:
        """Install the artifact's pre-exported programs into
        ``_progs``. Any failure — unreadable package, version/geometry
        mismatch, corrupt program bytes, injected ``artifact.load``
        fault — logs a counted warning and leaves the engine on live
        jit: a bad artifact degrades startup latency, never
        availability."""
        from ..export.serve_artifact import load_serve_programs
        try:
            fire_fault("artifact.load")
            programs = load_serve_programs(self.artifact,
                                           self.stack_signature())
        except Exception as e:      # noqa: BLE001 — degrade, don't die
            inc("veles_artifact_load_failures_total")
            self.warning(
                "%s: serve-artifact %s unusable (%s: %s); serving via "
                "live jit", self.name, self.artifact,
                type(e).__name__, e)
            return False
        for key, call in programs.items():
            self._progs[key] = _count_decode_dispatches(call)
        self.artifact_mode = True
        inc("veles_artifact_loads_total")
        self.info("%s: AOT artifact loaded from %s (%d programs; zero "
                  "jit compiles on the serving path)", self.name,
                  self.artifact, len(programs))
        return True

    def _build_prefill(self, bucket: int):
        """One program per bucket: pad-to-``bucket`` full-window pass
        through ``_block_prefill`` writing K/V into this slot's pool
        rows, plus the request's FIRST sampled token (from the last
        real position's logits) and its private PRNG carry. Under
        ``quant_weights`` the program takes the int8 parameter tree and
        dequantizes at its head (XLA fuses the ``q·s`` into each
        consuming matmul); under ``quant_kv`` the computed float rows
        are quantized once — per-position scales — before the pool
        write."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()
        d = stem.dim
        quant_w, quant_kv = self.quant_weights, self.quant_kv

        @functools.partial(jax.jit, donate_argnums=(6, 7))
        def prefill(params, ids, t_p, slot, temp, seed_key, keys,
                    caches):
            if quant_w:
                # reconstruct in the model's own float dtype (the
                # never-quantized stem table's — read at trace time),
                # not a hard f32: a bf16 model's quantized engine must
                # run the same-dtype matmuls the float engine does
                from ..quant import dequantize_params
                params = dequantize_params(
                    params, dtype=params[stem.name]["table"].dtype)
            x = _embed_prompt(stem, pos_emb, params, ids)
            x, blk_caches = _prefill_blocks(blocks, params, x,
                                            bucket, d)
            new_caches = []
            for (ck, cv), pool in zip(blk_caches, caches):
                # pad rows land in the pool too; they are causal-masked
                # for every real position and the decode steps rewrite
                # position p before the read mask reaches it
                if quant_kv:
                    from ..quant import quantize_rows_int8
                    ckq_pool, cvq_pool, ks_pool, vs_pool = pool
                    qk, sk = quantize_rows_int8(ck)
                    qv, sv = quantize_rows_int8(cv)
                    new_caches.append((
                        jax.lax.dynamic_update_slice(
                            ckq_pool, qk, (slot, 0, 0, 0)),
                        jax.lax.dynamic_update_slice(
                            cvq_pool, qv, (slot, 0, 0, 0)),
                        jax.lax.dynamic_update_slice(
                            ks_pool, sk, (slot, 0)),
                        jax.lax.dynamic_update_slice(
                            vs_pool, sv, (slot, 0))))
                else:
                    ck_pool, cv_pool = pool
                    new_caches.append((
                        jax.lax.dynamic_update_slice(
                            ck_pool, ck, (slot, 0, 0, 0)),
                        jax.lax.dynamic_update_slice(
                            cv_pool, cv, (slot, 0, 0, 0))))
            x_last = jnp.take(x[0], t_p - 1, axis=0, mode="clip")
            logits = _head_logits(head, params, x_last, prec)
            k2 = jax.random.split(seed_key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            samp = jax.random.categorical(
                k2[1], logits / jnp.maximum(temp, _TEMP_EPS)
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, samp, greedy)
            keys = jax.lax.dynamic_update_slice(keys, k2[0][None],
                                                (slot, 0))
            return first, keys, tuple(new_caches)

        return prefill

    def _build_decode(self):
        """THE decode step: ``decode_block`` scan iterations of the
        vmapped single-row ``_block_step`` over every slot — one fixed
        shape, compiled exactly once. Per-row sampling draws from each
        slot's private key stream, so a row's noise is a pure function
        of its request's seed (id-exact vs solo decode whatever else
        rides the pool). Under ``quant_kv`` each row dequantizes its
        int8 cache for the attention read, runs the SAME
        ``_block_step``, then quantizes only the one newly written
        position with its own fresh scale — previously written rows
        are never re-scaled, so there is no error accumulation across
        steps."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()
        quant_w, quant_kv = self.quant_weights, self.quant_kv

        def embed_rows(params, tok, pos):
            x = jnp.take(params[stem.name]["table"],
                         tok.astype(jnp.int32), axis=0, mode="clip")
            if pos_emb is not None:
                x = x + jnp.take(params[pos_emb.name]["table"], pos,
                                 axis=0, mode="clip")
            return x                            # (S, D)

        @functools.partial(jax.jit, donate_argnums=(4, 5))
        def step(params, tok, pos, temp, keys, caches):
            if quant_w:
                from ..quant import dequantize_params
                params = dequantize_params(
                    params, dtype=params[stem.name]["table"].dtype)

            def body(carry, _):
                tok, pos, keys, caches = carry
                x = embed_rows(params, tok, pos)
                new_caches = []
                for blk, pool in zip(blocks, caches):
                    p = params[blk.name]

                    if quant_kv:
                        from ..quant import (dequantize_rows_int8,
                                             quantize_rows_int8)

                        def rowq(x_row, ckq_row, cvq_row, ks_row,
                                 vs_row, pos_row, blk=blk, p=p):
                            ck_row = dequantize_rows_int8(
                                ckq_row, ks_row, dtype=x_row.dtype)
                            cv_row = dequantize_rows_int8(
                                cvq_row, vs_row, dtype=x_row.dtype)
                            y, ck2, cv2 = _block_step(
                                blk, p, x_row[None, None, :],
                                ck_row[None], cv_row[None], pos_row)
                            # quantize ONLY the newly written position
                            k_new = jnp.take(ck2[0], pos_row, axis=0,
                                             mode="clip")
                            v_new = jnp.take(cv2[0], pos_row, axis=0,
                                             mode="clip")
                            qk, sk = quantize_rows_int8(k_new[None])
                            qv, sv = quantize_rows_int8(v_new[None])
                            return (y[0, 0],
                                    jax.lax.dynamic_update_slice(
                                        ckq_row, qk, (pos_row, 0, 0)),
                                    jax.lax.dynamic_update_slice(
                                        cvq_row, qv, (pos_row, 0, 0)),
                                    jax.lax.dynamic_update_slice(
                                        ks_row, sk, (pos_row,)),
                                    jax.lax.dynamic_update_slice(
                                        vs_row, sv, (pos_row,)))

                        ckq, cvq, ks, vs = pool
                        x, ckq, cvq, ks, vs = jax.vmap(rowq)(
                            x, ckq, cvq, ks, vs, pos)
                        new_caches.append((ckq, cvq, ks, vs))
                        continue

                    def row(x_row, ck_row, cv_row, pos_row,
                            blk=blk, p=p):
                        y, ck2, cv2 = _block_step(
                            blk, p, x_row[None, None, :],
                            ck_row[None], cv_row[None], pos_row)
                        return y[0, 0], ck2[0], cv2[0]

                    ck, cv = pool
                    x, ck, cv = jax.vmap(row)(x, ck, cv, pos)
                    new_caches.append((ck, cv))
                logits = _head_logits(head, params, x, prec)  # (S, V)
                # _split_rows IS the id-exactness contract: the same
                # carry/subkey convention solo and batched generate use
                keys, subs = _split_rows(keys)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                samp = jax.vmap(jax.random.categorical)(
                    subs,
                    logits / jnp.maximum(temp, _TEMP_EPS)[:, None]
                ).astype(jnp.int32)
                nxt = jnp.where(temp > 0, samp, greedy)
                return (nxt, pos + 1, keys,
                        tuple(new_caches)), nxt

            (tok, pos, keys, caches), toks = jax.lax.scan(
                body, (tok, pos, keys, caches), None,
                length=self.decode_block)
            return toks, keys, caches            # toks (chunk, S)

        return step
