"""Continuous-batching decode engine: a paged slot-pool KV cache
driven by a bounded set of fixed-shape jitted programs.

Replaces the window-coalescing serving model (one batched decode per
exact shape key, everyone rides to the longest member's ``n_new``)
with iteration-level scheduling over a PAGED KV cache (the
block-table formulation of PAPERS.md's "Compiler-First State Space
Duality and Portable O(1) Autoregressive Caching for Inference"):

- K/V live in a global pool of fixed-size PAGES (``page_size``
  positions each, a multiple of ``decode_block``); every slot owns a
  page-table row — an int32 index array — and the jitted programs
  gather a slot's logical ``max_context`` cache view through it.
  Pool HBM is ``pages x page_size``, NOT ``max_slots x max_context``:
  concurrency is bounded by pages actually reserved, so the same HBM
  sustains roughly ``max_context / mean(prompt + n_new)`` times more
  concurrent slots than the dense pool it replaces;
- admission RESERVES each request's own worst case —
  ``ceil(max(bucket, prompt + n_new [+ gamma + 1]) / page_size)``
  pages per row, never ``max_context`` — and frees them the moment
  the row retires. Reserving up front makes page exhaustion
  impossible mid-decode in normal operation (the head request waits,
  FIFO kept, when the allocator cannot hold it); per-tick growth
  (:meth:`SlotScheduler.grow` via ``_grow_or_shed``) is the
  accounting safety net, and a row it cannot cover — or an injected
  ``serve.page_alloc`` fault — is shed 503 + Retry-After while
  everyone else keeps decoding;
- the decode step's shapes never change — page tables are data, not
  shape — so it still compiles exactly once; prefill pads prompts to
  a small set of length ``buckets``, so the greedy/sample plane holds
  ``len(buckets) + 1`` programs, never one per prompt length;
- ALL decode modes ride the pool: ``speculative`` rows advance by
  on-device draft/verify rounds (a second fixed-shape program sharing
  the page tables; the draft model's K/V pages ride the same
  allocator) and ``beam`` requests occupy ``beam_width`` hypothesis
  rows advanced by a fixed-shape group top-k step whose cache reorder
  is a page-granular copy. Each mode adds a bounded constant to the
  program count (:meth:`ContinuousEngine.programs_bound`);
- each slot carries its own PRNG stream derived purely from the
  request's ``seed``, so a request's tokens are id-exact vs its solo
  decode whatever strangers share the batch — greedy, sampled,
  speculative and beam rows co-tenant in one pool without changing
  each other's answers.

The per-block cache math is ``nn/sampling.py``'s ``_block_prefill`` /
``_block_step`` (and ``nn/speculative.py``'s ``_block_span`` for the
verify window) applied to the gathered page view — positions beyond a
row's pages are causal-masked to exact zeros, so the paged programs
cannot drift numerically from the dense formulation or the scan
decoder.

Two optional planes ride the same programs (veles_tpu/quant/,
docs/services.md "Quantized serving"):

- **int8 weights** (``quant_weights``): decode matmul weights stored
  per-channel int8, dequantized at the head of each program;
- **int8 KV cache** (``quant_kv``): the page pool stores int8 payloads
  with per-page f32 scale sidecars — half the pool HBM at the same
  page count. Speculative/beam requests ride the window plane when the
  pool is int8 (their round/step programs are float-pool only);
- **AOT artifact** (``artifact``): pre-exported prefill/decode
  programs loaded at :meth:`start` — zero jit compiles on the
  greedy/sample path. Spec/beam programs always build live (counted).
  A corrupt or mismatched artifact falls back to live jit with a
  counted warning.

The heavy-traffic request plane (docs/services.md "Prefix sharing &
streaming") adds three latency features on top, all greedy/sample +
float-pool only:

- **prefix sharing** (``prefix_cache``): a radix-tree index over
  ``page_size``-token blocks (:class:`~veles_tpu.serving.pages.
  PrefixCache`) maps shared prompt prefixes to refcounted pages;
  admission adopts matched pages READ-ONLY into the new slot's page
  table (pages are data, so THE decode step still compiles once) and
  prefills only the unmatched suffix — a shared system prompt costs
  its pages and its prefill FLOPs once across the whole pool. The
  first write that must land inside a shared page (a full-prompt
  match re-computing its last position) copies that page first
  (copy-on-write, counted); the decode step's write-back masks every
  shared page to the sink, so a writer can never mutate one. LRU
  leaves evict under allocator pressure;
- **chunked prefill** (``prefill_chunk``): long admissions prefill in
  fixed-size chunks co-scheduled with the decode tick — one chunk per
  tick per admitting row — instead of one monolithic bucketed pass,
  so a long admission stops stalling in-flight decodes (the
  ``prefill_stall`` gauge measures the residual per-tick stall). The
  chunk program reproduces ``attention_reference``'s exact arithmetic
  over the gathered page view, so chunked (and prefix-matched) rows
  stay bit-identical to the monolithic path;
- **token streaming**: rows whose ticket carries ``stream=True`` push
  emitted tokens at every step boundary (``Ticket.push_tokens``);
  the GenerationAPI drains them onto the wire as SSE events, so TTFT
  becomes a client-visible measurement.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy

from ..error import VelesError
from ..logger import Logger
from ..nn.sampling import (_block_step, _count_decode_dispatches,
                           _embed_prompt, _head_logits,
                           _prefill_blocks, _split_rows, params_of,
                           split_stack)
from ..resilience import health
from ..resilience.faults import FaultInjected, fire as fire_fault
from ..telemetry.counters import inc
from ..telemetry.spans import span

#: floor for the temperature divisor inside the one shared decode
#: program (greedy rows carry temperature 0; their categorical lane is
#: computed-and-discarded, so the clamp only has to keep it finite)
_TEMP_EPS = 1e-3

#: slot modes the plain decode step advances — also the only modes
#: that RESUME (scheduler.RESUME_MODES is the single source: their
#: per-slot PRNG stream advances exactly one split per emitted token,
#: so a retry can re-enter the stream mid-decode)
from .scheduler import RESUME_MODES as _STEP_MODES  # noqa: E402

#: jitted split-chain advance (built on first use): a 900-token
#: resume must cost ONE dispatch on the tick thread, not 900
#: host-loop split round-trips stalling every co-tenant decode
_advance_key_jit = None


def advanced_prng_key(seed: int, steps: int):
    """The per-slot PRNG carry after ``steps`` emitted tokens: every
    emitted token consumed exactly one ``jax.random.split`` of the
    slot's stream (``_split_rows`` batched, ``split(seed_key)`` at
    prefill — same carry-in-[0] convention), so the carry is a pure
    function of ``(seed, tokens emitted)``. A resumed prefill seeded
    with this key samples its first token from the SAME subkey the
    uninterrupted run would have used at that position — the
    token-level failover resume's id-exactness hinges on this one
    function. Computed as one jitted ``fori_loop`` dispatch (steps is
    a traced argument, so every resume depth shares one program)."""
    import jax
    key = jax.random.PRNGKey(int(seed))
    steps = int(steps)
    if steps <= 0:
        return key
    global _advance_key_jit
    if _advance_key_jit is None:
        import jax.numpy as jnp

        def advance(k, n):
            return jax.lax.fori_loop(
                0, n, lambda _i, kk: jax.random.split(kk)[0], k)

        _advance_key_jit = (jax.jit(advance), jnp)
    fn, jnp = _advance_key_jit
    return fn(key, jnp.int32(steps))


def _same_leaves(a: Dict, b: Dict) -> bool:
    """True when two ``params_of`` trees carry IDENTICAL array objects.
    ``device_view()`` returns its cached jax array until a host-side
    update re-places it, so object identity is the cheap 'weights
    unchanged' test the quantization cache keys on. An in-place device
    mutation that reuses the same ``jax.Array`` is invisible to this
    test — such mutators must call
    :meth:`ContinuousEngine.invalidate_quant_cache`."""
    if a.keys() != b.keys():
        return False
    for u in a:
        if a[u].keys() != b[u].keys():
            return False
        for k in a[u]:
            if a[u][k] is not b[u][k]:
                return False
    return True


def make_request(prompt, n_new, temperature=0.0, seed=0, eos_id=None,
                 mode="greedy", gamma=4, beam=4) -> Dict:
    """Normalized request dict (the subset of GenerationAPI's parsed
    request the engine consumes) — for tests and bench harnesses."""
    return {"prompt": [int(t) for t in prompt], "n_new": int(n_new),
            "temperature": float(temperature), "seed": int(seed),
            "eos_id": eos_id, "mode": str(mode), "gamma": int(gamma),
            "beam": int(beam)}


def fold_resume(req: Dict, resume_tokens) -> Dict:
    """Fold a failover retry's already-emitted tokens into an engine
    request: they become prompt suffix (the resumed prefill
    re-prefills them — one bucketed pass, never a re-decode),
    ``n_new`` drops to the REMAINING budget, and ``resume_k`` records
    how many stream positions the per-slot PRNG must advance before
    the first new token. ``req`` is the ORIGINAL request (full
    ``n_new``); the wire form a router sends — ``resume_tokens`` +
    remaining ``n_new`` — is what GenerationAPI's parse folds the
    same way."""
    resume = [int(t) for t in resume_tokens]
    if not resume:
        return dict(req, resume_k=0)
    remaining = int(req["n_new"]) - len(resume)
    if remaining < 1:
        raise ValueError(
            "resume_tokens (%d) leave no remaining n_new (%d)"
            % (len(resume), int(req["n_new"])))
    return dict(req,
                prompt=[int(t) for t in req["prompt"]] + resume,
                n_new=remaining, resume_k=len(resume))


class ContinuousEngine(Logger):
    """In-flight batching over a persistent paged KV-cache pool.

    ``wf`` is a generation-capable workflow (``Embedding`` →
    ``TransformerBlock``×N → ``LMHead``, validated at construction);
    ``draft`` is an optional smaller workflow of the same shape that
    enables ``mode=speculative`` on the pool. ``decode_block`` fuses
    that many decode steps into one dispatch (``lax.scan``);
    ``page_size`` must be a positive multiple of it so a chunk never
    outruns its growth check by more than one page.
    """

    def __init__(self, wf, max_slots: int = 8,
                 buckets: Tuple[int, ...] = (16, 32, 64, 128),
                 max_context: int = 640, decode_block: int = 1,
                 page_size: Optional[int] = None,
                 pages: Optional[int] = None,
                 spec_gamma: Optional[int] = None,
                 beam_width: Optional[int] = None,
                 draft=None,
                 quant_weights: Optional[bool] = None,
                 quant_kv: Optional[bool] = None,
                 artifact: Optional[str] = None,
                 prefix_cache: Optional[bool] = None,
                 prefill_chunk: Optional[int] = None,
                 tp: Optional[int] = None,
                 mesh=None,
                 name: str = "serving") -> None:
        super().__init__()
        from ..config import root
        from .pages import PagePool, PrefixCache, pages_for
        from .scheduler import SlotScheduler
        self.wf = wf
        self.name = name
        # quantization policy (root.common.quant.*, CLI --quant-weights
        # /--quant-kv); both off = bit-identical to the float engine
        self.quant_weights = bool(
            root.common.quant.get("weights", False)
            if quant_weights is None else quant_weights)
        self.quant_kv = bool(
            root.common.quant.get("kv", False)
            if quant_kv is None else quant_kv)
        # AOT serving artifact (export/serve_artifact.py): loaded at
        # start(); empty/None = live jit
        self.artifact = str(
            root.common.serving.get("artifact", "")
            if artifact is None else (artifact or ""))
        self.artifact_mode = False
        #: live jit traces this engine paid for (0 in artifact mode)
        self.compiled_live = 0
        # raises VelesError on anything but a generation stack (a bare
        # workflow has no forwards at all — same rejection)
        self.stack = split_stack(list(getattr(wf, "forwards", ()) or ()))
        self.max_slots = int(max_slots)
        self.max_context = int(max_context)
        self.decode_block = max(1, int(decode_block))
        serving_cfg = root.common.serving
        self.page_size = int(
            serving_cfg.get("page_size", 16)
            if page_size is None else page_size)
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        if self.page_size % self.decode_block:
            raise ValueError(
                "page_size %d must be a multiple of decode_block %d "
                "(a decode chunk may never outrun its page-growth "
                "check by more than one page)"
                % (self.page_size, self.decode_block))
        #: page-table entries per slot; the gathered view length is
        #: pages_per_slot * page_size >= max_context
        self.pages_per_slot = pages_for(self.max_context, self.page_size)
        cfg_pages = serving_cfg.get("pages", None) \
            if pages is None else pages
        #: usable pages; default = dense-equivalent capacity (every
        #: slot can hold max_context), which operators SHRINK to trade
        #: worst-case context reservation for more concurrent slots
        self.pages = int(self.max_slots * self.pages_per_slot
                         if cfg_pages in (None, 0) else cfg_pages)
        if self.pages < 1:
            raise ValueError("pages must be >= 1")
        self.spec_gamma = int(
            serving_cfg.get("spec_gamma", 4)
            if spec_gamma is None else spec_gamma)
        if self.spec_gamma < 1:
            raise ValueError("spec_gamma must be >= 1")
        self.beam_width = int(
            serving_cfg.get("beam_width", 4)
            if beam_width is None else beam_width)
        if self.beam_width < 1:
            raise ValueError("beam_width must be >= 1")
        from . import parse_buckets
        self.buckets = parse_buckets(buckets)
        self.page_pool = PagePool(self.pages, self.page_size)
        # heavy-traffic request plane knobs (root.common.serving.*,
        # CLI --serve-prefix-cache/--serve-prefill-chunk); both off =
        # bit-identical to the monolithic-prefill engine (test-locked)
        want_prefix = bool(
            serving_cfg.get("prefix_cache", False)
            if prefix_cache is None else prefix_cache)
        self.prefill_chunk = int(
            serving_cfg.get("prefill_chunk", 0)
            if prefill_chunk is None else prefill_chunk)
        if self.prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0 (0 = "
                             "monolithic bucketed prefill)")
        if (want_prefix or self.prefill_chunk) and self.quant_kv:
            # the chunk/suffix program writes float rows and the COW
            # copy moves float pages — the int8 pool keeps the
            # monolithic plane (same answers, no sharing)
            self.warning("%s: prefix sharing / chunked prefill serve "
                         "the float pool only; int8 KV keeps the "
                         "monolithic prefill plane", name)
            want_prefix = False
            self.prefill_chunk = 0
        #: effective chunk width (tokens per prefill-chunk dispatch):
        #: the knob, or one page when only prefix sharing needs the
        #: suffix program
        self._chunk = self.prefill_chunk or self.page_size
        self.prefix_cache: Optional[PrefixCache] = (
            PrefixCache(self.page_pool, self.page_size)
            if want_prefix else None)
        if self.prefix_cache is not None:
            # allocator pressure reclaims cached prefixes LRU-first
            # before any admission is refused or shed
            self.page_pool.evictor = self.prefix_cache.evict
        self.scheduler = SlotScheduler(self.max_slots, self.buckets,
                                       self.max_context,
                                       page_pool=self.page_pool,
                                       beam_width=self.beam_width,
                                       spec_gamma=self.spec_gamma)
        # QoS plane (root.common.serving.qos, CLI --serve-qos;
        # docs/services.md "Overload & QoS"): priority-aware admission
        # + lossless batch preemption. Off (the default) = scheduler
        # order, dispatch counts and outputs bit-identical to the
        # FIFO engine (test-locked feature-off lock).
        self.qos = bool(serving_cfg.get("qos", False))
        self.scheduler.qos = self.qos
        #: stable pressure source for dynamic Retry-After hints —
        #: registered only while a QoS engine runs (a bound method is
        #: a fresh object per access, so the identity-checked
        #: clear_pressure_provider needs this one stored)
        self._pressure_fn = lambda: (self.scheduler.queue_depth(),
                                     max(8, self.max_slots * 8))
        #: batch rows preempted for interactive admission / decoded
        #: tokens those preemptions preserved losslessly (stats keys)
        self.preemptions = 0
        self.preempted_tokens = 0
        # the draft workflow enables mode=speculative on the pool; an
        # unusable draft degrades spec to the window plane, never the
        # whole engine
        self.draft = None
        self.draft_stack = None
        if draft is not None:
            try:
                self.draft_stack = split_stack(
                    list(getattr(draft, "forwards", ()) or ()))
                self.draft = draft
            except VelesError as e:
                self.warning("%s: draft model unusable for pooled "
                             "speculation (%s); mode=speculative rides "
                             "the window plane", name, e)
        # tensor-parallel serving (root.common.serving.tp, CLI
        # --serve-tp; docs/services.md "Tensor-parallel serving"): the
        # fixed-shape programs shard_map over a 1D ("model",) mesh
        # slice — attention heads and K/V pages shard over the head
        # axis, FC/embedding weights shard column/row-parallel with
        # one psum per block, while page tables, the shared mask, slot
        # metadata and the PrefixCache stay REPLICATED host data
        # indexing logical pages. tp=1 (the default) is bit-identical
        # to the single-device engine (no shard_map in the trace).
        if mesh is not None and tp is None:
            tp = int(numpy.prod(list(mesh.shape.values())))
        self.tp = int(serving_cfg.get("tp", 1) if tp is None else tp)
        if self.tp < 1:
            raise ValueError("tp must be >= 1")
        self._mesh_arg = mesh
        self._tp_mesh_obj = None
        self._tp_params_cache = None   # (float tree, its placed twin)
        self._tp_draft_cache = None
        if self.tp > 1:
            if self.quant_weights or self.quant_kv:
                # the int8 programs dequantize per-page sidecars whose
                # scales are row-global; sharding them is future work
                raise VelesError(
                    "tensor-parallel serving (tp=%d) serves the float "
                    "plane only; disable --quant-weights/--quant-kv"
                    % self.tp)
            reason = self._tp_unshardable(self.stack)
            if reason:
                raise VelesError(
                    "stack cannot head-shard over tp=%d: %s"
                    % (self.tp, reason))
            if self.draft is not None:
                dreason = self._tp_unshardable(self.draft_stack)
                if dreason:
                    self.warning(
                        "%s: draft model cannot head-shard over tp=%d "
                        "(%s); mode=speculative rides the window "
                        "plane", name, self.tp, dreason)
                    self.draft = None
                    self.draft_stack = None
        pos_emb = self.stack["pos_emb"]
        self._table_len = (None if pos_emb is None else
                           pos_emb.param_arrays()["table"].shape[0])
        self._beam_G = max(1, self.max_slots // self.beam_width)
        self._progs: Dict = {}
        self._params = None
        self._draft_params = None
        self._quant_cache = None   # (float tree, its calibrated twin)
        self._caches = None
        self._draft_caches = None
        self._keys = None
        self._page_table = numpy.zeros(
            (self.max_slots, self.pages_per_slot), numpy.int32)
        self._tok = numpy.zeros(self.max_slots, numpy.int32)
        self._pos = numpy.zeros(self.max_slots, numpy.int32)
        self._temp = numpy.zeros(self.max_slots, numpy.float32)
        #: per-slot count of leading READ-ONLY page-table entries
        #: (prefix-cache adoptions) — a decode-step input: the chunk
        #: write-back masks those pages to the sink, making "a writer
        #: never mutates a shared page" structural, not behavioral
        self._shared = numpy.zeros(self.max_slots, numpy.int32)
        self._thread: Optional[threading.Thread] = None
        self._closing = False
        #: pending drain-by-handoff: (reason, done event, count box) —
        #: consumed by the tick thread at the next step boundary
        self._handoff: Optional[Tuple] = None
        #: replica-death hook (set by GenerationAPI): called when an
        #: injected ``serve.replica_death`` fires mid-decode, AFTER
        #: the in-flight tickets are settled with their resume
        #: progress — the dying gasp a failover retry continues from
        self.on_death = None
        self.admitted = 0
        self.retired = 0
        self.peak_slots = 0
        #: per-program dispatch tally keyed like ``_progs`` — what the
        #: bench prefix gate multiplies CostModel program costs by to
        #: price a load's actual prefill FLOPs
        self.prog_calls: Dict = {}
        #: chunked-prefill stall gauges: seconds of prefill work in
        #: the most recent tick that had co-tenant decodes in flight,
        #: and the worst such tick — THE "bounded TPOT jitter" number
        #: (veles_serving_prefill_stall_seconds on /metrics)
        self.prefill_stall_last = 0.0
        self.prefill_stall_max = 0.0
        #: requests that adopted at least one shared prefix block /
        #: chunk dispatches run (bench + stats surface)
        self.prefix_requests = 0
        self.chunk_dispatches = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ContinuousEngine":
        if self._thread is not None:
            return self
        if self.artifact and not self.artifact_mode:
            self._load_artifact()
        self._closing = False
        if self.qos:
            from .overload import set_pressure_provider
            set_pressure_provider(self._pressure_fn)
        if self.tp > 1:
            # build the mesh eagerly so a too-small device pool fails
            # the START, not the first admitted request's prefill
            self._tp_mesh()
            inc("veles_tp_engines_total")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name + ".engine")
        self._thread.start()
        from . import register_engine
        register_engine(self)
        self.info("%s: continuous batching up (slots=%d buckets=%s "
                  "max_context=%d decode_block=%d pages=%dx%d%s%s%s)",
                  self.name, self.max_slots, list(self.buckets),
                  self.max_context, self.decode_block, self.pages,
                  self.page_size,
                  " +spec" if self.draft is not None else "",
                  " +beam" if self.beam_width <= self.max_slots
                  else "",
                  " tp=%d" % self.tp if self.tp > 1 else "")
        return self

    def stop(self) -> None:
        with self.scheduler.cv:
            self._closing = True
            self.scheduler.cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        # a handoff the loop never consumed (stop racing a drain):
        # release its waiter — the abort below settles the tickets
        # (with progress) through the same first-terminal path
        pending_handoff, self._handoff = self._handoff, None
        if pending_handoff is not None:
            pending_handoff[1].set()
        self.scheduler.drain("server shutting down")
        self._abort_active("server shutting down", code=503,
                           retry_after=5.0, count_shed=False)
        if self.prefix_cache is not None:
            # release the index's page references — with every slot
            # retired above, the refcount ledger must balance to zero
            # (the poisoning regression test closes the loop)
            self.prefix_cache.clear()
        from .overload import clear_pressure_provider
        clear_pressure_provider(self._pressure_fn)
        from . import unregister_engine
        unregister_engine(self)

    # -- intake --------------------------------------------------------------
    def accepts(self, req: Dict) -> Optional[str]:
        """None when the slot pool can serve ``req``; otherwise the
        reason (caller falls back to the window-coalescing path)."""
        t_p, n_new = len(req["prompt"]), int(req["n_new"])
        mode = str(req.get("mode", "greedy"))
        if mode not in _STEP_MODES + ("speculative", "beam"):
            # fail CLOSED: an unrecognized mode would admit fine but
            # no tick path would ever advance it — the slot and its
            # reserved pages would leak for the life of the process
            return "unknown decode mode %r" % mode
        if t_p < 1:
            return "empty prompt"
        if int(req.get("resume_k", 0) or 0) and mode not in _STEP_MODES:
            # resume re-enters the per-slot PRNG stream mid-decode —
            # a contract only the plain decode step owns (docs/
            # services.md "Lossless request plane": window-plane,
            # speculative and beam requests retry from scratch)
            return ("token-level resume serves greedy/sample only "
                    "(mode=%s retries from scratch)" % mode)
        if mode == "speculative":
            if self.draft is None:
                return "no pooled draft model (speculation rides the "\
                       "window plane)"
            if int(req.get("gamma", self.spec_gamma)) != self.spec_gamma:
                return ("gamma %d differs from the pool's fixed-shape "
                        "round (spec_gamma=%d)"
                        % (int(req.get("gamma", 0)), self.spec_gamma))
            if self.quant_kv:
                return "int8 KV pool serves greedy/sample only; "\
                       "speculation rides the window plane"
        if mode == "beam":
            if int(req.get("beam", self.beam_width)) != self.beam_width:
                return ("beam %d differs from the pool's fixed-shape "
                        "group (beam_width=%d)"
                        % (int(req.get("beam", 0)), self.beam_width))
            if self.quant_kv:
                return "int8 KV pool serves greedy/sample only; beam "\
                       "rides the window plane"
            vocab = int(self.stack["head"].vocab_size)
            if self.beam_width > vocab:
                return ("beam %d exceeds the head's vocab size %d"
                        % (self.beam_width, vocab))
        reason = self.scheduler.reject_reason(
            t_p, n_new, mode=mode,
            gamma=int(req.get("gamma", self.spec_gamma)))
        if reason:
            return reason
        worst = self.scheduler._worst_positions(
            t_p, n_new, mode, int(req.get("gamma", self.spec_gamma)))
        if self._table_len is not None and worst > self._table_len:
            return ("generation to %d positions exceeds the trained "
                    "PositionalEmbedding table (%d rows)"
                    % (worst, self._table_len))
        if self.draft is not None and mode == "speculative":
            dpe = self.draft_stack["pos_emb"]
            if dpe is not None and \
                    worst > dpe.param_arrays()["table"].shape[0]:
                return ("speculation to %d positions exceeds the "
                        "draft's PositionalEmbedding table" % worst)
        if mode != "beam" and \
                0 < float(req.get("temperature", 0.0)) < _TEMP_EPS:
            # the shared decode program clamps the divisor at
            # _TEMP_EPS; a colder-than-that request would sample from
            # different logits here than solo sampling.generate does —
            # route it to the window plane, which divides exactly
            return ("temperature %g below the engine's %g resolution"
                    % (req["temperature"], _TEMP_EPS))
        bucket = self.scheduler.bucket_for(t_p)
        if self._kernel_straddle(t_p, bucket, self.stack):
            # padding to the bucket would flip attention_core's
            # flash/reference choice vs the exact-length solo prefill
            # (choose_flash is length-gated) — different kernels drift
            # in the last bits and break the id-exactness contract, so
            # such a prompt rides the window plane instead
            return ("prompt %d pads to bucket %d across the "
                    "flash-attention crossover" % (t_p, bucket))
        if mode == "speculative" and self._kernel_straddle(
                t_p, bucket, self.draft_stack):
            return ("prompt %d pads to bucket %d across the draft's "
                    "flash-attention crossover" % (t_p, bucket))
        return None

    def _kernel_straddle(self, t_p: int, bucket: int, stack) -> bool:
        """True when any block's attention would pick a different
        kernel for the padded bucket length than for the exact prompt
        length (see ``ops.flash_attention.choose_flash``)."""
        if t_p == bucket:
            return False
        from ..ops.flash_attention import choose_flash
        d = stack["stem"].dim
        for blk in stack["blocks"]:
            hd = d // blk.n_heads
            if choose_flash(bucket, hd) != choose_flash(t_p, hd):
                return True
        return False

    def submit(self, req: Dict, ticket,
               max_queue: Optional[int] = None,
               checked: bool = False) -> bool:
        """Enqueue one request; False = queue bound hit (caller
        sheds). ``ticket`` follows the :class:`scheduler.Ticket`
        protocol (``fail`` / ``succeed`` / ``deadline``).
        ``checked=True`` skips :meth:`accepts` — for callers that just
        routed on its verdict."""
        if not checked:
            reason = self.accepts(req)
            if reason is not None:
                # direct submits (no API-side accepts() pre-check) get
                # a clean client-fault answer instead of a 500 at
                # admission
                ticket.fail(reason, code=400)
                return True
        # the closing check and the enqueue share the scheduler's
        # condition (an RLock): stop() flips _closing under the same
        # lock before draining, so a ticket can never slip into the
        # queue after the drain and strand its handler until 504
        with self.scheduler.cv:
            if self._closing:
                return False
            return self.scheduler.push(req, ticket, max_queue)

    def serve(self, reqs: List[Dict], timeout: float = 300.0
              ) -> List[List[int]]:
        """Synchronous convenience (tests / bench): submit every
        request, wait, return each token list; raises on any error."""
        from .scheduler import Ticket
        tickets = [Ticket() for _ in reqs]
        for req, ticket in zip(reqs, tickets):
            if not self.submit(req, ticket):
                raise VelesError("serving queue full")
        out = []
        for req, ticket in zip(reqs, tickets):
            if not ticket.event.wait(timeout):
                raise VelesError("serving timed out for %r" % (req,))
            if ticket.error is not None:
                raise VelesError("serving failed: %s" % ticket.error)
            out.append(ticket.result["tokens"])
        return out

    # -- observability -------------------------------------------------------
    def stats(self) -> Dict[str, float]:
        from ..quant import pool_nbytes
        in_use = self.page_pool.in_use()
        # occupancy per DISTINCT page: a page shared by N slots (or by
        # a slot and the prefix index) holds its positions once, so
        # the fragmentation gauge cannot go negative — or read as
        # phantom HBM — under prefix sharing (satellite fix; in_use
        # already counts shared pages once)
        occ: Dict[int, int] = {}
        prefilling = 0
        for slot in self.scheduler.active():
            pos = int(self._pos[slot.idx])
            if slot.prefilled is not None:
                prefilling += 1
            for j, page in enumerate(slot.pages):
                filled = max(0, min(pos - j * self.page_size,
                                    self.page_size))
                if filled:
                    occ[page] = max(occ.get(page, 0), filled)
        if self.prefix_cache is not None:
            for page in self.prefix_cache.cached_pages():
                occ[page] = self.page_size   # cached blocks are full
        occupied = sum(occ.values())
        frag = (0.0 if in_use == 0 else
                max(0.0, 1.0 - occupied / (in_use * self.page_size)))
        prefix_blocks = (0 if self.prefix_cache is None
                         else self.prefix_cache.stats()["blocks"])
        return {
            "slots": self.max_slots,
            "slots_busy": self.scheduler.busy_count(),
            "peak_slots": self.peak_slots,
            "queue_depth": self.scheduler.queue_depth(),
            "admitted": self.admitted,
            "retired": self.retired,
            # QoS plane (docs/services.md "Overload & QoS"): priority
            # admission + lossless batch preemption, all zero with the
            # knob off
            "qos": int(self.qos),
            "preemptions": self.preemptions,
            "preempted_tokens": self.preempted_tokens,
            "programs": len(self._progs),
            # slot-kind discriminator: "paged" rows page a KV pool;
            # the O(1) lane (serving/recurrent.py) reports "state" and
            # the /metrics renderers emit veles_serving_pages_* rows
            # ONLY for paged engines, so fleet page math never mixes
            # kinds
            "slot_kind": "paged",
            # paged-pool occupancy (serving/pages.py): what an
            # operator sizes `pages`/`page_size` with
            "pages_total": self.pages,
            "pages_in_use": in_use,
            "page_size": self.page_size,
            "page_fragmentation": round(frag, 4),
            # heavy-traffic request plane (docs/services.md "Prefix
            # sharing & streaming"): index occupancy, chunked-prefill
            # progress and the per-tick decode stall the chunking
            # exists to bound
            "prefix_cache": int(self.prefix_cache is not None),
            "prefix_blocks": prefix_blocks,
            "prefix_requests": self.prefix_requests,
            "prefill_chunk": self._chunk if (
                self.prefill_chunk or self.prefix_cache is not None)
            else 0,
            "chunk_dispatches": self.chunk_dispatches,
            "prefilling": prefilling,
            "prefill_stall_seconds": round(self.prefill_stall_max, 6),
            # quantization/AOT plane (veles_tpu/quant/): what the
            # /metrics mode gauges render on both surfaces
            "artifact_mode": int(self.artifact_mode),
            "quant_weights": int(self.quant_weights),
            "quant_kv": int(self.quant_kv),
            "compiled_live": self.compiled_live,
            # mesh-slice width this ONE logical replica spans (1 =
            # solo). Every page gauge above counts LOGICAL pages —
            # host-side allocator state plus global array shapes, both
            # shard-agnostic — so a tp=4 slice reports its occupancy
            # ONCE, not four times (fleet.merge keys chip math off
            # veles_serving_tp, never off page gauges)
            "tp": self.tp,
            "kv_pool_bytes": pool_nbytes(self._caches)
            + pool_nbytes(self._draft_caches),
            # what ONE chip of the slice actually holds: the kv-head
            # axis shards tp ways (pages.per_shard_kv_heads), so the
            # per-chip HBM is the logical pool over tp — the number
            # an operator sizes a single chip's memory against
            "kv_pool_bytes_per_shard": (
                pool_nbytes(self._caches)
                + pool_nbytes(self._draft_caches)) // max(1, self.tp),
        }

    @property
    def closing(self) -> bool:
        """True once :meth:`stop` has begun — :meth:`submit` returns
        False for a closing engine too, and the caller's shed answer
        should say shutdown, not queue-full."""
        return self._closing

    @property
    def programs_built(self) -> int:
        """Jitted programs this engine ever built. The greedy/sample
        plane is bounded by ``len(buckets) + 1``; speculation adds its
        draft prefills + one round program, beam one step program —
        see :meth:`programs_bound`."""
        return len(self._progs)

    def programs_bound(self) -> int:
        """The hard ceiling on :attr:`programs_built`: bucketed
        prefills + the decode step, plus (draft configured) the draft
        prefills + the spec round, plus (beam servable) the beam step
        and the sibling page-copy — a CONSTANT per engine, never a
        function of traffic."""
        bound = len(self.buckets) + 1
        if self.draft is not None:
            bound += len(self.buckets) + 1
        has_pagecopy = False
        if self.beam_width <= self.max_slots:
            bound += 1
            has_pagecopy = self.beam_width > 1
        if self.prefix_cache is not None or self.prefill_chunk:
            bound += 1               # the ONE prefill-chunk program
            if self.prefix_cache is not None:
                has_pagecopy = True  # COW copies ride pagecopy
        return bound + (1 if has_pagecopy else 0)

    def invalidate_quant_cache(self) -> None:
        """Drop the calibrated int8 twin (and the cached device view)
        so the next idle boundary recalibrates from the live weights.
        The identity-keyed cache in :meth:`_prepare_params` cannot see
        an IN-PLACE device mutation that reuses the same ``jax.Array``
        object — any code path that mutates parameters without
        re-placing them must call this, or quantized serving would
        keep the stale scales forever."""
        self._quant_cache = None
        self._params = None
        self._draft_params = None

    # -- worker --------------------------------------------------------------
    def _loop(self) -> None:
        hb = "serving.%s" % self.name
        fail_streak = 0
        try:
            while True:
                with self.scheduler.cv:
                    while (not self.scheduler._queue
                           and self.scheduler.busy_count() == 0
                           and self._handoff is None
                           and not self._closing):
                        self.scheduler.cv.wait(timeout=5.0)
                        if not self._closing:
                            health.heartbeats.beat(hb)
                    if self._closing:
                        return
                health.heartbeats.beat(hb)
                try:
                    self._tick()
                    fail_streak = 0
                except Exception:     # noqa: BLE001 — serve, don't die
                    fail_streak += 1
                    self.exception("%s: serving tick failed", self.name)
                    self._abort_active("internal serving error",
                                       code=500, count_shed=False)
                    # donated buffers may be gone — rebuild lazily
                    self._reset_pool()
                    # a tick that dies before take_admissions never
                    # reaches the deadline check there: sweep the queue
                    # so waiting callers still get their 503 instead of
                    # hanging to full timeout, and back off instead of
                    # busy-spinning while the failure persists
                    from .scheduler import shed_expired
                    shed_expired(self.scheduler.expire_queued())
                    if not self._closing:
                        time.sleep(min(1.0, 0.05 * (2 ** fail_streak)))
        finally:
            health.heartbeats.unregister(hb)

    def _reset_pool(self) -> None:
        self._caches = self._draft_caches = self._keys = None
        self._params = self._draft_params = None

    def _active(self, modes: Tuple[str, ...]) -> List:
        return [s for s in self.scheduler.active() if s.mode in modes]

    def _tick(self) -> None:
        """One step boundary: admit into free slots, then advance each
        decode mode's rows by one fixed-shape dispatch."""
        pending_handoff = self._handoff
        if pending_handoff is not None:
            # drain-by-handoff runs ON the tick thread so the
            # progress snapshot can never race a decode dispatch
            self._handoff = None
            reason, done, box = pending_handoff
            try:
                box["count"] = self._do_handoff(reason)
            finally:
                done.set()
            return
        if self.scheduler.busy_count():
            try:
                # the mid-decode replica-death chaos site: `after=N`
                # kills this replica N in-flight ticks into its load,
                # deterministically — the settled tickets carry their
                # emitted-token prefix, so the router's failover
                # RESUMES from tokens_done instead of re-decoding
                fire_fault("serve.replica_death")
            except FaultInjected:
                self.warning("%s: injected replica death mid-decode — "
                             "settling in-flight tickets with resume "
                             "progress and tearing the front down",
                             self.name)
                self._abort_active(
                    "replica died mid-decode", code=503,
                    retry_after=1.0, count_shed=False)
                death = self.on_death
                if death is not None:
                    death()
                return
        # the param device-view walk (per-array locks) is too heavy to
        # repeat per decode chunk, but a snapshot held forever would
        # serve stale weights after a host-side update. Middle ground:
        # re-read whenever the pool is IDLE (no in-flight rows) — a
        # param change lands at the next burst boundary, no request
        # ever decodes on torn half-old/half-new weights, and under
        # sustained load the walk is never on the per-token path
        # (weights are frozen while serving, as everywhere in serving).
        params = self._params
        if params is None or self.scheduler.busy_count() == 0:
            params = self._params = self._prepare_params()
            if self.draft is not None:
                self._draft_params = self._prepare_draft_params()
        self._ensure_pool(params)
        from .scheduler import shed_expired
        # co-tenants in flight BEFORE this tick's admissions: only
        # their decode latency can be stalled by prefill work, so the
        # chunked-prefill stall gauge measures exactly that window
        had_inflight = self.scheduler.busy_count() > 0
        t_prefill = time.time()
        if self.qos:
            # QoS preemption happens HERE, at the step boundary
            # before admission, so freed slots/pages are handed to
            # the waiting interactive requests in this same tick
            self._preempt_for_interactive()
        admissions, expired = self.scheduler.take_admissions()
        shed_expired(expired)
        for slot in admissions:
            if self.scheduler.slots[slot.idx] is not slot:
                # already retired within this very loop — an n_new=1
                # beam group is finished (and every hypothesis row
                # freed) by its FIRST slot's admission; dispatching
                # prefills for the dead siblings would waste device
                # work and smear host state over freed rows
                continue
            try:
                self._admit(params, slot)
            except Exception as e:    # noqa: BLE001 — answer, don't die
                # retire the whole group before answering: sibling
                # hypothesis rows share this ticket, and leaving them
                # active would let _abort_active below overwrite the
                # already-set answer (a torn 500/503 read in the
                # handler thread)
                for victim in (slot.group.slots
                               if slot.group is not None else [slot]):
                    self._retire_slot(victim)
                slot.ticket.fail("%s: %s" % (type(e).__name__, e),
                                 code=500)
                # the prefill program DONATES the pool: a dispatch
                # that died may have consumed the co-tenants' caches
                # with it, and there is no cheap way to tell. Shed the
                # in-flight rows (503 + Retry-After) and rebuild the
                # pool rather than decode on possibly-dead buffers.
                self.exception("%s: admission failed; resetting the "
                               "slot pool", self.name)
                self._abort_active("serving pool reset after a failed "
                                   "admission", code=503,
                                   retry_after=1.0)
                self._reset_pool()
                return
        self.peak_slots = max(self.peak_slots,
                              self.scheduler.busy_count())
        # _prefill_tick handles its own serve.prefill_chunk fault
        # internally (sheds ONLY the faulted row, co-tenants keep
        # decoding) — no blanket abort may wrap it, or one injected
        # chunk fault would shed the whole pool
        prefill_work = bool(admissions) | self._prefill_tick(params)
        if prefill_work and had_inflight:
            self.prefill_stall_last = time.time() - t_prefill
            self.prefill_stall_max = max(self.prefill_stall_max,
                                         self.prefill_stall_last)
        try:
            if self._decodable():
                self._decode(params)
            if self._active(("speculative",)):
                self._spec_tick(params)
            if self.scheduler.active_beams():
                self._beam_tick(params)
        except FaultInjected as e:
            # an injected decode fault DEGRADES: in-flight rows are
            # shed with Retry-After, the pool stays consistent (the
            # fault fires before the dispatch)
            self._abort_active(str(e), code=503, retry_after=1.0)

    # -- QoS preemption --------------------------------------------------------
    @staticmethod
    def _emitted(slot) -> List[int]:
        """Every token this request has emitted since the CLIENT's
        submission: tokens an in-engine preemption folded back into
        the prompt (``_qos_prefix``) plus this slot's own decode
        output. Progress snapshots and final results are built from
        this, so preemption stays invisible on the wire — a router's
        own ``resume_tokens`` are NOT included (the router accounts
        for those itself, exactly as before)."""
        return list(slot.req.get("_qos_prefix", ())) + list(slot.tokens)

    def _preempt_victims(self, need: int) -> List:
        """Pick up to ``need`` preemptable batch rows: plain decode
        modes only (their PRNG stream resumes exactly), fully
        prefilled, with at least one emitted token and at least one
        still to go (a row about to finish is cheaper to let finish).
        Cheapest first — fewest decoded tokens means the smallest
        re-prefill on resume."""
        from .overload import request_priority
        victims = [s for s in self.scheduler.active()
                   if s.group is None and s.mode in _STEP_MODES
                   and request_priority(s.req) == "batch"
                   and s.prefilled is None and s.tokens
                   and len(s.tokens) < s.n_new]
        victims.sort(key=lambda s: (len(s.tokens), s.idx))
        return victims[:max(0, need)]

    def _preempt_for_interactive(self) -> None:
        """QoS preemption at the step boundary (docs/services.md
        "Overload & QoS"): when more interactive requests wait than
        free slots exist, batch rows are preempted through the
        token-level resume path — emitted tokens fold back into the
        prompt (:func:`fold_resume`), ``resume_k`` accumulates so the
        resumed prefill re-enters the per-slot PRNG stream exactly,
        and the SAME un-terminated ticket requeues. No terminal
        fires, no histogram double-samples: the client of a preempted
        batch request just sees a pause, and its final answer is
        bit-identical to an uninterrupted decode (test-locked)."""
        from .overload import qos_preempt_enabled, request_priority
        if not qos_preempt_enabled():
            return
        with self.scheduler.cv:
            waiting = sum(
                1 for req, _t in self.scheduler._queue
                if request_priority(req) == "interactive")
            free = len(self.scheduler._free)
        if waiting <= free:
            return
        for slot in self._preempt_victims(waiting - free):
            emitted = self._emitted(slot)
            resumed = fold_resume(slot.req, slot.tokens)
            # chained folds accumulate: the PRNG must advance one
            # split per token EVER emitted for this request, not just
            # this preemption's batch (fold_resume alone records only
            # the latest fold — correct for the router's single-shot
            # wire form, not for repeated in-engine preemption)
            resumed["resume_k"] = (int(slot.req.get("resume_k", 0)
                                       or 0) + len(slot.tokens))
            resumed["_qos_prefix"] = emitted
            resumed["_requeued"] = True
            # progress rides the ticket too: a failure between
            # preemption and completion still answers with the full
            # resume record
            slot.ticket.set_progress(emitted)
            self._retire_slot(slot)
            self.scheduler.push(resumed, slot.ticket)
            self.preemptions += 1
            self.preempted_tokens += len(slot.tokens)
            inc("veles_qos_preemptions_total")
            inc("veles_qos_preempted_tokens_total", len(slot.tokens))
            self.debug("%s: preempted batch request %s at %d tokens "
                       "(lossless resume queued)", self.name,
                       slot.ticket.request_id, len(emitted))

    def _prepare_params(self) -> Dict:
        """Fresh device-side params for the serving programs: the
        float tree, or its per-channel int8 twin under
        ``quant_weights``. Calibration is NOT repeated per idle
        boundary: ``device_view()`` returns the cached jax array until
        a host-side update re-places it, so leaf identity against the
        last-calibrated tree tells exactly when the weights actually
        changed — unchanged weights reuse the quantized twin, updated
        weights get fresh scales at the next burst boundary. In-place
        device mutations (same ``jax.Array`` object, new bytes) are
        invisible here — their authors must call
        :meth:`invalidate_quant_cache`."""
        params = params_of(self.wf)
        if self.tp > 1:
            # sharded placement is cached by the same leaf-identity
            # test the quant twin uses: unchanged weights reuse the
            # resident shards, updated weights re-place at the next
            # burst boundary (quant is gated off under tp)
            cached = self._tp_params_cache
            if cached is not None and _same_leaves(cached[0], params):
                return cached[1]
            placed = self._tp_place(
                params, self._params_pspec(self.stack, params))
            self._tp_params_cache = (params, placed)
            return placed
        if not self.quant_weights:
            return params
        cached = self._quant_cache
        if cached is not None and _same_leaves(cached[0], params):
            return cached[1]
        from ..quant import quantize_params
        qparams, _report = quantize_params(params)
        self._quant_cache = (params, qparams)
        return qparams

    def _prepare_draft_params(self) -> Dict:
        """The draft tree — under ``tp`` placed on the mesh with the
        same identity caching as :meth:`_prepare_params`."""
        params = params_of(self.draft)
        if self.tp <= 1:
            return params
        cached = self._tp_draft_cache
        if cached is not None and _same_leaves(cached[0], params):
            return cached[1]
        placed = self._tp_place(
            params, self._params_pspec(self.draft_stack, params))
        self._tp_draft_cache = (params, placed)
        return placed

    def _ensure_pool(self, params) -> None:
        if self._caches is not None:
            return
        import jax.numpy as jnp
        from ..quant import block_page_pool
        rows = self.page_pool.device_rows
        dtype = self._pool_dtype(params)

        def pools(stack, quantized):
            d = stack["stem"].dim
            out = []
            for blk in stack["blocks"]:
                bkv = getattr(blk, "n_kv_heads", blk.n_heads)
                hd = d // blk.n_heads
                out.append(block_page_pool(rows, self.page_size, bkv,
                                           hd, dtype, quantized))
            return tuple(out)

        self._caches = pools(self.stack, self.quant_kv)
        if self.draft is not None and not self.quant_kv:
            # the draft pool shares the allocator and page tables; it
            # stays float. Under quant_kv accepts() routes EVERY
            # speculative request to the window plane, so allocating
            # it there would be pure dead HBM against the very claim
            # quant_kv makes
            self._draft_caches = pools(self.draft_stack, False)
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)
        if self.tp > 1:
            # pools shard over the kv-head axis (each chip holds every
            # logical page's heads/tp slice — pages.py per_shard_kv);
            # keys stay replicated. Placing them NOW keeps the
            # donation path alias-clean from the first dispatch
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P
            mesh = self._tp_mesh()
            self._caches = self._tp_place(
                self._caches, self._caches_pspec(self.stack))
            if self._draft_caches is not None:
                self._draft_caches = self._tp_place(
                    self._draft_caches,
                    self._caches_pspec(self.draft_stack))
            self._keys = jax.device_put(
                self._keys, NamedSharding(mesh, P()))

    def _pool_dtype(self, params):
        """Float dtype of the activation path (the stem table's —
        also under quant_weights, which never touches ``table``)."""
        stem = self.stack["stem"]
        return params[stem.name]["table"].dtype

    # -- tensor-parallel mesh (docs/services.md "Tensor-parallel
    # serving") -----------------------------------------------------------
    @property
    def _tp_axis(self):
        """Mesh axis name the programs shard over, or None solo."""
        return "model" if self.tp > 1 else None

    def _tp_unshardable(self, stack) -> Optional[str]:
        """Reason string when ``stack`` cannot head/vocab-shard over
        ``self.tp`` ways, else None. Every sharded dimension must
        divide evenly — a ragged shard would silently change the
        math, and id-exactness is the whole contract."""
        tp = self.tp
        stem, head = stack["stem"], stack["head"]
        vocab = stem.param_arrays()["table"].shape[0]
        if vocab % tp:
            return "vocab %d %% tp %d != 0" % (vocab, tp)
        hv = head.param_arrays()["weights"].shape[1]
        if hv % tp:
            return "head vocab %d %% tp %d != 0" % (hv, tp)
        from .pages import per_shard_kv_heads
        for blk in stack["blocks"]:
            kv = getattr(blk, "n_kv_heads", blk.n_heads)
            try:
                per_shard_kv_heads(kv, tp)
            except ValueError:
                return ("%s heads %d/kv %d not divisible by tp %d"
                        % (blk.name, blk.n_heads, kv, tp))
            if blk.n_heads % tp:
                return ("%s heads %d/kv %d not divisible by tp %d"
                        % (blk.name, blk.n_heads, kv, tp))
            hidden = blk.param_arrays()["w1"].shape[1]
            if hidden % tp:
                return ("%s ffn hidden %d %% tp %d != 0"
                        % (blk.name, hidden, tp))
        return None

    def _tp_mesh(self):
        """The 1D ``("model",)`` mesh slice this engine serves as —
        built lazily (no jax import at construction) from the first
        ``self.tp`` local devices, or the caller's ``mesh=`` knob."""
        if self._tp_mesh_obj is None:
            if self._mesh_arg is not None:
                self._tp_mesh_obj = self._mesh_arg
            else:
                import jax
                devs = jax.devices()
                if len(devs) < self.tp:
                    raise VelesError(
                        "tp=%d needs %d devices; %d visible (set "
                        "TPU_VISIBLE_CHIPS / XLA_FLAGS for a CPU "
                        "virtual mesh)" % (self.tp, self.tp,
                                           len(devs)))
                from jax.sharding import Mesh
                self._tp_mesh_obj = Mesh(
                    numpy.array(devs[:self.tp]), ("model",))
        return self._tp_mesh_obj

    def _params_pspec(self, stack, params):
        """PartitionSpec tree matching ``params``: wq/wk/wv/w1/w3 and
        the head weights shard COLUMN-parallel, wo/w2 and the stem
        table ROW-parallel, b1/head-bias along their sharded dim; b2,
        norms and the positional table stay replicated (b2 is added
        once AFTER the block psum — a sharded b2 would be
        tp-counted)."""
        from jax.sharding import PartitionSpec as P
        stem, head = stack["stem"], stack["head"]
        blocks = {blk.name for blk in stack["blocks"]}
        col = {"wq", "wk", "wv", "w1", "w3"}
        row = {"wo", "w2"}
        out = {}
        for uname, leaves in params.items():
            spec = {}
            for key in leaves:
                if uname == stem.name and key == "table":
                    s = P("model", None)
                elif uname == head.name and key == "weights":
                    s = P(None, "model")
                elif uname == head.name and key == "bias":
                    s = P("model")
                elif uname in blocks and key in col:
                    s = P(None, "model")
                elif uname in blocks and key in row:
                    s = P("model", None)
                elif uname in blocks and key == "b1":
                    s = P("model")
                else:
                    s = P()
                spec[key] = s
            out[uname] = spec
        return out

    def _caches_pspec(self, stack):
        """Per-block K/V page-pool specs: the pool's kv-head axis
        (axis 2 of (rows, page_size, kv, hd)) shards over the mesh —
        each chip holds every LOGICAL page's ``kv/tp`` head slice, so
        page ids, refcounts, COW and the eviction ledger never learn
        about sharding."""
        from jax.sharding import PartitionSpec as P
        s = P(None, None, "model", None)
        return tuple((s, s) for _ in stack["blocks"])

    def _tp_place(self, tree, specs):
        """``device_put`` a pytree onto the mesh per its spec tree."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = self._tp_mesh()
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec))
        return jax.device_put(tree, shardings)

    def _finalize(self, fn, donate=(), in_specs=None, out_specs=None):
        """jit a program builder's raw function — plain ``jax.jit``
        solo (bit-identical to the pre-TP engine), or jit(shard_map)
        over the ``("model",)`` mesh under ``tp>1``. One seam, so
        every builder stays a single definition for both planes."""
        import jax
        if self.tp <= 1:
            return jax.jit(fn, donate_argnums=donate)
        from ..parallel.compat import shard_map_compat
        return jax.jit(
            shard_map_compat(fn, self._tp_mesh(), in_specs, out_specs),
            donate_argnums=donate)

    # -- admission ------------------------------------------------------------
    def _refresh_table_row(self, slot) -> None:
        """Sync the host page-table row with the slot's page list —
        THE one place the row layout is written (admission, growth and
        the sibling page-copy all go through here)."""
        row = self._page_table[slot.idx]
        row[:] = 0
        row[:len(slot.pages)] = slot.pages

    def _table_row(self, slot):
        import jax.numpy as jnp
        self._refresh_table_row(slot)
        return jnp.asarray(self._page_table[slot.idx])

    def _admit(self, params, slot) -> None:
        import jax
        import jax.numpy as jnp
        t_p, bucket = slot.t_p, slot.bucket
        group = slot.group
        if group is not None and slot is not group.slots[0]:
            # sibling hypothesis rows start as exact copies of the
            # lead row's prompt cache: ONE page-granular device copy
            # instead of re-running the full prefill per hypothesis
            # (the lead admits first — take_admissions fills groups in
            # order)
            dst_row = self._table_row(slot)
            src_row = self._table_row(group.slots[0])
            self._caches = self._program("pagecopy")(
                src_row, dst_row, self._caches)
            self._pos[slot.idx] = t_p
            self._temp[slot.idx] = slot.temperature
            return
        if group is None and slot.mode in _STEP_MODES \
                and self._admit_chunked(slot):
            # prefix adoption / chunked prefill: the suffix prefills
            # chunk-by-chunk across ticks (_prefill_tick), co-scheduled
            # with the decode step instead of stalling it
            return
        ids = numpy.zeros((1, bucket), numpy.int32)
        ids[0, :t_p] = slot.req["prompt"]
        ids_dev = jnp.asarray(ids)
        table_row = self._table_row(slot)
        prog = self._program("prefill", bucket)
        resume_k = int(slot.req.get("resume_k", 0) or 0)
        # a resumed request's prompt already carries its emitted-token
        # prefix (fold_resume); the PRNG carry must re-enter the
        # stream at the resumed position — one host-side split per
        # token already emitted, so the resumed decode's noise is
        # bit-identical to the uninterrupted run's
        seed_key = (advanced_prng_key(slot.req.get("seed", 0), resume_k)
                    if resume_k
                    else jax.random.PRNGKey(int(slot.req.get("seed",
                                                             0))))
        if resume_k and group is None:
            inc("veles_resume_tokens_total", resume_k)
        wait = max(0.0, (slot.ticket.admitted or time.time())
                   - slot.ticket.enqueued)
        with span("serving.prefill", bucket=bucket, slot=slot.idx,
                  t_p=t_p, mode=slot.mode,
                  request_id=slot.ticket.request_id,
                  trace_id=slot.ticket.trace_id,
                  attempt=slot.ticket.attempt):
            first, logits, self._keys, self._caches = prog(
                params, ids_dev, numpy.int32(t_p),
                numpy.int32(slot.idx), numpy.float32(slot.temperature),
                seed_key, table_row, self._keys, self._caches)
        inc("veles_serving_prefill_dispatches_total")
        self._pos[slot.idx] = t_p
        self._temp[slot.idx] = slot.temperature
        if slot.mode == "speculative":
            self._draft_caches = self._program("dprefill", bucket)(
                self._draft_params, ids_dev, table_row,
                self._draft_caches)
            inc("veles_serving_prefill_dispatches_total")
        if group is None:
            if not slot.req.get("_requeued"):
                # a preempted-and-requeued request was admitted (and
                # its queue wait counted) once already — exactly-once
                # accounting holds across preempt → requeue → finish
                inc("veles_serving_admitted_total")
                inc("veles_serving_queue_wait_seconds_total", wait)
                self.admitted += 1
            first = int(first)
            # the int() above synced the prefill dispatch: this step
            # boundary IS prefill-done and first-token time (host-side
            # stamps only — no device work rides on tracing)
            slot.ticket.mark_prefill_done()
            slot.ticket.mark_first_token()
            self._tok[slot.idx] = first
            if slot.mode in _STEP_MODES:
                self._prefix_insert(slot)
            done = slot.record(first)
            slot.ticket.push_tokens([first])
            if done:
                self._finish(slot)
            return
        # beam: count the REQUEST once, expand the first top-W
        # hypotheses from the prefill logits (the same log_softmax +
        # top_k arithmetic nn/beam.py's first expansion runs)
        if slot is group.slots[0]:
            inc("veles_serving_admitted_total")
            inc("veles_serving_queue_wait_seconds_total", wait)
            self.admitted += 1
            logp0 = jax.nn.log_softmax(
                jnp.asarray(logits).astype(jnp.float32))
            top0, tok0 = jax.lax.top_k(logp0, self.beam_width)
            group.cur = numpy.asarray(tok0, numpy.int32)
            group.scores = numpy.asarray(top0, numpy.float32)
            eos = slot.eos_id
            group.finished = (group.cur == (-1 if eos is None
                                            else int(eos)))
            group.toks = numpy.zeros(
                (self.beam_width, slot.n_new), numpy.int32)
            group.toks[:, 0] = group.cur
            group.step = 0
            # the numpy.asarray(top-k) above synced the expansion:
            # the group's first hypothesis tokens exist NOW
            slot.ticket.mark_prefill_done()
            slot.ticket.mark_first_token()
            if slot.n_new == 1:
                self._finish_beam(group)

    # -- prefix sharing + chunked prefill -------------------------------------
    def _chunk_kernel_safe(self, bucket: int) -> bool:
        """True when the monolithic bucketed prefill would use the
        REFERENCE attention kernel for every block at this bucket —
        the chunk/suffix program always computes reference arithmetic
        over the gathered page view, so chunking (and adopting pages
        a chunked/reference prefill wrote) is only id-exact when the
        monolithic path would have picked the same kernel. Above the
        flash crossover the request simply rides the monolithic
        plane (same answers, no sharing)."""
        from ..ops.flash_attention import choose_flash
        d = self.stack["stem"].dim
        for blk in self.stack["blocks"]:
            if choose_flash(bucket, d // blk.n_heads):
                return False
        return True

    def _admit_chunked(self, slot) -> bool:
        """Prefix-cache adoption + chunked-prefill start for one plain
        decode-mode admission (already holding its worst-case page
        reservation). True when the slot now prefills chunk-by-chunk
        across ticks; False = the caller runs the monolithic bucketed
        prefill exactly as before."""
        if (self.prefix_cache is None and not self.prefill_chunk) \
                or self.quant_kv:
            return False
        if not self._chunk_kernel_safe(slot.bucket):
            return False
        t_p = slot.t_p
        P = self.page_size
        matched: List[int] = []
        if self.prefix_cache is not None:
            try:
                # raise = injected index loss, corrupt = injected
                # index rot: both DEGRADE to a shorter/empty match and
                # a full prefill — the token comparison inside match()
                # is the authority, so a corrupted index can never
                # adopt wrong pages
                corrupting = fire_fault("serve.prefix_match")
                matched = self.prefix_cache.match(slot.req["prompt"],
                                                  corrupt=corrupting)
            except FaultInjected as e:
                self.warning("%s: injected prefix-match fault (%s) — "
                             "degrading to a full prefill",
                             self.name, e)
                matched = []
            if matched:
                inc("veles_prefix_hits_total")
                self.prefix_requests += 1
            elif t_p // P:
                inc("veles_prefix_misses_total")
        if not matched and not self.prefill_chunk:
            return False
        # at least one token must prefill (the suffix pass emits the
        # first token's logits), so a FULL-prompt match re-computes
        # its last position — into a COPY of the last shared page
        # (copy-on-write), never into the shared page itself
        start = min(len(matched) * P, t_p - 1)
        k_full = start // P
        cow_src = matched[k_full] if len(matched) * P > start else None
        give_back: List[int] = []
        for i in range(k_full):
            give_back.append(slot.pages[i])
            slot.pages[i] = matched[i]
        slot.shared = k_full
        self._shared[slot.idx] = k_full
        if k_full:
            inc("veles_prefix_shared_pages_total", k_full)
        if cow_src is not None:
            fresh = self.page_pool.alloc(1)
            if fresh:
                import jax.numpy as jnp
                src = numpy.zeros(self.pages_per_slot, numpy.int32)
                dst = numpy.zeros(self.pages_per_slot, numpy.int32)
                src[0], dst[0] = cow_src, fresh[0]
                self._caches = self._program("pagecopy")(
                    jnp.asarray(src), jnp.asarray(dst), self._caches)
                give_back.append(slot.pages[k_full])
                slot.pages[k_full] = fresh[0]
                inc("veles_prefix_cow_copies_total")
            else:
                # no page to copy into: shorten the match to the block
                # boundary — the whole last block re-prefills
                start = k_full * P
            self.page_pool.free([cow_src])   # drop the match's ref
        self.page_pool.free(give_back)
        resume_k = int(slot.req.get("resume_k", 0) or 0)
        if resume_k:
            inc("veles_resume_tokens_total", resume_k)
        wait = max(0.0, (slot.ticket.admitted or time.time())
                   - slot.ticket.enqueued)
        if not slot.req.get("_requeued"):
            # preempted-and-requeued rows were counted at their first
            # admission (see _admit) — never twice
            inc("veles_serving_admitted_total")
            inc("veles_serving_queue_wait_seconds_total", wait)
            self.admitted += 1
        slot.prefilled = start
        self._pos[slot.idx] = start
        self._temp[slot.idx] = slot.temperature
        return True

    def _prefix_insert(self, slot) -> None:
        """Cache a freshly prefilled prompt's FULL blocks so the next
        admission shares them. The slot's pages stay immutable for
        those positions (decode writes land at >= t_p, the write-back
        masks shared entries), so the index's reference outlives the
        slot safely. Skipped above the flash crossover: pages a flash
        prefill wrote must not seed reference-kernel suffixes."""
        if self.prefix_cache is None or slot.group is not None \
                or slot.mode not in _STEP_MODES:
            return
        if not self._chunk_kernel_safe(slot.bucket):
            return
        n_blocks = slot.t_p // self.page_size
        if n_blocks:
            self.prefix_cache.insert(
                slot.req["prompt"][:n_blocks * self.page_size],
                slot.pages[:n_blocks])

    def _prefill_tick(self, params) -> bool:
        """Advance every chunk-prefilling row by ONE chunk — the
        co-scheduling half of chunked prefill: admissions interleave
        with the decode step at ``prefill_chunk`` granularity instead
        of stalling it for a monolithic bucketed pass. Returns True
        when any chunk dispatched. The ``serve.prefill_chunk`` fault
        fires per chunk: an injected raise sheds THAT row 503 +
        Retry-After with a resume payload while co-tenants keep
        decoding."""
        import jax
        import jax.numpy as jnp
        pending = [s for s in self._active(_STEP_MODES)
                   if s.prefilled is not None]
        work = False
        for slot in pending:
            if self.scheduler.slots[slot.idx] is not slot:
                continue
            try:
                fire_fault("serve.prefill_chunk")
            except FaultInjected as e:
                # shed with a resume payload: nothing was emitted yet,
                # so the payload is the (possibly empty) progress — a
                # router retry redoes the prefill elsewhere
                slot.ticket.set_progress(self._emitted(slot))
                self._retire_slot(slot)
                if slot.ticket.fail(
                        "injected prefill-chunk fault: %s" % e,
                        code=503, retry_after=1.0):
                    inc("veles_shed_requests_total")
                continue
            t_p = slot.t_p
            p0 = int(slot.prefilled)
            C = self._chunk
            final = p0 + C >= t_p
            ids = numpy.zeros(C, numpy.int32)
            seg = slot.req["prompt"][p0:p0 + C]
            ids[:len(seg)] = seg
            resume_k = int(slot.req.get("resume_k", 0) or 0)
            # the PRNG carry matters only at the final chunk (it
            # samples the first token); resumed requests re-enter
            # their stream exactly like the monolithic prefill
            seed_key = (advanced_prng_key(slot.req.get("seed", 0),
                                          resume_k)
                        if final and resume_k
                        else jax.random.PRNGKey(
                            int(slot.req.get("seed", 0))))
            table_row = self._table_row(slot)
            with span("serving.prefill_chunk", slot=slot.idx, p0=p0,
                      chunk=C, t_p=t_p, final=int(final),
                      request_id=slot.ticket.request_id,
                      trace_id=slot.ticket.trace_id):
                first, self._keys, self._caches = \
                    self._program("pchunk")(
                        params, jnp.asarray(ids), numpy.int32(p0),
                        numpy.int32(t_p), numpy.int32(slot.idx),
                        numpy.float32(slot.temperature), seed_key,
                        table_row, numpy.int32(1 if final else 0),
                        self._keys, self._caches)
            inc("veles_serving_prefill_dispatches_total")
            self.chunk_dispatches += 1
            work = True
            if not final:
                slot.prefilled = p0 + C
                self._pos[slot.idx] = min(p0 + C, t_p)
                continue
            slot.prefilled = None
            self._pos[slot.idx] = t_p
            first = int(first)          # syncs the chunk dispatch
            slot.ticket.mark_prefill_done()
            slot.ticket.mark_first_token()
            self._tok[slot.idx] = first
            self._prefix_insert(slot)
            done = slot.record(first)
            slot.ticket.push_tokens([first])
            if done:
                self._finish(slot)
        return work

    def _decodable(self) -> List:
        """Plain decode-mode rows whose prefill is complete — the rows
        THE decode step advances (chunk-prefilling rows join at their
        final chunk's step boundary)."""
        return [s for s in self._active(_STEP_MODES)
                if s.prefilled is None]

    # -- page growth -----------------------------------------------------------
    def _grow_or_shed(self, slots: List, need_fn) -> List:
        """Extend each slot's pages to cover ``need_fn(slot)``
        positions before the next dispatch. Admission reserved every
        row's own worst case, so this normally allocates NOTHING —
        it is the accounting safety net: a slot the allocator cannot
        cover (ledger drift, or an injected ``serve.page_alloc``
        fault) is SHED — 503 + Retry-After, pages freed, pool stays
        consistent — while the survivors keep decoding. Returns the
        surviving slots; their page-table rows are refreshed."""
        alive: List = []
        dead = set()
        for slot in slots:
            if id(slot) in dead:
                continue
            grown = self.scheduler.grow(slot, need_fn(slot))
            if grown:
                self._refresh_table_row(slot)
                alive.append(slot)
                continue
            victims = (slot.group.slots if slot.group is not None
                       else [slot])
            for v in victims:
                dead.add(id(v))
                if v in alive:
                    alive.remove(v)
                self._retire_slot(v)
            # ONE shed request however many hypothesis rows it held —
            # the admitted/retired counters are per request too, and
            # fail()'s first-terminal True keeps a ticket another
            # sweep already answered from counting twice
            if slot.mode in _STEP_MODES:
                victims[0].ticket.set_progress(
                    self._emitted(victims[0]))
            if victims[0].ticket.fail(
                    "serving page pool exhausted mid-decode",
                    code=503, retry_after=1.0):
                inc("veles_shed_requests_total")
        return alive

    # -- the decode chunk ------------------------------------------------------
    def _decode(self, params) -> None:
        import jax.numpy as jnp
        active = self._grow_or_shed(
            self._decodable(),
            lambda s: min(s.t_p + s.n_new,
                          int(self._pos[s.idx]) + self.decode_block))
        if not active:
            return
        mask = numpy.zeros(self.max_slots, numpy.int32)
        for slot in active:
            mask[slot.idx] = 1
        base_len = {id(s): len(s.tokens) for s in active}
        fire_fault("serve.decode_step")
        with span("serving.decode_step", active=len(active),
                  chunk=self.decode_block):
            toks, self._keys, self._caches = self._program("step")(
                params, jnp.asarray(self._tok), jnp.asarray(self._pos),
                jnp.asarray(self._temp), jnp.asarray(mask),
                jnp.asarray(self._page_table),
                jnp.asarray(self._shared), self._keys,
                self._caches)
            toks = numpy.asarray(toks)          # (decode_block, S)
        inc("veles_serving_decode_dispatches_total")
        finished: List = []
        for h in range(toks.shape[0]):
            still = [s for s in active if s not in finished]
            if not still:
                break
            for slot in still:
                token = int(toks[h, slot.idx])
                self._tok[slot.idx] = token
                self._pos[slot.idx] += 1
                if slot.record(token):
                    finished.append(slot)
        for slot in active:
            # streaming rows hand this chunk's tokens to their drain
            # loop at the step boundary — before _finish's terminal
            # sentinel, so the wire order is tokens-then-done
            slot.ticket.push_tokens(slot.tokens[base_len[id(slot)]:])
        for slot in finished:
            self._finish(slot)

    # -- the speculative round -------------------------------------------------
    def _spec_tick(self, params) -> None:
        """One on-device draft/verify round for every speculative row:
        the draft proposes ``spec_gamma`` tokens (a ``lax.scan`` of
        single-row steps over its paged view), the target verifies the
        whole window in ONE multi-position pass, and the accept rule
        emits up to gamma tokens per row — all rows advance by their
        own accepted lengths inside one fixed-shape dispatch."""
        import jax.numpy as jnp
        gamma = self.spec_gamma
        active = self._grow_or_shed(
            self._active(("speculative",)),
            lambda s: min(s.t_p + s.n_new + gamma + 1,
                          int(self._pos[s.idx]) + gamma))
        if not active:
            return
        smask = numpy.zeros(self.max_slots, numpy.int32)
        for slot in active:
            smask[slot.idx] = 1
        fire_fault("serve.decode_step")
        with span("serving.spec_round", active=len(active),
                  gamma=gamma):
            (out_vec, n_emit, acc, new_tok, self._keys, self._caches,
             self._draft_caches) = self._program("spec")(
                params, self._draft_params, jnp.asarray(self._tok),
                jnp.asarray(self._pos), jnp.asarray(self._temp),
                jnp.asarray(smask), jnp.asarray(self._page_table),
                self._keys, self._caches, self._draft_caches)
            out_vec = numpy.asarray(out_vec)     # (S, gamma)
            n_emit = numpy.asarray(n_emit)
            acc = numpy.asarray(acc)
            new_tok = numpy.asarray(new_tok)
        inc("veles_serving_decode_dispatches_total")
        inc("veles_serving_spec_rounds_total", len(active))
        for slot in active:
            i = slot.idx
            emitted = int(n_emit[i])
            slot.rounds += 1
            slot.acc += int(acc[i])
            self._pos[i] += emitted
            self._tok[i] = int(new_tok[i])
            done = False
            base = len(slot.tokens)
            for t in out_vec[i, :emitted]:
                if slot.record(int(t)):
                    done = True
                    break
            slot.ticket.push_tokens(slot.tokens[base:])
            if done:
                self._finish(slot)

    # -- the beam step ---------------------------------------------------------
    def _beam_tick(self, params) -> None:
        """One top-k step for every live beam group: each hypothesis
        row runs the single-row step over its paged view, the group
        expands W x V continuations, keeps the top W, and REORDERS the
        caches by surviving parent — a page-granular copy through the
        page tables, batched across groups in one fixed-shape
        dispatch. The arithmetic is nn/beam.py's (f32 log_softmax,
        frozen-eos lanes, flat top_k), so a pooled beam request's
        tokens equal its solo ``beam_generate`` exactly."""
        import jax.numpy as jnp
        groups = self.scheduler.active_beams()
        hyps = [s for g in groups for s in g.slots]
        alive_slots = self._grow_or_shed(
            hyps, lambda s: min(s.t_p + max(s.n_new - 1, 1),
                                int(self._pos[s.idx]) + 1))
        groups = [g for g in groups
                  if all(s in alive_slots for s in g.slots)]
        if not groups:
            return
        G, W, P = self._beam_G, self.beam_width, self.pages_per_slot
        cur = numpy.zeros((G, W), numpy.int32)
        pos = numpy.zeros(G, numpy.int32)
        scores = numpy.full((G, W), -numpy.inf, numpy.float32)
        finished = numpy.zeros((G, W), bool)
        eosv = numpy.full(G, -1, numpy.int32)
        gmask = numpy.zeros(G, numpy.int32)
        tables_g = numpy.zeros((G, W, P), numpy.int32)
        for gi, group in enumerate(groups):
            cur[gi] = group.cur
            pos[gi] = group.t_p + group.step
            scores[gi] = group.scores
            finished[gi] = group.finished
            eosv[gi] = (-1 if group.slots[0].eos_id is None
                        else int(group.slots[0].eos_id))
            gmask[gi] = 1
            for wi, slot in enumerate(group.slots):
                tables_g[gi, wi] = self._page_table[slot.idx]
        fire_fault("serve.decode_step")
        with span("serving.beam_step", groups=len(groups),
                  width=W):
            tok, parent, new_scores, new_fin, self._caches = \
                self._program("beam")(
                    params, jnp.asarray(cur), jnp.asarray(pos),
                    jnp.asarray(scores), jnp.asarray(finished),
                    jnp.asarray(eosv), jnp.asarray(gmask),
                    jnp.asarray(tables_g), self._caches)
            tok = numpy.asarray(tok)
            parent = numpy.asarray(parent)
            new_scores = numpy.asarray(new_scores)
            new_fin = numpy.asarray(new_fin)
        inc("veles_serving_decode_dispatches_total")
        inc("veles_serving_beam_steps_total", len(groups))
        for gi, group in enumerate(groups):
            i = group.step + 1
            group.toks = group.toks[parent[gi]].copy()
            group.toks[:, i] = tok[gi]
            group.cur = tok[gi].copy()
            group.scores = new_scores[gi].copy()
            group.finished = new_fin[gi].copy()
            group.step = i
            for slot in group.slots:
                self._pos[slot.idx] += 1
            if i >= group.slots[0].n_new - 1:
                self._finish_beam(group)

    # -- retirement -------------------------------------------------------------
    def _retire_slot(self, slot) -> None:
        """Clear a row's host state and free its slot + pages. The
        page-table row is zeroed so a retired row's stale view can
        never alias pages the allocator hands to the next admission."""
        self._tok[slot.idx] = 0
        self._pos[slot.idx] = 0
        self._temp[slot.idx] = 0.0
        self._shared[slot.idx] = 0
        self._page_table[slot.idx, :] = 0
        self.scheduler.retire(slot)

    def _finish(self, slot) -> None:
        """Retire a row the moment it is done: free the slot and its
        pages (the next admission reuses them immediately) and answer
        the ticket."""
        # co-resident rows at retirement — the window plane's
        # batched_with response key, kept so the schema does not
        # depend on which plane served the request
        batched_with = max(0, self.scheduler.busy_count() - 1)
        self._retire_slot(slot)
        # _emitted prepends any tokens an in-engine QoS preemption
        # folded back into the prompt — the client's answer covers
        # the WHOLE generation, bit-identical to an uninterrupted run
        tokens = self._emitted(slot)
        result = {"tokens": tokens,
                  "batched_with": batched_with,
                  "engine": "continuous"}
        if slot.mode == "speculative":
            rounds = max(slot.rounds, 1)
            result["rounds"] = rounds
            result["acceptance"] = slot.acc / (rounds * self.spec_gamma)
        # count only a first-terminal answer, symmetric with every
        # shed path: a late _finish racing a stop()-side abort must
        # not push retired past admitted
        if slot.ticket.succeed(result):
            inc("veles_serving_retired_total")
            inc("veles_serving_tokens_total", len(tokens))
            self.retired += 1

    def _finish_beam(self, group) -> None:
        """Answer a beam request: rank hypotheses exactly like
        ``beam_generate`` (descending score; eos freezing already
        shaped the scores) and retire every hypothesis row."""
        order = numpy.argsort(-group.scores.astype(numpy.float64))
        best = int(order[0])
        for slot in group.slots:
            self._retire_slot(slot)
        batched_with = max(0, self.scheduler.busy_count() - 1)
        # gated on first-terminal like _finish: one retirement per
        # REQUEST, never re-counted by a late tick racing an abort
        if group.ticket.succeed({
                "tokens": [int(t) for t in group.toks[best]],
                "scores": [float(group.scores[i]) for i in order],
                "batched_with": batched_with,
                "engine": "continuous"}):
            inc("veles_serving_retired_total")
            inc("veles_serving_tokens_total", group.toks.shape[1])
            self.retired += 1

    def _abort_active(self, reason: str, code: int = 500,
                      retry_after: Optional[float] = None,
                      count_shed: bool = True) -> None:
        answered = set()
        for slot in self.scheduler.active():
            # aborted rows hand their emitted-token prefix back on the
            # ticket BEFORE the terminal: the failure answer then
            # carries {resume: ...} and a failover retry re-enters the
            # decode at tokens_done instead of token 0 (plain decode
            # modes only — spec/beam retries restart from scratch)
            if slot.mode in _STEP_MODES \
                    and (slot.tokens
                         or slot.req.get("_qos_prefix")):
                slot.ticket.set_progress(self._emitted(slot))
            self._retire_slot(slot)
            if id(slot.ticket) not in answered:
                answered.add(id(slot.ticket))
                # one shed per REQUEST, not per hypothesis row — kept
                # like-for-like with admitted/retired accounting;
                # count only a first-terminal answer (an already-
                # answered ticket must not re-count)
                first = slot.ticket.fail(reason, code=code,
                                         retry_after=retry_after)
                if count_shed and first:
                    inc("veles_shed_requests_total")

    # -- drain-by-handoff ------------------------------------------------------
    def handoff(self, reason: str = "server draining; request handed "
                                    "off with resume progress",
                timeout: float = 30.0) -> int:
        """Hand every in-flight request back to its caller: at the
        NEXT step boundary each active ticket is settled 503 +
        Retry-After with its emitted-token prefix attached
        (``error_payload()`` then carries ``resume``), so a fleet
        router re-dispatches it elsewhere with ``resume_tokens`` and
        the drain's latency is bounded by one step boundary — never
        by the longest co-tenant generation. Queued (not yet
        admitted) tickets are shed the same 503 without progress.
        Runs on the tick thread (a progress snapshot can never race a
        decode dispatch); returns the number of requests handed back
        with progress. Safe on an idle or closing engine (0)."""
        done = threading.Event()
        box = {"count": 0}
        with self.scheduler.cv:
            if self._closing or self._thread is None:
                return 0
            self._handoff = (reason, done, box)
            self.scheduler.cv.notify_all()
        if not done.wait(timeout):
            self.warning("%s: handoff timed out after %.1fs (tick "
                         "thread wedged?); the drain proceeds to the "
                         "abort path", self.name, timeout)
        return box["count"]

    def _do_handoff(self, reason: str) -> int:
        """The tick-thread half of :meth:`handoff`. The ``serve.handoff``
        fault point fires once per in-flight ticket: an injected raise
        degrades THAT ticket to a plain 503 shed (no resume progress —
        its retry re-decodes from scratch), never blocks the drain."""
        handed = 0
        answered = set()
        for slot in self.scheduler.active():
            ticket = slot.ticket
            if id(ticket) not in answered:
                answered.add(id(ticket))
                snapshot_ok = True
                try:
                    fire_fault("serve.handoff")
                except FaultInjected as e:
                    snapshot_ok = False
                    self.warning(
                        "%s: progress snapshot failed mid-drain for "
                        "%s (%s) — handing off without resume",
                        self.name, ticket.request_id, e)
                if snapshot_ok and slot.mode in _STEP_MODES:
                    ticket.set_progress(self._emitted(slot))
                if ticket.fail(reason, code=503, retry_after=1.0,
                               outcome="handoff"):
                    if ticket.progress:
                        handed += 1
                        inc("veles_handoff_requests_total")
                    else:
                        inc("veles_shed_requests_total")
            # every hypothesis/co-tenant row of the ticket retires
            self._retire_slot(slot)
        # queued-but-unadmitted tickets leave with the same answer
        # (no progress — nothing was decoded for them yet)
        shed = self.scheduler.drain(reason, code=503, retry_after=1.0)
        if shed:
            inc("veles_shed_requests_total", shed)
        return handed

    # -- jitted programs -------------------------------------------------------
    def _program(self, kind: str, bucket: Optional[int] = None):
        key = (kind, bucket)
        prog = self._progs.get(key)
        if prog is None:
            # in artifact mode the base-plane programs were installed
            # at start(); spec/beam/draft programs always build live
            builders = {"prefill": self._build_prefill,
                        "dprefill": self._build_draft_prefill,
                        "step": self._build_decode,
                        "spec": self._build_spec_round,
                        "beam": self._build_beam_step,
                        "pchunk": self._build_prefill_chunk,
                        "pagecopy": self._build_page_copy}
            jitted = (builders[kind](bucket)
                      if kind in ("prefill", "dprefill")
                      else builders[kind]())
            prog = self._progs[key] = self._instrument_live(jitted,
                                                            key)
        return prog

    @staticmethod
    def _count_tp_dispatch(call):
        """Count one ``veles_tp_dispatches_total`` per invocation —
        the TP observability seam for artifact-installed programs
        (the live path counts inside ``_instrument_live``)."""
        import functools

        @functools.wraps(call)
        def counted(*args, **kwargs):
            inc("veles_tp_dispatches_total")
            return call(*args, **kwargs)
        return counted

    def _instrument_live(self, jitted, key=None):
        """Wrap a live jitted program: every call counts one
        ``veles_decode_dispatches_total`` (the round-5 regression
        lock's counter — same contract as
        ``sampling._count_decode_dispatches``). The first call
        explicitly lowers+compiles (``jit.lower(...).compile()``, the
        ``accelerated.cost_of`` pattern) and installs the compiled
        executable for every later dispatch, so
        ``veles_serving_compile_seconds_total`` brackets ONLY the
        trace+compile — the cold-start cost the AOT artifact path
        exists to delete — never the first dispatch's execution.
        Engine programs are fixed-shape, so one compile per program is
        exact, not a heuristic."""
        box: Dict[str, object] = {}
        tp_on = self.tp > 1

        def dispatch(*args):
            inc("veles_decode_dispatches_total")
            if tp_on:
                # the TP observability seam: every dispatch that ran
                # through a shard_mapped program (gate_tp's zero-
                # leakage check asserts this NEVER moves solo)
                inc("veles_tp_dispatches_total")
            if key is not None:
                # per-program tally: the bench prefix gate prices a
                # load's prefill FLOPs as sum(cost(program) x calls)
                self.prog_calls[key] = self.prog_calls.get(key, 0) + 1
            exe = box.get("exe")
            if exe is None:
                try:
                    t0 = time.time()
                    exe = jitted.lower(*args).compile()
                except AttributeError:      # non-pjit backends
                    exe = jitted
                else:
                    self.compiled_live += 1
                    inc("veles_compiles_total")
                    inc("veles_serving_compile_seconds_total",
                        time.time() - t0)
                box["exe"] = exe
            return exe(*args)

        dispatch._jitted = jitted
        # the compiled executable, once built — bench's lossless gate
        # reads Compiled.cost_analysis() off it to prove a resumed
        # decode costs fewer FLOPs than a full redo
        dispatch.compiled = lambda: box.get("exe")
        return dispatch

    # -- AOT artifact (export/serve_artifact.py) ------------------------------
    def stack_signature(self) -> Dict:
        """Geometry the exported programs are shape-committed to: the
        abstract spec of (params tree, page pool) plus every serving
        knob the base-plane programs bake in. Export stamps it into
        the artifact; load refuses on any mismatch — a program traced
        for different shapes would fail deep inside XLA with an opaque
        error (or worse, run on reinterpreted buffers). Purely
        abstract: under ``quant_weights`` the int8 spec comes from
        ``quantize_params_spec``, so building a signature never runs
        (or counts) a calibration pass."""
        import jax

        def spec(tree):
            return jax.tree_util.tree_map(
                lambda a: [list(a.shape), str(a.dtype)], tree)

        params = params_of(self.wf)
        if self.quant_weights:
            from ..quant import quantize_params_spec
            sig_params = quantize_params_spec(params)
        else:
            sig_params = params
        stem, blocks = self.stack["stem"], self.stack["blocks"]
        d = stem.dim
        pools = []
        for blk in blocks:
            bkv = getattr(blk, "n_kv_heads", blk.n_heads)
            pools.append([bkv, d // blk.n_heads])
        return {
            "params": spec(sig_params),
            "pools": pools,
            "pool_dtype": str(self._pool_dtype(params)),
            "max_slots": self.max_slots,
            "buckets": list(self.buckets),
            "max_context": self.max_context,
            "decode_block": self.decode_block,
            # paged-pool geometry: page tables are now program inputs,
            # so the page count and size are shape commitments too
            "page_size": self.page_size,
            "pages": self.pages,
            "pages_per_slot": self.pages_per_slot,
            "quant_weights": bool(self.quant_weights),
            "quant_kv": bool(self.quant_kv),
            # the request plane's shape commitments: the decode step
            # takes the per-slot shared-page mask since v3, and the
            # chunk width shapes the (live-built) suffix program — an
            # artifact exported under other knobs refuses cleanly
            "prefix_cache": self.prefix_cache is not None,
            "prefill_chunk": int(self.prefill_chunk),
            # v5: sharded programs are committed to a mesh shape — an
            # artifact exported for one slice width refuses on another
            # (and every v4 artifact, lacking the key, refuses too)
            "tp": int(self.tp),
            "mesh": ([["model", self.tp]] if self.tp > 1 else []),
        }

    def _load_artifact(self) -> bool:
        """Install the artifact's pre-exported programs into
        ``_progs``. Any failure — unreadable package, version/geometry
        mismatch, corrupt program bytes, injected ``artifact.load``
        fault — logs a counted warning and leaves the engine on live
        jit: a bad artifact degrades startup latency, never
        availability."""
        from ..export.serve_artifact import load_serve_programs
        try:
            fire_fault("artifact.load")
            programs = load_serve_programs(self.artifact,
                                           self.stack_signature())
        except Exception as e:      # noqa: BLE001 — degrade, don't die
            inc("veles_artifact_load_failures_total")
            self.warning(
                "%s: serve-artifact %s unusable (%s: %s); serving via "
                "live jit", self.name, self.artifact,
                type(e).__name__, e)
            return False
        tp_on = self.tp > 1
        for key, call in programs.items():
            counted = _count_decode_dispatches(call)
            if tp_on:
                # artifact-installed programs are the same shard_mapped
                # executables the live path builds, so they feed the TP
                # dispatch seam too — otherwise a sharded engine serving
                # from an artifact under-reports veles_tp_dispatches_total
                counted = self._count_tp_dispatch(counted)
            self._progs[key] = counted
        self.artifact_mode = True
        inc("veles_artifact_loads_total")
        self.info("%s: AOT artifact loaded from %s (%d programs; zero "
                  "jit compiles on the serving path)", self.name,
                  self.artifact, len(programs))
        return True

    # -- paged gather/scatter helpers (trace-time) ----------------------------
    def _view(self, payload, table_row):
        """Gather one slot's logical cache view through its page-table
        row: (pages, page_size, kv, hd) + (P,) -> (P*page_size, kv,
        hd). Unallocated entries point at the sink page; its garbage
        rows sit beyond the causal mask until a write claims them."""
        import jax.numpy as jnp
        pages = jnp.take(payload, table_row, axis=0, mode="clip")
        return pages.reshape((-1,) + payload.shape[2:])

    def _row_targets(self, tables, pos, mask):
        """Per-slot (page id, in-page offset) for writing position
        ``pos`` — masked rows are pointed at the sink page, so one
        batched scatter serves every lane of the fixed-shape step."""
        import jax.numpy as jnp
        P = tables.shape[1]
        pg_idx = jnp.clip(pos // self.page_size, 0, P - 1)
        pg = jnp.take_along_axis(tables, pg_idx[:, None], axis=1)[:, 0]
        pg = jnp.where(mask > 0, pg, 0)
        off = jnp.clip(pos % self.page_size, 0, self.page_size - 1)
        return pg, off

    def _paged_row_step(self, blk, p, kp, vp, tp=1, tp_axis=None):
        """The vmap'able single-row paged decode body shared by THE
        decode step and the spec round's draft proposal: gather the
        row's logical view through its page-table row, advance one
        position with ``_block_step``, return ``(y, k_new, v_new)`` —
        only the newly written position's rows, for the batched page
        scatter. One definition so the gather/write discipline cannot
        diverge between decode modes."""
        import jax.numpy as jnp

        def row(x_row, trow, pos_row):
            ck = self._view(kp, trow)
            cv = self._view(vp, trow)
            y, ck2, cv2 = _block_step(blk, p, x_row[None, None, :],
                                      ck[None], cv[None], pos_row,
                                      tp=tp, tp_axis=tp_axis)
            return (y[0, 0],
                    jnp.take(ck2[0], pos_row, axis=0, mode="clip"),
                    jnp.take(cv2[0], pos_row, axis=0, mode="clip"))

        return row

    def _scatter_prompt(self, pool, rows, table_row, bucket, scales=None):
        """Write a bucket's prefill K or V rows page-wise into the
        pool: (bucket, kv, hd) padded up to whole pages and scattered
        at this slot's page ids (a static-length index slice — the
        program stays fixed-shape). ``scales`` rides along for the
        int8 pool's per-page sidecar."""
        import jax.numpy as jnp
        n_pages = -(-bucket // self.page_size)
        pad = n_pages * self.page_size - bucket
        if pad:
            rows = jnp.pad(rows, ((0, pad),) + ((0, 0),) * (rows.ndim - 1))
        rows = rows.reshape((n_pages, self.page_size) + rows.shape[1:])
        pool = pool.at[table_row[:n_pages]].set(rows)
        if scales is None:
            return pool
        if pad:
            scales = jnp.pad(scales, ((0, pad),))
        return pool, scales.reshape(n_pages, self.page_size)

    # -- program builders ------------------------------------------------------
    def _build_prefill(self, bucket: int):
        """One program per bucket: pad-to-``bucket`` full-window pass
        through ``_block_prefill`` writing K/V page-wise into this
        slot's pages, plus the request's FIRST sampled token, the
        last-real-position logits (the beam expansion's input) and the
        slot's private PRNG carry. Under ``quant_weights`` the program
        takes the int8 parameter tree and dequantizes at its head
        (XLA fuses the ``q·s`` into each consuming matmul); under
        ``quant_kv`` the computed float rows are quantized once —
        per-position scales in the per-page sidecars — before the
        pool write."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()
        d = stem.dim
        quant_w, quant_kv = self.quant_weights, self.quant_kv
        tp, tp_axis = self.tp, self._tp_axis

        def prefill(params, ids, t_p, slot, temp, seed_key, table_row,
                    keys, caches):
            if quant_w:
                # reconstruct in the model's own float dtype (the
                # never-quantized stem table's — read at trace time),
                # not a hard f32: a bf16 model's quantized engine must
                # run the same-dtype matmuls the float engine does
                from ..quant import dequantize_params
                params = dequantize_params(
                    params, dtype=params[stem.name]["table"].dtype)
            x = _embed_prompt(stem, pos_emb, params, ids, tp=tp,
                              tp_axis=tp_axis)
            x, blk_caches = _prefill_blocks(blocks, params, x,
                                            bucket, d, tp=tp,
                                            tp_axis=tp_axis)
            new_caches = []
            for (ck, cv), pool in zip(blk_caches, caches):
                # pad rows land in the pages too; they are causal-
                # masked for every real position and the decode steps
                # rewrite position p before the read mask reaches it
                if quant_kv:
                    from ..quant import quantize_rows_int8
                    kq, vq, ks, vs = pool
                    qk, sk = quantize_rows_int8(ck)
                    qv, sv = quantize_rows_int8(cv)
                    kq, skp = self._scatter_prompt(kq, qk[0],
                                                   table_row, bucket,
                                                   sk[0])
                    vq, svp = self._scatter_prompt(vq, qv[0],
                                                   table_row, bucket,
                                                   sv[0])
                    n_pages = -(-bucket // self.page_size)
                    ks = ks.at[table_row[:n_pages]].set(skp)
                    vs = vs.at[table_row[:n_pages]].set(svp)
                    new_caches.append((kq, vq, ks, vs))
                else:
                    kp, vp = pool
                    kp = self._scatter_prompt(kp, ck[0], table_row,
                                              bucket)
                    vp = self._scatter_prompt(vp, cv[0], table_row,
                                              bucket)
                    new_caches.append((kp, vp))
            x_last = jnp.take(x[0], t_p - 1, axis=0, mode="clip")
            logits = _head_logits(head, params, x_last, prec,
                                  tp_axis=tp_axis)
            k2 = jax.random.split(seed_key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            samp = jax.random.categorical(
                k2[1], logits / jnp.maximum(temp, _TEMP_EPS)
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, samp, greedy)
            keys = jax.lax.dynamic_update_slice(keys, k2[0][None],
                                                (slot, 0))
            return first, logits, keys, tuple(new_caches)

        if tp <= 1:
            return self._finalize(prefill, donate=(7, 8))
        from jax.sharding import PartitionSpec as P
        cs = self._caches_pspec(self.stack)
        pspec = self._params_pspec(self.stack, params_of(self.wf))
        return self._finalize(
            prefill, donate=(7, 8),
            in_specs=(pspec, P(), P(), P(), P(), P(), P(), P(), cs),
            out_specs=(P(), P(), P(), cs))

    def _build_draft_prefill(self, bucket: int):
        """The draft model's prompt pass for a speculative admission:
        writes the draft's K/V pages through the SAME page-table row
        the target uses (the slot's pages index both pools), emits
        nothing."""
        import jax
        stack = self.draft_stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks = stack["blocks"]
        d = stem.dim
        tp, tp_axis = self.tp, self._tp_axis

        def dprefill(params_d, ids, table_row, dcaches):
            x = _embed_prompt(stem, pos_emb, params_d, ids, tp=tp,
                              tp_axis=tp_axis)
            _x, blk_caches = _prefill_blocks(blocks, params_d, x,
                                             bucket, d, tp=tp,
                                             tp_axis=tp_axis)
            new_caches = []
            for (ck, cv), (kp, vp) in zip(blk_caches, dcaches):
                kp = self._scatter_prompt(kp, ck[0], table_row, bucket)
                vp = self._scatter_prompt(vp, cv[0], table_row, bucket)
                new_caches.append((kp, vp))
            return tuple(new_caches)

        if tp <= 1:
            return self._finalize(dprefill, donate=(3,))
        from jax.sharding import PartitionSpec as P
        cs = self._caches_pspec(stack)
        pspec = self._params_pspec(stack, params_of(self.draft))
        return self._finalize(
            dprefill, donate=(3,),
            in_specs=(pspec, P(), P(), cs), out_specs=cs)

    def _build_decode(self):
        """THE decode step: ``decode_block`` scan iterations of the
        vmapped single-row ``_block_step`` over every slot's gathered
        page view — one fixed shape, compiled exactly once; page
        tables arrive as DATA. The float pool gathers each row's view
        ONCE per chunk, carries it through the scan (the inner step
        runs at dense-pool cost), and scatters the pages back in one
        batched write per block at chunk end (masked rows target the
        sink page). Per-row sampling draws from each slot's private
        key stream, advanced ONLY for masked-in rows, so a row's
        noise is a pure function of its request's seed whatever other
        modes share the pool. Under ``quant_kv`` each scan iteration
        dequantizes the row's int8 view for the attention read, runs
        the SAME ``_block_step``, then quantizes only the one newly
        written position with its own fresh scale — previously
        written rows are never re-scaled, so there is no error
        accumulation across steps."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()
        quant_w, quant_kv = self.quant_weights, self.quant_kv
        tp, tp_axis = self.tp, self._tp_axis

        def embed_rows(params, tok, pos):
            from ..nn.sampling import _embed_ids
            x = _embed_ids(stem, params, tok, tp=tp, tp_axis=tp_axis)
            if pos_emb is not None:
                x = x + jnp.take(params[pos_emb.name]["table"], pos,
                                 axis=0, mode="clip")
            return x                            # (S, D)

        def step(params, tok, pos, temp, mask, tables, shared, keys,
                 caches):
            if quant_w:
                from ..quant import dequantize_params
                params = dequantize_params(
                    params, dtype=params[stem.name]["table"].dtype)

            def sample_next(tok, pos, keys, x):
                logits = _head_logits(head, params, x, prec,
                                      tp_axis=tp_axis)        # (S, V)
                # _split_rows IS the id-exactness contract: the same
                # carry/subkey convention solo and batched generate
                # use — advanced only for rows this step owns, so
                # co-tenant spec rows keep their own stream positions
                keys2, subs = _split_rows(keys)
                keys = jnp.where(mask[:, None] > 0, keys2, keys)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                samp = jax.vmap(jax.random.categorical)(
                    subs,
                    logits / jnp.maximum(temp, _TEMP_EPS)[:, None]
                ).astype(jnp.int32)
                nxt = jnp.where(temp > 0, samp, greedy)
                nxt = jnp.where(mask > 0, nxt, tok)
                return nxt, pos + (mask > 0), keys

            if not quant_kv:
                # CHUNK-VIEW formulation: gather each row's logical
                # view ONCE per chunk, carry it through the scan (the
                # per-iteration math is then exactly the dense pool's
                # — no gathers on the inner step), and scatter every
                # page back in one batched write per block at chunk
                # end. Masked rows' write-back targets the sink, so
                # co-tenant spec/beam pages are untouchable from here
                # exactly as with per-step scatters.
                views = []
                for kp, vp in caches:
                    views.append((
                        jax.vmap(lambda t, kp=kp: self._view(kp, t))(
                            tables),
                        jax.vmap(lambda t, vp=vp: self._view(vp, t))(
                            tables)))         # each (S, T, kv, hd)

                def body(carry, _):
                    tok, pos, keys, vws = carry
                    x = embed_rows(params, tok, pos)
                    new_vws = []
                    for blk, (ck, cv) in zip(blocks, vws):
                        p = params[blk.name]

                        def row(x_row, ck_row, cv_row, pos_row,
                                blk=blk, p=p):
                            y, ck2, cv2 = _block_step(
                                blk, p, x_row[None, None, :],
                                ck_row[None], cv_row[None], pos_row,
                                tp=tp, tp_axis=tp_axis)
                            return y[0, 0], ck2[0], cv2[0]

                        x, ck, cv = jax.vmap(row)(x, ck, cv, pos)
                        new_vws.append((ck, cv))
                    nxt, pos, keys = sample_next(tok, pos, keys, x)
                    return (nxt, pos, keys, tuple(new_vws)), nxt

                (tok, pos, keys, views), toks = jax.lax.scan(
                    body, (tok, pos, keys, tuple(views)), None,
                    length=self.decode_block)
                # write-back targets: masked rows AND each row's
                # leading SHARED (prefix-adopted) pages go to the sink
                # — a shared page is structurally read-only here, so a
                # retired (or live) writer can never mutate one
                keep = (mask[:, None] > 0) & (
                    jnp.arange(tables.shape[1])[None, :]
                    >= shared[:, None])
                wtab = jnp.where(keep, tables, 0).reshape(-1)  # (S*P,)
                new_caches = []
                for (kp, vp), (ck, cv) in zip(caches, views):
                    shape = (wtab.shape[0],
                             self.page_size) + kp.shape[2:]
                    kp = kp.at[wtab].set(ck.reshape(shape))
                    vp = vp.at[wtab].set(cv.reshape(shape))
                    new_caches.append((kp, vp))
                return toks, keys, tuple(new_caches)

            # int8 pool: per-step gather/scatter — the read has to
            # dequantize row-wise anyway, and only the one new
            # position may be (re)quantized per step (no error
            # accumulation), so there is no whole-view carry to win
            def body(carry, _):
                tok, pos, keys, caches = carry
                x = embed_rows(params, tok, pos)
                new_caches = []
                for blk, pool in zip(blocks, caches):
                    p = params[blk.name]
                    from ..quant import (dequantize_rows_int8,
                                         quantize_rows_int8)
                    kq, vq, ks, vs = pool

                    def rowq(x_row, trow, pos_row, blk=blk, p=p,
                             kq=kq, vq=vq, ks=ks, vs=vs):
                        ck = dequantize_rows_int8(
                            self._view(kq, trow),
                            self._view(ks, trow),
                            dtype=x_row.dtype)
                        cv = dequantize_rows_int8(
                            self._view(vq, trow),
                            self._view(vs, trow),
                            dtype=x_row.dtype)
                        y, ck2, cv2 = _block_step(
                            blk, p, x_row[None, None, :],
                            ck[None], cv[None], pos_row)
                        # quantize ONLY the newly written position
                        k_new = jnp.take(ck2[0], pos_row, axis=0,
                                         mode="clip")
                        v_new = jnp.take(cv2[0], pos_row, axis=0,
                                         mode="clip")
                        qk, sk = quantize_rows_int8(k_new[None])
                        qv, sv = quantize_rows_int8(v_new[None])
                        return (y[0, 0], qk[0], sk[0], qv[0],
                                sv[0])

                    x, kn, ksn, vn, vsn = jax.vmap(rowq)(
                        x, tables, pos)
                    pg, off = self._row_targets(tables, pos, mask)
                    kq = kq.at[pg, off].set(kn)
                    vq = vq.at[pg, off].set(vn)
                    ks = ks.at[pg, off].set(ksn)
                    vs = vs.at[pg, off].set(vsn)
                    new_caches.append((kq, vq, ks, vs))
                nxt, pos, keys = sample_next(tok, pos, keys, x)
                return (nxt, pos, keys, tuple(new_caches)), nxt

            (tok, pos, keys, caches), toks = jax.lax.scan(
                body, (tok, pos, keys, caches), None,
                length=self.decode_block)
            return toks, keys, caches            # toks (chunk, S)

        if tp <= 1:
            return self._finalize(step, donate=(7, 8))
        from jax.sharding import PartitionSpec as P
        cs = self._caches_pspec(self.stack)
        pspec = self._params_pspec(self.stack, params_of(self.wf))
        return self._finalize(
            step, donate=(7, 8),
            in_specs=(pspec, P(), P(), P(), P(), P(), P(), P(), cs),
            out_specs=(P(), P(), cs))

    def _build_spec_round(self):
        """ONE fixed-shape speculative round over the pool: the draft
        proposes ``spec_gamma`` tokens per row (a ``lax.scan`` of
        single-row steps through the draft's paged view), the target
        verifies the whole window in one ``_block_span`` pass per row,
        and ``nn/speculative``'s accept arithmetic (greedy
        prefix-match or the Leviathan rejection rule — selected
        per-row by temperature) emits up to gamma tokens. Rejected
        positions leave stale page rows behind; every read masks
        strictly by position and the next round overwrites from the
        accepted head, so stale rows are never observed — the same
        cache discipline as the solo decoder, which greedy rows
        therefore match bit-for-bit."""
        import jax
        import jax.numpy as jnp
        from ..nn.speculative import _block_span, _stochastic_accept
        from ..ops import matmul_precision
        gamma = self.spec_gamma
        tgt, drf = self.stack, self.draft_stack
        prec = matmul_precision()
        quant_w = self.quant_weights
        tp, tp_axis = self.tp, self._tp_axis

        def embed_rows(stack, params, tok, pos):
            from ..nn.sampling import _embed_ids
            x = _embed_ids(stack["stem"], params, tok, tp=tp,
                           tp_axis=tp_axis)
            pe = stack["pos_emb"]
            if pe is not None:
                x = x + jnp.take(params[pe.name]["table"], pos,
                                 axis=0, mode="clip")
            return x

        def spec_round(params_t, params_d, tok, pos, temp, smask,
                       tables, keys, caches_t, caches_d):
            if quant_w:
                from ..quant import dequantize_params
                params_t = dequantize_params(
                    params_t,
                    dtype=params_t[tgt["stem"].name]["table"].dtype)
            tau = jnp.where(temp > 0, temp, 1.0)        # (S,)
            keys2 = jax.vmap(
                lambda k: jax.random.split(k, 3))(keys)  # (S, 3, 2)
            k_carry, k_d, k_a = keys2[:, 0], keys2[:, 1], keys2[:, 2]
            keys = jnp.where(smask[:, None] > 0, k_carry, keys)

            # -- draft proposes gamma tokens ---------------------------------
            def propose(carry, j):
                dtok, caches_d = carry
                x = embed_rows(drf, params_d, dtok, pos + j)
                new_caches = []
                for blk, (kp, vp) in zip(drf["blocks"], caches_d):
                    p = params_d[blk.name]
                    x, k_new, v_new = jax.vmap(
                        self._paged_row_step(blk, p, kp, vp, tp=tp,
                                             tp_axis=tp_axis))(
                            x, tables, pos + j)
                    pg, off = self._row_targets(tables, pos + j, smask)
                    kp = kp.at[pg, off].set(k_new)
                    vp = vp.at[pg, off].set(v_new)
                    new_caches.append((kp, vp))
                logits = _head_logits(drf["head"], params_d, x, prec,
                                      tp_axis=tp_axis) / tau[:, None]
                greedy_t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                samp = jax.vmap(
                    lambda k, row: jax.random.categorical(
                        jax.random.fold_in(k, j), row)
                )(k_d, logits).astype(jnp.int32)
                nxt = jnp.where(temp > 0, samp, greedy_t)
                nxt = jnp.where(smask > 0, nxt, dtok)
                probs = jax.nn.softmax(logits, axis=-1)
                return (nxt, tuple(new_caches)), (nxt, probs)

            (_, caches_d), (d_toks, pd) = jax.lax.scan(
                propose, (tok, caches_d), jnp.arange(gamma))
            d_toks = jnp.moveaxis(d_toks, 0, 1)     # (S, gamma)
            pd = jnp.moveaxis(pd, 0, 1)             # (S, gamma, V)

            # -- target verifies the window in one pass ----------------------
            window = jnp.concatenate([tok[:, None], d_toks[:, :-1]],
                                     axis=1)        # (S, gamma)
            x = jax.vmap(
                lambda w, p0: embed_rows(
                    tgt, params_t, w, p0 + jnp.arange(gamma))
            )(window, pos)                          # (S, gamma, D)
            new_caches_t = []
            for blk, (kp, vp) in zip(tgt["blocks"], caches_t):
                p = params_t[blk.name]

                def vrow(x_row, trow, pos_row, blk=blk, p=p,
                         kp=kp, vp=vp):
                    ck = self._view(kp, trow)
                    cv = self._view(vp, trow)
                    y, ck2, cv2 = _block_span(
                        blk, p, x_row[None], ck[None], cv[None],
                        pos_row, tp=tp, tp_axis=tp_axis)
                    news_k = [jnp.take(ck2[0], pos_row + j, axis=0,
                                       mode="clip")
                              for j in range(gamma)]
                    news_v = [jnp.take(cv2[0], pos_row + j, axis=0,
                                       mode="clip")
                              for j in range(gamma)]
                    return (y[0], jnp.stack(news_k), jnp.stack(news_v))

                x, knews, vnews = jax.vmap(vrow)(x, tables, pos)
                for j in range(gamma):
                    pg, off = self._row_targets(tables, pos + j, smask)
                    kp = kp.at[pg, off].set(knews[:, j])
                    vp = vp.at[pg, off].set(vnews[:, j])
                new_caches_t.append((kp, vp))
            caches_t = tuple(new_caches_t)
            t_logits = _head_logits(tgt["head"], params_t, x, prec,
                                    tp_axis=tp_axis) \
                / tau[:, None, None]                # (S, gamma, V)

            # -- accept + emit (nn/speculative arithmetic) -------------------
            ar = jnp.arange(gamma)

            def accept(k_a_row, t_row, pd_row, d_row, temp_row):
                t_arg = jnp.argmax(t_row, axis=-1).astype(jnp.int32)
                match = d_row == t_arg
                a_g = jnp.minimum(
                    jnp.argmin(match) + gamma * match.all(), gamma)
                fix_g = t_arg[jnp.minimum(a_g, gamma - 1)]
                a_s, fix_s = _stochastic_accept(
                    k_a_row, jax.nn.softmax(t_row, axis=-1), pd_row,
                    d_row)
                a = jnp.where(temp_row > 0, a_s, a_g)
                fix = jnp.where(temp_row > 0, fix_s, fix_g)
                out_vec = jnp.where(ar < a, d_row,
                                    jnp.where(ar == a, fix, 0))
                n_emit = jnp.minimum(a + 1, gamma)
                new_tok = jnp.where(a < gamma, fix, d_row[gamma - 1])
                return a, out_vec, n_emit, new_tok

            a, out_vec, n_emit, new_tok = jax.vmap(accept)(
                k_a, t_logits, pd, d_toks, temp)
            n_emit = jnp.where(smask > 0, n_emit, 0)
            a = jnp.where(smask > 0, a, 0)
            new_tok = jnp.where(smask > 0, new_tok, tok)
            return (out_vec, n_emit, a, new_tok, keys, caches_t,
                    caches_d)

        if tp <= 1:
            return self._finalize(spec_round, donate=(7, 8, 9))
        from jax.sharding import PartitionSpec as P
        cs_t = self._caches_pspec(tgt)
        cs_d = self._caches_pspec(drf)
        pspec_t = self._params_pspec(tgt, params_of(self.wf))
        pspec_d = self._params_pspec(drf, params_of(self.draft))
        return self._finalize(
            spec_round, donate=(7, 8, 9),
            in_specs=(pspec_t, pspec_d, P(), P(), P(), P(), P(), P(),
                      cs_t, cs_d),
            out_specs=(P(), P(), P(), P(), P(), cs_t, cs_d))

    def _build_prefill_chunk(self):
        """ONE fixed-shape suffix/chunk prefill shared by prefix-cache
        adoption and chunked prefill: ``_chunk`` prompt tokens at
        positions ``p0..p0+C-1`` for a single slot, attending over the
        slot's gathered page view (adopted prefix K/V included).

        Id-exactness is arithmetic, not luck: the attention reproduces
        ``attention_reference``'s EXACT op order — einsum in the model
        dtype, f32 cast then ``* scale``, -1e30 mask, ``exp(s-max)``
        softmax, value product with weights cast back to the model
        dtype — so a chunked (or prefix-matched) prompt's layer
        outputs are bit-identical to the monolithic bucketed pass
        (masked view positions contribute EXACT zeros whatever the
        padded length; ``_chunk_kernel_safe`` keeps flash-crossover
        buckets on the monolithic plane). Chunk K/V rows scatter
        per-position through the page table (positions beyond the
        table target the sink; pad positions past ``t_p`` are
        rewritten by the decode step before any read mask reaches
        them). The FINAL chunk samples the request's first token with
        the bucketed prefill's exact seed-key convention and installs
        the slot's PRNG carry; non-final chunks leave ``keys``
        untouched."""
        import jax
        import jax.numpy as jnp
        from ..nn.attention import expand_kv
        from ..nn.speculative import _rope_span
        from ..nn.transformer import block_ffn, block_norm
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()
        d = stem.dim
        C = self._chunk
        P = self.page_size
        quant_w = self.quant_weights
        tp, tp_axis = self.tp, self._tp_axis

        def pchunk(params, ids, p0, t_p, slot, temp, seed_key,
                   table_row, final, keys, caches):
            if quant_w:
                from ..quant import dequantize_params
                params = dequantize_params(
                    params, dtype=params[stem.name]["table"].dtype)
            x = _embed_prompt(stem, pos_emb, params, ids[None],
                              pos0=p0, tp=tp,
                              tp_axis=tp_axis)         # (1, C, D)
            pos_idx = p0 + jnp.arange(C)
            pg = jnp.take(table_row, pos_idx // P, mode="fill",
                          fill_value=0)
            off = pos_idx % P
            new_caches = []
            for blk, (kp, vp) in zip(blocks, caches):
                p = params[blk.name]
                h = blk.n_heads // tp
                kv = getattr(blk, "n_kv_heads", blk.n_heads) // tp
                hd = d // blk.n_heads
                a_in = block_norm(jnp, blk, p, x, "ln1")
                q = jnp.dot(a_in, p["wq"],
                            precision=prec).reshape(1, C, h, hd)
                k = jnp.dot(a_in, p["wk"],
                            precision=prec).reshape(1, C, kv, hd)
                v = jnp.dot(a_in, p["wv"],
                            precision=prec).reshape(1, C, kv, hd)
                if blk.rope:
                    base = getattr(blk, "rope_base", 10000.0)
                    q = _rope_span(jnp, q, p0, base)
                    k = _rope_span(jnp, k, p0, base)
                # gathered view + C zero rows: dynamic_update_slice
                # then never clamp-shifts over real rows, and the
                # extra keys sit behind the causal mask as exact zeros
                ck = self._view(kp, table_row)
                cv = self._view(vp, table_row)
                zpad = jnp.zeros((C,) + ck.shape[1:], ck.dtype)
                ck = jax.lax.dynamic_update_slice(
                    jnp.concatenate([ck, zpad]), k[0], (p0, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    jnp.concatenate([cv, zpad]), v[0], (p0, 0, 0))
                k_full = expand_kv(jnp, ck[None], h)
                v_full = expand_kv(jnp, cv[None], h)
                scale = 1.0 / (hd ** 0.5)
                s = jnp.einsum("bqhd,bkhd->bhqk", q,
                               k_full).astype(jnp.float32) * scale
                t_idx = jnp.arange(k_full.shape[1])[None, :]
                q_idx = pos_idx[:, None]
                valid = t_idx <= q_idx
                win = getattr(blk, "window", None)
                if win:
                    valid = valid & (t_idx > q_idx - win)
                s = jnp.where(valid[None, None], s, -1e30)
                w = jnp.exp(s - s.max(axis=-1, keepdims=True))
                w = w / w.sum(axis=-1, keepdims=True)
                o = jnp.einsum("bhqk,bkhd->bqhd", w.astype(q.dtype),
                               v_full).reshape(1, C, h * hd)
                proj = jnp.dot(o, p["wo"], precision=prec)
                if tp_axis is not None:
                    proj = jax.lax.psum(proj, tp_axis)
                x = x + proj
                f_in = block_norm(jnp, blk, p, x, "ln2")
                x = x + block_ffn(jnp, blk, p, f_in, prec,
                                  tp_axis=tp_axis)
                kp = kp.at[pg, off].set(k[0])
                vp = vp.at[pg, off].set(v[0])
                new_caches.append((kp, vp))
            x_last = jnp.take(x[0], t_p - 1 - p0, axis=0, mode="clip")
            logits = _head_logits(head, params, x_last, prec,
                                  tp_axis=tp_axis)
            k2 = jax.random.split(seed_key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            samp = jax.random.categorical(
                k2[1], logits / jnp.maximum(temp, _TEMP_EPS)
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, samp, greedy)
            upd = jax.lax.dynamic_update_slice(keys, k2[0][None],
                                               (slot, 0))
            keys = jnp.where(final > 0, upd, keys)
            return first, keys, tuple(new_caches)

        if tp <= 1:
            return self._finalize(pchunk, donate=(9, 10))
        from jax.sharding import PartitionSpec as PS
        cs = self._caches_pspec(self.stack)
        pspec = self._params_pspec(self.stack, params_of(self.wf))
        return self._finalize(
            pchunk, donate=(9, 10),
            in_specs=(pspec, PS(), PS(), PS(), PS(), PS(), PS(), PS(),
                      PS(), PS(), cs),
            out_specs=(PS(), PS(), cs))

    def _build_page_copy(self):
        """Clone one slot's pages into another slot's pages — the
        beam sibling admission: every hypothesis row starts as an
        identical copy of the lead row's prompt cache, so one
        page-granular device copy replaces ``beam_width - 1``
        redundant prefill dispatches. Unallocated table entries alias
        the sink page on both sides (garbage copied to garbage, never
        read). Beam never serves the int8 pool, so the pools here are
        always float ``(k, v)`` pairs."""
        import jax
        import jax.numpy as jnp

        def pagecopy(src_row, dst_row, caches):
            # page ids are LOGICAL: under tp each shard copies its own
            # kv-head slice of the same page rows — the body is
            # axis-0 take/set, transparently shard-agnostic
            new_caches = []
            for kp, vp in caches:
                kp = kp.at[dst_row].set(
                    jnp.take(kp, src_row, axis=0, mode="clip"))
                vp = vp.at[dst_row].set(
                    jnp.take(vp, src_row, axis=0, mode="clip"))
                new_caches.append((kp, vp))
            return tuple(new_caches)

        if self.tp <= 1:
            return self._finalize(pagecopy, donate=(2,))
        from jax.sharding import PartitionSpec as P
        cs = self._caches_pspec(self.stack)
        return self._finalize(pagecopy, donate=(2,),
                              in_specs=(P(), P(), cs), out_specs=cs)

    def _build_beam_step(self):
        """ONE fixed-shape beam step over every group: each hypothesis
        runs the single-row step over its paged view; the group-level
        top-k (f32 log_softmax, frozen-eos lanes, flat ``top_k`` over
        W·V — ``nn/beam.py``'s exact arithmetic) picks the surviving
        (parent, token) pairs, and the cache reorder lands as a
        page-granular copy: every child's pages are rewritten from its
        parent's updated view through the page tables in one batched
        scatter. Masked groups read real pages but write the sink."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, pos_emb = stack["stem"], stack["pos_emb"]
        blocks, head = stack["blocks"], stack["head"]
        prec = matmul_precision()
        quant_w = self.quant_weights
        W, P = self.beam_width, self.pages_per_slot
        page = self.page_size
        tp, tp_axis = self.tp, self._tp_axis

        def beam_step(params, cur, pos, scores, finished, eosv, gmask,
                      tables_g, caches):
            if quant_w:
                from ..quant import dequantize_params
                params = dequantize_params(
                    params, dtype=params[stem.name]["table"].dtype)
            G = cur.shape[0]
            flat_tab = tables_g.reshape(G * W, P)
            flat_cur = cur.reshape(G * W)
            flat_pos = jnp.repeat(pos, W)
            from ..nn.sampling import _embed_ids
            x = _embed_ids(stem, params, flat_cur, tp=tp,
                           tp_axis=tp_axis)
            if pos_emb is not None:
                x = x + jnp.take(params[pos_emb.name]["table"],
                                 flat_pos, axis=0, mode="clip")
            views = []                      # per block: updated views
            for blk in blocks:
                p = params[blk.name]
                kp, vp = caches[len(views)]

                def row(x_row, trow, pos_row, blk=blk, p=p,
                        kp=kp, vp=vp):
                    ck = self._view(kp, trow)
                    cv = self._view(vp, trow)
                    y, ck2, cv2 = _block_step(
                        blk, p, x_row[None, None, :],
                        ck[None], cv[None], pos_row,
                        tp=tp, tp_axis=tp_axis)
                    return y[0, 0], ck2[0], cv2[0]

                x, ck_new, cv_new = jax.vmap(row)(x, flat_tab,
                                                  flat_pos)
                views.append((ck_new, cv_new))  # (GW, T, kv, hd)
            logits = _head_logits(head, params, x, prec,
                                  tp_axis=tp_axis)     # (GW, V)
            v = logits.shape[-1]
            logp = jax.nn.log_softmax(
                logits.astype(jnp.float32)).reshape(G, W, v)

            def group_topk(logp_g, scores_g, fin_g, eos_g):
                frozen = jnp.full((v,), -jnp.inf).at[eos_g].set(0.0)
                logp_g = jnp.where(fin_g[:, None], frozen[None, :],
                                   logp_g)
                joint = scores_g[:, None] + logp_g       # (W, V)
                flat, idx = jax.lax.top_k(joint.reshape(-1), W)
                parent = idx // v
                tok = (idx % v).astype(jnp.int32)
                fin = fin_g[parent] | (tok == eos_g)
                return tok, parent, flat, fin

            tok, parent, new_scores, new_fin = jax.vmap(group_topk)(
                logp, scores, finished, eosv)
            # cache reorder: child pages <- parent's updated view,
            # page-granular, one batched scatter per block
            flat_parent = (parent
                           + (jnp.arange(G) * W)[:, None]).reshape(
                               G * W)
            write_tab = jnp.where(
                gmask.astype(bool)[:, None, None], tables_g, 0
            ).reshape(G * W * P)
            new_caches = []
            for (kp, vp), (ck_new, cv_new) in zip(caches, views):
                sel_k = jnp.take(ck_new, flat_parent, axis=0,
                                 mode="clip")
                sel_v = jnp.take(cv_new, flat_parent, axis=0,
                                 mode="clip")
                shape = (G * W * P, page) + sel_k.shape[2:]
                kp = kp.at[write_tab].set(sel_k.reshape(shape))
                vp = vp.at[write_tab].set(sel_v.reshape(shape))
                new_caches.append((kp, vp))
            return tok, parent, new_scores, new_fin, tuple(new_caches)

        if tp <= 1:
            return self._finalize(beam_step, donate=(8,))
        from jax.sharding import PartitionSpec as PS
        cs = self._caches_pspec(self.stack)
        pspec = self._params_pspec(self.stack, params_of(self.wf))
        return self._finalize(
            beam_step, donate=(8,),
            in_specs=(pspec, PS(), PS(), PS(), PS(), PS(), PS(), PS(),
                      cs),
            out_specs=(PS(), PS(), PS(), PS(), cs))
