"""O(1)-state serving lane: a recurrent slot pool for SSM/linear-
attention and LSTM stacks.

The paged engine's unit of per-slot memory is a page table over an
O(context) KV pool. A recurrent stack (``nn/ssm.py``'s SSMBlock,
``nn/rnn.py``'s LSTM/RNN) needs neither: its whole past is a FIXED
per-slot state tensor (per head an ``e x e`` matrix, or an LSTM's
``(h, c)`` pair), so a slot costs constant HBM whatever the context —
the "portable O(1) autoregressive caching" half of PAPERS.md's
"Compiler-First State Space Duality and Portable O(1) Autoregressive
Caching for Inference". This module hosts that lane on the SAME
request plane as :class:`~veles_tpu.serving.engine.ContinuousEngine`:

- **two proven-equivalent modes, ONE step body**: prefill runs the
  chunked parallel scan (``lax.scan`` of ``step_state`` over
  ``page_size``-token chunks), decode runs the single application of
  the same body — bit-identity between the modes is structural (see
  nn/ssm.py), so a scanned prompt and a decoded continuation cannot
  drift;
- **pageless slots**: the :class:`SlotScheduler` runs with
  ``page_pool=None`` (``slot_kind="state"``) — admission never
  reserves pages, decode can never shed on page exhaustion, and the
  pool's HBM is ``max_slots x state_bytes_per_slot``, constant in
  sequence length. At equal HBM this serves a multiple of the paged
  transformer pool's concurrent slots (the bench ``o1state`` gate
  stamps the multiplier);
- **state-checkpoint prefix cache**: the prefix-cache analog for a
  lane with no pages. Prefill snapshots the slot's state at every
  ``page_size``-token block boundary into a radix
  :class:`~veles_tpu.serving.pages.StateCache`; a later admission
  sharing the prefix adopts the deepest snapshot COPY-ON-WRITE (one
  host→device row upload) and scans only the suffix — a shared
  system prompt costs one snapshot, not a re-scan per request;
- **the whole request plane rides along**: SSE streaming
  (``Ticket.push_tokens`` at every step boundary), token-level
  failover resume (``fold_resume`` + ``advanced_prng_key`` — restore
  the nearest checkpoint, re-scan the gap, id-exact), drain-by-
  handoff, the ``serve.replica_death`` / ``serve.decode_step`` chaos
  sites plus the lane's own ``serve.state_restore`` /
  ``serve.state_checkpoint`` fault points, and the AOT serve-artifact
  (labels ``rscan``/``rstep``, ARTIFACT_VERSION 4) for a zero-compile
  cold start.

Exactly TWO fixed-shape jitted programs serve the lane — the chunk
scan and the decode step — co-tenant with (and shaped like) the paged
tick, so the jit cache stays bounded however long the prompts get.

Operator guide: docs/services.md "O(1)-state serving".
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy

from ..error import VelesError
from ..logger import Logger
from ..nn.sampling import (_embed_prompt, _head_logits,
                           _split_rows, params_of)
from ..nn.ssm import mask_keep
from ..resilience import health
from ..resilience.faults import FaultInjected, fire as fire_fault
from ..telemetry.counters import inc
from ..telemetry.spans import span
from .engine import (advanced_prng_key, fold_resume,   # noqa: F401
                     _TEMP_EPS, _STEP_MODES)
from .pages import StateCache


def split_recurrent_stack(forwards) -> Dict:
    """Partition a workflow's forwards into the recurrent serving
    stack: ``Embedding`` → recurrent units (anything exposing the
    ``init_state``/``step_state``/``scan_state`` protocol — SSMBlock,
    LSTM, RNN) → ``LMHead``. Raises :class:`VelesError` on any other
    shape — notably a ``PositionalEmbedding`` anywhere in the chain:
    a constant-size state carries no notion of absolute position, so
    a position-dependent stack cannot ride the O(1) lane."""
    from ..nn.transformer import Embedding, LMHead
    units = list(forwards or ())
    names = [type(u).__name__ for u in units]

    def reject():
        raise VelesError(
            "O(1)-state serving supports Embedding → "
            "(SSMBlock|LSTM|RNN)* → LMHead chains; found %s"
            % (names or "no forwards"))

    if len(units) < 2 or not isinstance(units[0], Embedding) \
            or not isinstance(units[-1], LMHead):
        reject()
    blocks = units[1:-1]
    for blk in blocks:
        if not (hasattr(blk, "step_state")
                and hasattr(blk, "init_state")
                and hasattr(blk, "scan_state")):
            reject()
    return {"stem": units[0], "blocks": blocks, "head": units[-1]}


class RecurrentEngine(Logger):
    """In-flight batching over a persistent fixed-size state pool.

    ``wf`` is a recurrent generation workflow (``Embedding`` →
    recurrent units → ``LMHead``, validated at construction).
    ``page_size`` is the lane's CHECKPOINT INTERVAL: prefill scans in
    ``page_size``-token chunks and snapshots the state at each full
    chunk's boundary — the same knob that sizes the paged pool's
    blocks keeps the two lanes' prefix granularity comparable.
    ``decode_block`` fuses that many decode steps into one dispatch
    (``lax.scan``), exactly like the paged tick.
    """

    def __init__(self, wf, max_slots: int = 8,
                 max_context: int = 640, decode_block: int = 1,
                 page_size: Optional[int] = None,
                 state_cache: Optional[bool] = None,
                 artifact: Optional[str] = None,
                 name: str = "serving") -> None:
        super().__init__()
        from ..config import root
        from .scheduler import SlotScheduler
        self.wf = wf
        self.name = name
        serving_cfg = root.common.serving
        self.artifact = str(
            serving_cfg.get("artifact", "")
            if artifact is None else (artifact or ""))
        self.artifact_mode = False
        self.compiled_live = 0
        # raises VelesError on anything but a recurrent generation
        # stack — the GenerationAPI fallback chain keys off this
        self.stack = split_recurrent_stack(
            list(getattr(wf, "forwards", ()) or ()))
        self.max_slots = int(max_slots)
        self.max_context = int(max_context)
        self.decode_block = max(1, int(decode_block))
        # wire defaults for the /generate parser: the O(1) lane has no
        # speculative/beam programs, but clients omitting gamma/beam
        # must still parse — accepts() then rejects those modes to the
        # window worker
        self.spec_gamma = int(serving_cfg.get("spec_gamma", 4))
        self.beam_width = int(serving_cfg.get("beam_width", 4))
        self.page_size = int(
            serving_cfg.get("page_size", 16)
            if page_size is None else page_size)
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        want_cache = bool(
            serving_cfg.get("state_cache", False)
            if state_cache is None else state_cache)
        self.state_cache: Optional[StateCache] = (
            StateCache(self.page_size,
                       serving_cfg.get("state_cache_blocks", None))
            if want_cache else None)
        # pageless admission: no page pool, so the scheduler's ledger
        # paths are structurally inert — admission is on free SLOTS
        # only and page exhaustion cannot exist on this lane. One
        # bucket (= max_context): chunked scanning serves any prompt
        # length, so there is no prefill-program count to bound with
        # a bucket ladder
        self.scheduler = SlotScheduler(self.max_slots,
                                       (self.max_context,),
                                       self.max_context,
                                       page_pool=None,
                                       slot_kind="state")
        #: QoS plane (docs/services.md "Overload & QoS"): off by
        #: default — the feature-off lock keeps admission strict FIFO
        #: and the preemption path structurally unreachable
        self.qos = bool(serving_cfg.get("qos", False))
        self.scheduler.qos = self.qos
        self._pressure_fn = lambda: (self.scheduler.queue_depth(),
                                     max(8, self.max_slots * 8))
        self.preemptions = 0
        self.preempted_tokens = 0
        self._progs: Dict = {}
        self._params = None
        self._states = None
        self._keys = None
        self._tok = numpy.zeros(self.max_slots, numpy.int32)
        self._pos = numpy.zeros(self.max_slots, numpy.int32)
        self._temp = numpy.zeros(self.max_slots, numpy.float32)
        self._thread: Optional[threading.Thread] = None
        self._closing = False
        self._handoff: Optional[Tuple] = None
        #: replica-death hook (set by GenerationAPI) — same contract
        #: as the paged engine's
        self.on_death = None
        self.admitted = 0
        self.retired = 0
        self.peak_slots = 0
        self.prog_calls: Dict = {}
        #: requests that adopted a state checkpoint / chunk dispatches
        #: run / lane counters mirrored as gauges for stats()
        self.prefix_requests = 0
        self.chunk_dispatches = 0
        self.state_restores = 0
        self.state_rescans = 0
        self.state_checkpoints = 0

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RecurrentEngine":
        if self._thread is not None:
            return self
        if self.artifact and not self.artifact_mode:
            self._load_artifact()
        if self.qos:
            from .overload import set_pressure_provider
            set_pressure_provider(self._pressure_fn)
        self._closing = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=self.name + ".engine")
        self._thread.start()
        from . import register_engine
        register_engine(self)
        self.info("%s: O(1)-state serving up (slots=%d max_context=%d "
                  "decode_block=%d checkpoint_every=%d%s)",
                  self.name, self.max_slots, self.max_context,
                  self.decode_block, self.page_size,
                  " +state_cache" if self.state_cache is not None
                  else "")
        return self

    def stop(self) -> None:
        with self.scheduler.cv:
            self._closing = True
            self.scheduler.cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        pending_handoff, self._handoff = self._handoff, None
        if pending_handoff is not None:
            pending_handoff[1].set()
        self.scheduler.drain("server shutting down")
        self._abort_active("server shutting down", code=503,
                           retry_after=5.0, count_shed=False)
        if self.state_cache is not None:
            self.state_cache.clear()
        from .overload import clear_pressure_provider
        clear_pressure_provider(self._pressure_fn)
        from . import unregister_engine
        unregister_engine(self)

    # -- intake --------------------------------------------------------------
    def accepts(self, req: Dict) -> Optional[str]:
        """None when the state pool can serve ``req``; otherwise the
        reason (caller falls back to the window-coalescing path)."""
        t_p, n_new = len(req["prompt"]), int(req["n_new"])
        mode = str(req.get("mode", "greedy"))
        if mode not in _STEP_MODES:
            # fail CLOSED like the paged engine: an unknown (or
            # spec/beam) mode has no fixed-shape program here
            return ("O(1)-state pool serves greedy/sample only "
                    "(mode=%s)" % mode)
        if t_p < 1:
            return "empty prompt"
        reason = self.scheduler.reject_reason(t_p, n_new, mode=mode)
        if reason:
            return reason
        if 0 < float(req.get("temperature", 0.0)) < _TEMP_EPS:
            return ("temperature %g below the engine's %g resolution"
                    % (req["temperature"], _TEMP_EPS))
        return None

    def submit(self, req: Dict, ticket,
               max_queue: Optional[int] = None,
               checked: bool = False) -> bool:
        """Enqueue one request; False = queue bound hit or closing
        (caller sheds). Same contract as the paged engine's."""
        if not checked:
            reason = self.accepts(req)
            if reason is not None:
                ticket.fail(reason, code=400)
                return True
        with self.scheduler.cv:
            if self._closing:
                return False
            return self.scheduler.push(req, ticket, max_queue)

    def serve(self, reqs: List[Dict], timeout: float = 300.0
              ) -> List[List[int]]:
        """Synchronous convenience (tests / bench): submit every
        request, wait, return each token list; raises on any error."""
        from .scheduler import Ticket
        tickets = [Ticket() for _ in reqs]
        for req, ticket in zip(reqs, tickets):
            if not self.submit(req, ticket):
                raise VelesError("serving queue full")
        out = []
        for req, ticket in zip(reqs, tickets):
            if not ticket.event.wait(timeout):
                raise VelesError("serving timed out for %r" % (req,))
            if ticket.error is not None:
                raise VelesError("serving failed: %s" % ticket.error)
            out.append(ticket.result["tokens"])
        return out

    # -- observability -------------------------------------------------------
    def state_bytes_per_slot(self) -> int:
        """HBM one slot's recurrent state occupies — CONSTANT in
        sequence length (the lane's whole point; the bench o1state
        gate proves it flat vs token count)."""
        if self._states is not None:
            return sum(int(leaf.nbytes) for st in self._states
                       for leaf in st.values()) // self.max_slots
        import jax.numpy as jnp
        dtype = jnp.dtype(jnp.float32)
        total = 0
        for blk in self.stack["blocks"]:
            for shape in blk.state_shapes(1).values():
                total += int(numpy.prod(shape)) * dtype.itemsize
        return total

    def stats(self) -> Dict[str, float]:
        pool_bytes = (0 if self._states is None else
                      sum(int(leaf.nbytes) for st in self._states
                          for leaf in st.values()))
        cache_stats = (self.state_cache.stats()
                       if self.state_cache is not None
                       else {"blocks": 0, "bytes": 0})
        return {
            "slots": self.max_slots,
            "slots_busy": self.scheduler.busy_count(),
            "peak_slots": self.peak_slots,
            "queue_depth": self.scheduler.queue_depth(),
            "admitted": self.admitted,
            "retired": self.retired,
            "qos": int(self.qos),
            "preemptions": self.preemptions,
            "preempted_tokens": self.preempted_tokens,
            "programs": len(self._progs),
            # the slot-kind discriminator: /metrics renders
            # veles_serving_pages_* rows ONLY for paged engines, so a
            # pageless replica can never skew the fleet's page math
            # (the router ranks on slot occupancy, comparable across
            # kinds)
            "slot_kind": "state",
            "pages_total": 0,
            "pages_in_use": 0,
            "page_size": self.page_size,
            "page_fragmentation": 0.0,
            "prefix_cache": int(self.state_cache is not None),
            "prefix_blocks": cache_stats["blocks"],
            "prefix_requests": self.prefix_requests,
            "prefill_chunk": self.page_size,
            "chunk_dispatches": self.chunk_dispatches,
            "prefilling": 0,
            "prefill_stall_seconds": 0.0,
            "artifact_mode": int(self.artifact_mode),
            "quant_weights": 0,
            "quant_kv": 0,
            "compiled_live": self.compiled_live,
            # the O(1) claim as a gauge: per-slot state HBM, constant
            # however long each slot has decoded
            "kv_pool_bytes": pool_bytes,
            "state_bytes_per_slot": self.state_bytes_per_slot(),
            "state_cache_blocks": cache_stats["blocks"],
            "state_cache_bytes": cache_stats["bytes"],
            "state_checkpoints": self.state_checkpoints,
            "state_restores": self.state_restores,
            "state_rescans": self.state_rescans,
        }

    @property
    def closing(self) -> bool:
        return self._closing

    @property
    def programs_built(self) -> int:
        return len(self._progs)

    def programs_bound(self) -> int:
        """The hard ceiling on :attr:`programs_built`: the chunk scan
        and the decode step. TWO, whatever the traffic — chunked
        scanning needs no bucket ladder."""
        return 2

    # -- worker --------------------------------------------------------------
    def _loop(self) -> None:
        hb = "serving.%s" % self.name
        fail_streak = 0
        try:
            while True:
                with self.scheduler.cv:
                    while (not self.scheduler._queue
                           and self.scheduler.busy_count() == 0
                           and self._handoff is None
                           and not self._closing):
                        self.scheduler.cv.wait(timeout=5.0)
                        if not self._closing:
                            health.heartbeats.beat(hb)
                    if self._closing:
                        return
                health.heartbeats.beat(hb)
                try:
                    self._tick()
                    fail_streak = 0
                except Exception:     # noqa: BLE001 — serve, don't die
                    fail_streak += 1
                    self.exception("%s: serving tick failed", self.name)
                    self._abort_active("internal serving error",
                                       code=500, count_shed=False)
                    self._reset_pool()
                    from .scheduler import shed_expired
                    shed_expired(self.scheduler.expire_queued())
                    if not self._closing:
                        time.sleep(min(1.0, 0.05 * (2 ** fail_streak)))
        finally:
            health.heartbeats.unregister(hb)

    def _reset_pool(self) -> None:
        self._states = self._keys = None
        self._params = None

    def _tick(self) -> None:
        """One step boundary: admit into free slots (each admission
        scans its whole prompt chunk-by-chunk), then advance every
        busy row by one fixed-shape decode dispatch."""
        pending_handoff = self._handoff
        if pending_handoff is not None:
            self._handoff = None
            reason, done, box = pending_handoff
            try:
                box["count"] = self._do_handoff(reason)
            finally:
                done.set()
            return
        if self.scheduler.busy_count():
            try:
                fire_fault("serve.replica_death")
            except FaultInjected:
                self.warning("%s: injected replica death mid-decode — "
                             "settling in-flight tickets with resume "
                             "progress and tearing the front down",
                             self.name)
                self._abort_active(
                    "replica died mid-decode", code=503,
                    retry_after=1.0, count_shed=False)
                death = self.on_death
                if death is not None:
                    death()
                return
        params = self._params
        if params is None or self.scheduler.busy_count() == 0:
            params = self._params = params_of(self.wf)
        self._ensure_pool(params)
        from .scheduler import shed_expired
        if self.qos:
            self._preempt_for_interactive()
        admissions, expired = self.scheduler.take_admissions()
        shed_expired(expired)
        for slot in admissions:
            if self.scheduler.slots[slot.idx] is not slot:
                continue
            try:
                self._admit(params, slot)
            except Exception as e:    # noqa: BLE001 — answer, don't die
                self._retire_slot(slot)
                slot.ticket.fail("%s: %s" % (type(e).__name__, e),
                                 code=500)
                # the chunk program DONATES the state pool: a dead
                # dispatch may have consumed the co-tenants' rows
                # with it — shed and rebuild rather than decode on
                # possibly-dead buffers
                self.exception("%s: admission failed; resetting the "
                               "state pool", self.name)
                self._abort_active("serving pool reset after a failed "
                                   "admission", code=503,
                                   retry_after=1.0)
                self._reset_pool()
                return
        self.peak_slots = max(self.peak_slots,
                              self.scheduler.busy_count())
        try:
            if self.scheduler.active():
                self._decode(params)
        except FaultInjected as e:
            self._abort_active(str(e), code=503, retry_after=1.0)

    # -- QoS preemption --------------------------------------------------------
    @staticmethod
    def _emitted(slot) -> List[int]:
        """Every token the client's ORIGINAL request has produced so
        far: internally-folded preempt prefixes plus this admission's
        tokens. All progress/result reporting goes through this so
        preemption stays invisible to the wire."""
        return list(slot.req.get("_qos_prefix", ())) + list(slot.tokens)

    def _preempt_victims(self, need: int) -> List:
        from .overload import request_priority
        victims = [s for s in self.scheduler.active()
                   if s.group is None and s.mode in _STEP_MODES
                   and request_priority(s.req) == "batch"
                   and s.prefilled is None and s.tokens
                   and len(s.tokens) < s.n_new]
        # evict the least-invested first (fewest tokens to re-fold)
        victims.sort(key=lambda s: (len(s.tokens), s.idx))
        return victims[:max(0, need)]

    def _preempt_for_interactive(self) -> None:
        """Free state slots for queued interactive requests by
        requeueing batch rows at this step boundary with their resume
        payload — same fold_resume/advanced_prng_key machinery as
        failover, so the preempted decode finishes bit-identical."""
        from .overload import qos_preempt_enabled, request_priority
        if not qos_preempt_enabled():
            return
        with self.scheduler.cv:
            waiting = sum(1 for req, _t in self.scheduler._queue
                          if request_priority(req) == "interactive")
            free = len(self.scheduler._free)
        if waiting <= free:
            return
        for slot in self._preempt_victims(waiting - free):
            emitted = self._emitted(slot)
            resumed = fold_resume(slot.req, slot.tokens)
            # fold_resume records only THIS fold's length; the PRNG
            # re-entry point is every token ever emitted, so a twice-
            # preempted request must accumulate
            resumed["resume_k"] = (int(slot.req.get("resume_k", 0)
                                       or 0) + len(slot.tokens))
            resumed["_qos_prefix"] = emitted
            resumed["_requeued"] = True
            slot.ticket.set_progress(emitted)
            self._retire_slot(slot)
            self.scheduler.push(resumed, slot.ticket)
            self.preemptions += 1
            self.preempted_tokens += len(slot.tokens)
            inc("veles_qos_preemptions_total")
            inc("veles_qos_preempted_tokens_total", len(slot.tokens))
            self.debug("%s: preempted batch slot %d at %d tokens for "
                       "an interactive admission (request %s)",
                       self.name, slot.idx, len(slot.tokens),
                       slot.ticket.request_id)

    def _ensure_pool(self, params) -> None:
        if self._states is not None:
            return
        import jax.numpy as jnp
        stem = self.stack["stem"]
        dtype = params[stem.name]["table"].dtype
        self._states = tuple(blk.init_state(self.max_slots, dtype)
                             for blk in self.stack["blocks"])
        self._keys = jnp.zeros((self.max_slots, 2), jnp.uint32)

    def _set_state_row(self, idx: int, snap) -> None:
        """Initialize one slot's state row: zeros for a cold scan, or
        an adopted checkpoint snapshot (COPY-ON-WRITE: the host
        snapshot is uploaded, never aliased — the cache's copy stays
        bit-untouched however the slot decodes on)."""
        import jax.numpy as jnp
        new = []
        for bi, st in enumerate(self._states):
            row = {}
            for k, leaf in st.items():
                if snap is None:
                    val = jnp.zeros(leaf.shape[1:], leaf.dtype)
                else:
                    val = jnp.asarray(snap[bi][k][0], leaf.dtype)
                row[k] = leaf.at[idx].set(val)
            new.append(row)
        self._states = tuple(new)

    # -- admission ------------------------------------------------------------
    def _admit(self, params, slot) -> None:
        import jax
        import jax.numpy as jnp
        prompt = slot.req["prompt"]
        t_p = slot.t_p
        C = self.page_size
        # -- checkpoint restore (the prefix-cache analog) ------------
        # match over prompt[:-1]: at least one token must scan (the
        # final chunk emits the first token's logits), so a full-
        # prompt match adopts the PREVIOUS boundary's snapshot and
        # re-scans the tail — the state-lane shape of the paged
        # cache's copy-on-write last page
        start, snap = 0, None
        if self.state_cache is not None:
            try:
                # raise = injected checkpoint loss, corrupt = injected
                # index rot: both DEGRADE to a shorter/empty match and
                # a longer re-scan — token equality inside match() is
                # the authority, so a rotten index can never restore a
                # wrong state
                corrupting = fire_fault("serve.state_restore")
                start, snap = self.state_cache.match(
                    prompt[:t_p - 1], corrupt=corrupting)
            except FaultInjected as e:
                self.warning("%s: injected state-restore fault (%s) — "
                             "degrading to a full re-scan",
                             self.name, e)
                start, snap = 0, None
                self.state_rescans += 1
                inc("veles_o1_state_rescans_total")
            if start:
                self.state_restores += 1
                self.prefix_requests += 1
                inc("veles_o1_state_restores_total")
                inc("veles_o1_state_restored_tokens_total", start)
        self._set_state_row(slot.idx, snap)
        resume_k = int(slot.req.get("resume_k", 0) or 0)
        if resume_k:
            inc("veles_resume_tokens_total", resume_k)
        wait = max(0.0, (slot.ticket.admitted or time.time())
                   - slot.ticket.enqueued)
        seed = int(slot.req.get("seed", 0))
        # -- chunked scan over the (unmatched) prompt ----------------
        snaps: Dict[int, Tuple] = {}
        p0 = start
        first = None
        with span("serving.prefill", bucket=C, slot=slot.idx,
                  t_p=t_p, mode=slot.mode,
                  request_id=slot.ticket.request_id,
                  trace_id=slot.ticket.trace_id,
                  attempt=slot.ticket.attempt):
            while True:
                n_real = min(C, t_p - p0)
                final = p0 + n_real >= t_p
                ids = numpy.zeros(C, numpy.int32)
                ids[:n_real] = prompt[p0:p0 + n_real]
                # the PRNG carry matters only at the final chunk (it
                # samples the first token); resumed requests re-enter
                # their stream exactly like the paged prefill does
                seed_key = (advanced_prng_key(seed, resume_k)
                            if final and resume_k
                            else jax.random.PRNGKey(seed))
                first, self._keys, self._states, row = \
                    self._program("scan")(
                        params, jnp.asarray(ids), numpy.int32(n_real),
                        numpy.int32(slot.idx),
                        numpy.float32(slot.temperature), seed_key,
                        numpy.int32(1 if final else 0),
                        self._keys, self._states)
                inc("veles_serving_prefill_dispatches_total")
                self.chunk_dispatches += 1
                boundary = p0 + n_real
                if n_real == C and self.state_cache is not None:
                    # a full chunk ends on a block boundary: snapshot
                    # the row's state host-side — the checkpoint the
                    # next same-prefix admission adopts
                    snaps[boundary // C] = tuple(
                        {k: numpy.asarray(v) for k, v in st.items()}
                        for st in row)
                if final:
                    break
                p0 = boundary
        self._pos[slot.idx] = t_p
        self._temp[slot.idx] = slot.temperature
        if not slot.req.get("_requeued"):
            # a preempt-requeue is the SAME admitted request coming
            # back — count it once, at its first admission
            inc("veles_serving_admitted_total")
            inc("veles_serving_queue_wait_seconds_total", wait)
            self.admitted += 1
        first = int(first)
        slot.ticket.mark_prefill_done()
        slot.ticket.mark_first_token()
        self._tok[slot.idx] = first
        self._checkpoint_insert(slot, snaps)
        done = slot.record(first)
        slot.ticket.push_tokens([first])
        if done:
            self._finish(slot)

    def _checkpoint_insert(self, slot, snaps: Dict[int, Tuple]) -> None:
        """Cache a freshly scanned prompt's block-boundary snapshots
        so the next admission adopts them. The ``serve.state_checkpoint``
        fault point degrades to NOT caching — the request itself is
        already answered from the live state, so an injected failure
        costs future admissions a re-scan, never correctness."""
        if self.state_cache is None or not snaps:
            return
        n_blocks = slot.t_p // self.page_size
        if not n_blocks:
            return
        try:
            fire_fault("serve.state_checkpoint")
        except FaultInjected as e:
            self.warning("%s: injected state-checkpoint fault (%s) — "
                         "prompt not cached; same-prefix admissions "
                         "re-scan", self.name, e)
            return
        added = self.state_cache.insert(
            slot.req["prompt"][:n_blocks * self.page_size],
            [snaps.get(i + 1) for i in range(n_blocks)])
        if added:
            self.state_checkpoints += added
            inc("veles_o1_state_checkpoints_total", added)

    # -- the decode chunk ------------------------------------------------------
    def _decode(self, params) -> None:
        import jax.numpy as jnp
        active = self.scheduler.active()
        if not active:
            return
        mask = numpy.zeros(self.max_slots, numpy.int32)
        for slot in active:
            mask[slot.idx] = 1
        base_len = {id(s): len(s.tokens) for s in active}
        fire_fault("serve.decode_step")
        with span("serving.decode_step", active=len(active),
                  chunk=self.decode_block):
            toks, self._keys, self._states = self._program("step")(
                params, jnp.asarray(self._tok),
                jnp.asarray(self._temp), jnp.asarray(mask),
                self._keys, self._states)
            toks = numpy.asarray(toks)          # (decode_block, S)
        inc("veles_serving_decode_dispatches_total")
        finished: List = []
        for h in range(toks.shape[0]):
            still = [s for s in active if s not in finished]
            if not still:
                break
            for slot in still:
                token = int(toks[h, slot.idx])
                self._tok[slot.idx] = token
                self._pos[slot.idx] += 1
                if slot.record(token):
                    finished.append(slot)
        for slot in active:
            slot.ticket.push_tokens(slot.tokens[base_len[id(slot)]:])
        for slot in finished:
            self._finish(slot)

    # -- retirement -------------------------------------------------------------
    def _retire_slot(self, slot) -> None:
        """Clear a row's host state and free its slot. The device
        state row is left as-is — the next admission re-initializes
        it (zeros or an adopted checkpoint) before any dispatch reads
        it, and masked rows never update."""
        self._tok[slot.idx] = 0
        self._pos[slot.idx] = 0
        self._temp[slot.idx] = 0.0
        self.scheduler.retire(slot)

    def _finish(self, slot) -> None:
        batched_with = max(0, self.scheduler.busy_count() - 1)
        self._retire_slot(slot)
        tokens = self._emitted(slot)
        result = {"tokens": tokens,
                  "batched_with": batched_with,
                  "engine": "recurrent"}
        if slot.ticket.succeed(result):
            inc("veles_serving_retired_total")
            inc("veles_serving_tokens_total", len(tokens))
            self.retired += 1

    def _abort_active(self, reason: str, code: int = 500,
                      retry_after: Optional[float] = None,
                      count_shed: bool = True) -> None:
        answered = set()
        for slot in self.scheduler.active():
            if slot.mode in _STEP_MODES and (
                    slot.tokens or slot.req.get("_qos_prefix")):
                slot.ticket.set_progress(self._emitted(slot))
            self._retire_slot(slot)
            if id(slot.ticket) not in answered:
                answered.add(id(slot.ticket))
                first = slot.ticket.fail(reason, code=code,
                                         retry_after=retry_after)
                if count_shed and first:
                    inc("veles_shed_requests_total")

    # -- drain-by-handoff ------------------------------------------------------
    def handoff(self, reason: str = "server draining; request handed "
                                    "off with resume progress",
                timeout: float = 30.0) -> int:
        """Hand every in-flight request back with its emitted-token
        prefix at the NEXT step boundary — same contract (and same
        ``serve.handoff`` fault point) as the paged engine's."""
        done = threading.Event()
        box = {"count": 0}
        with self.scheduler.cv:
            if self._closing or self._thread is None:
                return 0
            self._handoff = (reason, done, box)
            self.scheduler.cv.notify_all()
        if not done.wait(timeout):
            self.warning("%s: handoff timed out after %.1fs (tick "
                         "thread wedged?); the drain proceeds to the "
                         "abort path", self.name, timeout)
        return box["count"]

    def _do_handoff(self, reason: str) -> int:
        handed = 0
        answered = set()
        for slot in self.scheduler.active():
            ticket = slot.ticket
            if id(ticket) not in answered:
                answered.add(id(ticket))
                snapshot_ok = True
                try:
                    fire_fault("serve.handoff")
                except FaultInjected as e:
                    snapshot_ok = False
                    self.warning(
                        "%s: progress snapshot failed mid-drain for "
                        "%s (%s) — handing off without resume",
                        self.name, ticket.request_id, e)
                if snapshot_ok and slot.mode in _STEP_MODES:
                    ticket.set_progress(self._emitted(slot))
                if ticket.fail(reason, code=503, retry_after=1.0,
                               outcome="handoff"):
                    if ticket.progress:
                        handed += 1
                        inc("veles_handoff_requests_total")
                    else:
                        inc("veles_shed_requests_total")
            self._retire_slot(slot)
        shed = self.scheduler.drain(reason, code=503, retry_after=1.0)
        if shed:
            inc("veles_shed_requests_total", shed)
        return handed

    # -- jitted programs -------------------------------------------------------
    def _program(self, kind: str):
        key = (kind, None)
        prog = self._progs.get(key)
        if prog is None:
            builders = {"scan": self._build_scan_chunk,
                        "step": self._build_decode}
            prog = self._progs[key] = self._instrument_live(
                builders[kind](), key)
        return prog

    def _instrument_live(self, jitted, key=None):
        """Identical wrapper to the paged engine's: one dispatch
        counter per call, one explicit lower+compile on the first —
        ``veles_serving_compile_seconds_total`` brackets ONLY the
        trace+compile the AOT artifact path exists to delete."""
        box: Dict[str, object] = {}

        def dispatch(*args):
            inc("veles_decode_dispatches_total")
            if key is not None:
                self.prog_calls[key] = self.prog_calls.get(key, 0) + 1
            exe = box.get("exe")
            if exe is None:
                try:
                    t0 = time.time()
                    exe = jitted.lower(*args).compile()
                except AttributeError:      # non-pjit backends
                    exe = jitted
                else:
                    self.compiled_live += 1
                    inc("veles_compiles_total")
                    inc("veles_serving_compile_seconds_total",
                        time.time() - t0)
                box["exe"] = exe
            return exe(*args)

        dispatch._jitted = jitted
        dispatch.compiled = lambda: box.get("exe")
        return dispatch

    # -- AOT artifact (export/serve_artifact.py) ------------------------------
    def stack_signature(self) -> Dict:
        """Geometry the exported recurrent programs are shape-
        committed to: the abstract params spec, every block's state
        leaf shapes at ``max_slots`` rows, and the lane knobs the two
        programs bake in. Same refuse-on-mismatch contract as the
        paged engine's signature."""
        import jax

        def spec(tree):
            return jax.tree_util.tree_map(
                lambda a: [list(a.shape), str(a.dtype)], tree)

        params = params_of(self.wf)
        states = []
        for blk in self.stack["blocks"]:
            states.append(
                {k: list(shape) for k, shape
                 in sorted(blk.state_shapes(self.max_slots).items())})
        return {
            "kind": "recurrent",
            "params": spec(params),
            "states": states,
            "pool_dtype": str(
                params[self.stack["stem"].name]["table"].dtype),
            "max_slots": self.max_slots,
            "max_context": self.max_context,
            "decode_block": self.decode_block,
            "page_size": self.page_size,
            "state_cache": self.state_cache is not None,
        }

    def _load_artifact(self) -> bool:
        from ..export.serve_artifact import load_serve_programs
        try:
            fire_fault("artifact.load")
            programs = load_serve_programs(self.artifact,
                                           self.stack_signature())
        except Exception as e:      # noqa: BLE001 — degrade, don't die
            inc("veles_artifact_load_failures_total")
            self.warning(
                "%s: serve-artifact %s unusable (%s: %s); serving via "
                "live jit", self.name, self.artifact,
                type(e).__name__, e)
            return False
        from ..nn.sampling import _count_decode_dispatches
        for key, call in programs.items():
            self._progs[key] = _count_decode_dispatches(call)
        self.artifact_mode = True
        inc("veles_artifact_loads_total")
        self.info("%s: AOT artifact loaded from %s (%d programs; zero "
                  "jit compiles on the serving path)", self.name,
                  self.artifact, len(programs))
        return True

    # -- program builders ------------------------------------------------------
    def _build_scan_chunk(self):
        """THE prefill program: one ``page_size``-token chunk of ONE
        slot's prompt — slice the slot's state rows, ``lax.scan`` the
        shared step bodies over the chunk (positions past ``n_real``
        length-masked so padding never perturbs the carried state),
        write the rows back, and (final chunk only) sample the first
        token with the paged prefill's exact key convention. Also
        returns the slot's post-chunk state rows for host-side
        checkpointing — full-chunk boundaries ARE the block
        boundaries the StateCache indexes."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, blocks, head = stack["stem"], stack["blocks"], \
            stack["head"]
        prec = matmul_precision()

        @functools.partial(jax.jit, donate_argnums=(7, 8))
        def scan_chunk(params, ids, n_real, slot, temp, seed_key,
                       final, keys, states):
            x = _embed_prompt(stem, None, params, ids[None])  # (1,C,D)
            new_states = []
            rows = []
            for blk, st in zip(blocks, states):
                st_row = jax.tree_util.tree_map(
                    lambda leaf: jax.lax.dynamic_slice(
                        leaf, (slot,) + (0,) * (leaf.ndim - 1),
                        (1,) + leaf.shape[1:]), st)
                x, st_row = blk.scan_state(params[blk.name], x,
                                           st_row, length=n_real)
                rows.append(st_row)
                new_states.append(jax.tree_util.tree_map(
                    lambda leaf, row_leaf: jax.lax.dynamic_update_slice(
                        leaf, row_leaf,
                        (slot,) + (0,) * (leaf.ndim - 1)),
                    st, st_row))
            x_last = jnp.take(x[0], n_real - 1, axis=0, mode="clip")
            logits = _head_logits(head, params, x_last, prec)
            k2 = jax.random.split(seed_key)
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            samp = jax.random.categorical(
                k2[1], logits / jnp.maximum(temp, _TEMP_EPS)
            ).astype(jnp.int32)
            first = jnp.where(temp > 0, samp, greedy)
            # the key row advances only at the FINAL chunk — the one
            # that actually sampled (same gate as the paged chunk
            # program)
            upd = jax.lax.dynamic_update_slice(keys, k2[0][None],
                                               (slot, 0))
            keys = jnp.where(final > 0, upd, keys)
            return first, keys, tuple(new_states), tuple(rows)

        return scan_chunk

    def _build_decode(self):
        """THE decode step: ``decode_block`` scan iterations of the
        SAME per-token step bodies the prefill scanned — one fixed
        shape over all ``max_slots`` rows, compiled exactly once.
        Masked-out rows keep their state BIT-UNTOUCHED (``mask_keep``
        per leaf) and their key stream unadvanced, so a row's tokens
        are a pure function of its request whatever strangers share
        the pool — the paged lane's id-exactness contract, kept."""
        import jax
        import jax.numpy as jnp
        from ..ops import matmul_precision
        stack = self.stack
        stem, blocks, head = stack["stem"], stack["blocks"], \
            stack["head"]
        prec = matmul_precision()

        @functools.partial(jax.jit, donate_argnums=(4, 5))
        def step(params, tok, temp, mask, keys, states):

            def body(carry, _):
                tok, keys, states = carry
                x = jnp.take(params[stem.name]["table"],
                             tok.astype(jnp.int32), axis=0,
                             mode="clip")                 # (S, D)
                new_states = []
                for blk, st in zip(blocks, states):
                    x, st2 = blk.step_state(params[blk.name], x, st)
                    st2 = jax.tree_util.tree_map(
                        lambda new, old: mask_keep(mask > 0, new,
                                                   old), st2, st)
                    new_states.append(st2)
                logits = _head_logits(head, params, x, prec)  # (S, V)
                keys2, subs = _split_rows(keys)
                keys = jnp.where(mask[:, None] > 0, keys2, keys)
                greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                samp = jax.vmap(jax.random.categorical)(
                    subs,
                    logits / jnp.maximum(temp, _TEMP_EPS)[:, None]
                ).astype(jnp.int32)
                nxt = jnp.where(temp > 0, samp, greedy)
                nxt = jnp.where(mask > 0, nxt, tok)
                return (nxt, keys, tuple(new_states)), nxt

            (tok, keys, states), toks = jax.lax.scan(
                body, (tok, keys, states), None,
                length=self.decode_block)
            return toks, keys, states

        return step


def generate_recurrent(wf, prompt, n_new, temperature: float = 0.0,
                       seed: int = 0, eos_id=None,
                       mode: str = "greedy") -> List[int]:
    """Solo-decode oracle for the O(1) lane: serve ONE request through
    a private single-slot :class:`RecurrentEngine` and return its
    tokens. Because every program is fixed-shape and every slot's
    noise derives purely from its seed, a pooled request's tokens must
    equal this — the id-exactness bar the o1 serving tests hold the
    shared pool to."""
    from .engine import make_request
    eng = RecurrentEngine(
        wf, max_slots=1,
        max_context=max(16, len(list(prompt)) + int(n_new)),
        name="o1_solo")
    eng.start()
    try:
        return eng.serve([make_request(
            list(prompt), int(n_new), temperature=float(temperature),
            seed=int(seed), eos_id=eos_id, mode=mode)])[0]
    finally:
        eng.stop()
