"""Fixed-size page allocator for the paged KV-cache slot pool.

The dense slot pool sized every row to ``max_context``, so pool HBM
was ``max_slots x max_context`` whatever the actual request mix. The
paged pool (the block-table formulation of PAPERS.md's "Compiler-First
State Space Duality and Portable O(1) Autoregressive Caching for
Inference") stores K/V in a global pool of fixed-size pages of
``page_size`` positions each; every slot owns a *page table* — an
int32 index array of ``ceil(max_context / page_size)`` entries — and
the jitted programs gather a slot's logical cache view through it.
Concurrency is then bounded by PAGES, not by worst-case context:
admission reserves only the pages a request's own prompt + budget can
ever touch (``ceil((prompt + n_new [+ gamma + 1]) / page_size)``),
never ``max_context`` worth.

This module is the pure-host half: the allocator (free list, usage
accounting, exhaustion counters). Device-side page pools are shaped by
``quant/kv.py``'s :func:`~veles_tpu.quant.kv.block_page_pool`; the
jitted gather/scatter lives in ``serving/engine.py``.

Page 0 is the SINK: it is never allocated, and masked/retired rows in
the fixed-shape programs direct their writes at it (a batched scatter
needs *some* in-bounds target for every lane). Sink content is
garbage by design and no live page table ever points at it for a
position a read mask can reach.
"""

from __future__ import annotations

import threading
from typing import List, Optional

from ..telemetry.counters import inc


def pages_for(positions: int, page_size: int) -> int:
    """Pages needed to hold ``positions`` cache rows (ceil div)."""
    return max(0, (int(positions) + page_size - 1) // page_size)


class PagePool:
    """Free-list allocator over ``pages`` usable pages (device rows
    ``1..pages``; row 0 is the sink). Thread-safe; the scheduler
    allocates at admission, the engine allocates growth at step
    boundaries and frees at retirement."""

    def __init__(self, pages: int, page_size: int) -> None:
        if pages < 1:
            raise ValueError("page pool needs >= 1 usable page")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.pages = int(pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(1, self.pages + 1))

    @property
    def device_rows(self) -> int:
        """Rows the device arrays carry: the usable pages + the sink."""
        return self.pages + 1

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def in_use(self) -> int:
        with self._lock:
            return self.pages - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` page ids, or None when the pool cannot satisfy the
        request (exhaustion — counted; the caller decides between
        waiting for retirements and shedding 503 + Retry-After)."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            if len(self._free) < n:
                inc("veles_serving_pages_exhausted_total")
                return None
            out, self._free = self._free[:n], self._free[n:]
        inc("veles_serving_pages_alloc_total", n)
        return out

    def free(self, ids: List[int]) -> None:
        if not ids:
            return
        with self._lock:
            self._free.extend(int(i) for i in ids)
            self._free.sort()
        inc("veles_serving_pages_free_total", len(ids))
