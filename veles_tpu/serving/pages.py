"""Fixed-size page allocator + prefix-sharing index for the paged
KV-cache slot pool.

The dense slot pool sized every row to ``max_context``, so pool HBM
was ``max_slots x max_context`` whatever the actual request mix. The
paged pool (the block-table formulation of PAPERS.md's "Compiler-First
State Space Duality and Portable O(1) Autoregressive Caching for
Inference") stores K/V in a global pool of fixed-size pages of
``page_size`` positions each; every slot owns a *page table* — an
int32 index array of ``ceil(max_context / page_size)`` entries — and
the jitted programs gather a slot's logical cache view through it.
Concurrency is then bounded by PAGES, not by worst-case context:
admission reserves only the pages a request's own prompt + budget can
ever touch (``ceil((prompt + n_new [+ gamma + 1]) / page_size)``),
never ``max_context`` worth.

Pages are REFCOUNTED: prefix sharing (:class:`PrefixCache`) lets many
slots — and the cache index itself — hold the same physical page, so
:meth:`PagePool.free` releases one reference and a page returns to
the free list only when its last holder lets go. ``in_use`` counts a
shared page ONCE, however many slots adopted it (the fleet /metrics
aggregation reads these gauges; double-counting a shared system
prompt would report phantom HBM).

This module is the pure-host half: the allocator (free list, refcount
ledger, usage accounting, exhaustion counters) and the prefix index (a
radix tree over ``page_size``-token blocks mapping shared prompt
prefixes to pages, LRU-evicted under allocator pressure). Device-side
page pools are shaped by ``quant/kv.py``'s
:func:`~veles_tpu.quant.kv.block_page_pool`; the jitted gather/scatter
lives in ``serving/engine.py``.

Page 0 is the SINK: it is never allocated, and masked/retired rows in
the fixed-shape programs direct their writes at it (a batched scatter
needs *some* in-bounds target for every lane). Sink content is
garbage by design and no live page table ever points at it for a
position a read mask can reach.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..telemetry.counters import inc


def pages_for(positions: int, page_size: int) -> int:
    """Pages needed to hold ``positions`` cache rows (ceil div)."""
    return max(0, (int(positions) + page_size - 1) // page_size)


def per_shard_kv_heads(n_kv_heads: int, tp: int = 1) -> int:
    """K/V heads each mesh shard STORES per logical page under
    tensor-parallel serving (``serving/engine.py`` ``tp=`` knob).

    The allocator above — page ids, tables, refcounts, ``in_use`` —
    indexes LOGICAL pages only: one page means "``page_size``
    positions of one slot's cache", wherever its head slices live.
    Under ``tp=N`` the device pool's kv-head axis is sharded over the
    ``("model",)`` mesh, so each chip holds ``n_kv_heads / N`` heads
    of every logical page and the HOST-side admission/eviction math
    is identical at every ``tp`` — which is exactly why the scheduler
    can stay shard-agnostic. Raises ValueError on a ragged split
    (a shard holding half a head would change the attention math)."""
    n_kv_heads, tp = int(n_kv_heads), max(1, int(tp))
    if n_kv_heads % tp:
        raise ValueError("kv heads %d %% tp %d != 0 — a ragged "
                         "head shard cannot serve id-exact"
                         % (n_kv_heads, tp))
    return n_kv_heads // tp


class PagePool:
    """Refcounted free-list allocator over ``pages`` usable pages
    (device rows ``1..pages``; row 0 is the sink). Thread-safe; the
    scheduler allocates at admission, the engine allocates growth at
    step boundaries and frees at retirement; the prefix cache and
    adopting slots :meth:`share` pages they did not allocate."""

    def __init__(self, pages: int, page_size: int) -> None:
        if pages < 1:
            raise ValueError("page pool needs >= 1 usable page")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.pages = int(pages)
        self.page_size = int(page_size)
        self._lock = threading.Lock()
        self._free: List[int] = list(range(1, self.pages + 1))
        #: page id -> holders (slots + the prefix index); a page is in
        #: the free list iff it has no entry here
        self._rc: Dict[int, int] = {}
        #: pressure valve: called OUTSIDE the pool lock with the page
        #: shortfall when :meth:`alloc` cannot satisfy a request; the
        #: engine points it at :meth:`PrefixCache.evict` so cached
        #: prefixes are reclaimed LRU-first before anyone is refused
        self.evictor: Optional[Callable[[int], int]] = None

    @property
    def device_rows(self) -> int:
        """Rows the device arrays carry: the usable pages + the sink."""
        return self.pages + 1

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def in_use(self) -> int:
        """Pages with at least one holder — a SHARED page counts once,
        not per adopting slot (satellite fix: the fragmentation gauge
        and fleet ``pages_in_use`` aggregation stay truthful under
        prefix sharing)."""
        with self._lock:
            return self.pages - len(self._free)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._rc.get(int(page), 0)

    def ledger(self) -> Dict[int, int]:
        """Snapshot of the refcount ledger (poisoning/balance tests:
        after all slots retire and the prefix cache clears, this must
        be empty and ``in_use()`` zero)."""
        with self._lock:
            return dict(self._rc)

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` page ids (each with refcount 1), or None when the
        pool cannot satisfy the request (exhaustion — counted; the
        caller decides between waiting for retirements and shedding
        503 + Retry-After). Under pressure the :attr:`evictor` is
        asked ONCE to release cached-prefix pages before refusing."""
        n = int(n)
        if n <= 0:
            return []
        evicted = False
        while True:
            with self._lock:
                if len(self._free) >= n:
                    out, self._free = self._free[:n], self._free[n:]
                    for page in out:
                        self._rc[page] = 1
                    break
                shortfall = n - len(self._free)
            if self.evictor is not None and not evicted:
                # outside the lock: the evictor frees pages through
                # free(), which takes the lock itself
                evicted = True
                try:
                    self.evictor(shortfall)
                except Exception:   # noqa: BLE001 — pressure valve only
                    pass
                continue
            inc("veles_serving_pages_exhausted_total")
            return None
        inc("veles_serving_pages_alloc_total", n)
        return out

    def share(self, page: int) -> int:
        """Take one more reference on an allocated page (prefix
        adoption / cache insertion). Raises on a page nobody holds —
        sharing a freed page would alias the next admission's data,
        the exact poisoning the refcount ledger exists to prevent."""
        page = int(page)
        with self._lock:
            rc = self._rc.get(page)
            if rc is None:
                raise ValueError(
                    "page %d is not allocated — cannot share" % page)
            self._rc[page] = rc + 1
            return rc + 1

    def free(self, ids: Sequence[int]) -> None:
        """Release one reference per page; pages whose LAST reference
        dropped return to the free list (counted — the alloc/free
        counters balance against ``in_use``, not against raw
        share/release traffic)."""
        if not ids:
            return
        released = 0
        with self._lock:
            for i in ids:
                page = int(i)
                rc = self._rc.get(page)
                if rc is None:
                    # double free — tolerated like the idempotent slot
                    # retire (shutdown sweeps may race), never counted
                    continue
                if rc > 1:
                    self._rc[page] = rc - 1
                    continue
                del self._rc[page]
                self._free.append(page)
                released += 1
            self._free.sort()
        if released:
            inc("veles_serving_pages_free_total", released)


class _PrefixNode:
    """One cached ``page_size``-token block: the exact tokens (THE
    match key — hashes pick the dict slot, token equality decides, so
    a corrupted index can only degrade to a miss, never to wrong
    tokens), the physical page holding its K/V rows, and the LRU
    stamp."""

    __slots__ = ("tokens", "page", "children", "parent", "last_use")

    def __init__(self, tokens: Tuple[int, ...], page: int,
                 parent: Optional["_PrefixNode"]) -> None:
        self.tokens = tokens
        self.page = page
        self.children: Dict[Tuple[int, ...], "_PrefixNode"] = {}
        self.parent = parent
        self.last_use = 0


class PrefixCache:
    """Radix tree over hashed token blocks (block = ``page_size``
    tokens) mapping shared prompt prefixes to refcounted pages.

    Admission walks the tree over a prompt's full blocks; every
    matched node's page is :meth:`PagePool.share`-adopted into the new
    slot's page table, so the slot's prefill covers only the unmatched
    suffix — a 2k-token system prompt shared by the whole pool costs
    its pages and its prefill FLOPs once. After a prefill completes,
    the slot's own full blocks are :meth:`insert`-ed so the NEXT
    admission shares them.

    The tree holds its own page references (a retired writer's prefix
    outlives it), released by LRU leaf eviction under allocator
    pressure (:meth:`evict` — wired as :attr:`PagePool.evictor`) or
    :meth:`clear`. All mutation happens on the engine's tick thread;
    the lock exists for the /metrics stats reads."""

    def __init__(self, pool: PagePool, page_size: int,
                 max_blocks: Optional[int] = None) -> None:
        self.pool = pool
        self.page_size = int(page_size)
        #: soft block budget: insertions past it evict LRU leaves
        #: first (0/None = bounded only by allocator pressure)
        self.max_blocks = int(max_blocks or 0)
        self._lock = threading.Lock()
        self._root = _PrefixNode((), 0, None)
        self._clock = 0
        self._blocks = 0

    def _blocks_of(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        p = self.page_size
        n = len(tokens) // p
        return [tuple(int(t) for t in tokens[i * p:(i + 1) * p])
                for i in range(n)]

    def match(self, tokens: Sequence[int],
              corrupt=None) -> List[int]:
        """Walk the tree over ``tokens``' full blocks; returns the
        matched pages IN ORDER, each with a reference already taken
        for the caller (the adopting slot owns them like its own
        allocations — :meth:`PagePool.free` at retirement releases).

        ``corrupt`` is the armed ``serve.prefix_match`` fault: when
        set, every candidate block key is damaged before the equality
        check — a corrupted index DEGRADES to a shorter (or empty)
        match and a full prefill, never to wrong tokens, because the
        token comparison is the authority, not the hash."""
        matched: List[int] = []
        with self._lock:
            node = self._root
            self._clock += 1
            for block in self._blocks_of(tokens):
                key = block
                if corrupt is not None:
                    # damage the LOOKUP key the way a rotten index
                    # entry would: the tokens no longer compare equal,
                    # so the walk stops and the suffix prefills fully
                    raw = bytearray()
                    for t in block:
                        raw += int(t).to_bytes(8, "little", signed=True)
                    raw = corrupt.corrupt(bytes(raw))
                    key = tuple(
                        int.from_bytes(raw[i:i + 8], "little",
                                       signed=True)
                        for i in range(0, len(raw) - len(raw) % 8, 8))
                child = node.children.get(key)
                if child is None or child.tokens != block:
                    break
                child.last_use = self._clock
                self.pool.share(child.page)
                matched.append(child.page)
                node = child
        return matched

    def insert(self, tokens: Sequence[int],
               pages: Sequence[int]) -> int:
        """Record ``tokens``' full blocks, backed by the slot's
        ``pages`` (parallel lists: block i lives in ``pages[i]``).
        Blocks already present are only LRU-touched (the tree keeps
        its existing page — two identical prefills must not hold two
        copies); new nodes take their own reference on the slot's
        page, which therefore survives the slot's retirement. Returns
        the number of NEW blocks cached."""
        blocks = self._blocks_of(tokens)
        added = 0
        with self._lock:
            self._clock += 1
            node = self._root
            for i, block in enumerate(blocks):
                if i >= len(pages):
                    break
                child = node.children.get(block)
                if child is None:
                    try:
                        self.pool.share(int(pages[i]))
                    except ValueError:
                        break          # page already gone — stop here
                    child = _PrefixNode(block, int(pages[i]), node)
                    node.children[block] = child
                    self._blocks += 1
                    added += 1
                child.last_use = self._clock
                node = child
        if self.max_blocks and self._blocks > self.max_blocks:
            self.evict(0, over_budget=True)
        return added

    def _leaves(self) -> List[_PrefixNode]:
        out: List[_PrefixNode] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            kids = list(node.children.values())
            if not kids and node is not self._root:
                out.append(node)
            stack.extend(kids)
        return out

    def evict(self, need_pages: int, over_budget: bool = False) -> int:
        """Drop least-recently-used LEAF blocks (a block with cached
        children anchors their prefix and is never dropped first)
        until ``need_pages`` pages actually returned to the free list
        — or, with ``over_budget``, until the soft block budget holds.
        ONE tree walk seeds a heap of leaves; evicting a leaf can
        only promote its parent, which is pushed as it becomes
        childless — so reclaiming k pages is O(blocks + k log blocks),
        never a re-walk per drop on the allocator-pressure path an
        admission is waiting on. Counted per dropped block. Returns
        pages actually freed."""
        import heapq
        freed = 0
        dropped = 0
        with self._lock:
            heap = [(n.last_use, i, n)
                    for i, n in enumerate(self._leaves())]
            heapq.heapify(heap)
            tie = len(heap)
            while heap:
                if over_budget:
                    if not self.max_blocks \
                            or self._blocks <= self.max_blocks:
                        break
                elif freed >= need_pages:
                    break
                _, _, victim = heapq.heappop(heap)
                parent = victim.parent
                if victim.children or parent is None \
                        or parent.children.get(victim.tokens) \
                        is not victim:
                    continue           # stale heap entry
                parent.children.pop(victim.tokens, None)
                self._blocks -= 1
                dropped += 1
                before = self.pool.free_count()
                self.pool.free([victim.page])
                freed += self.pool.free_count() - before
                if parent is not self._root and not parent.children:
                    heapq.heappush(heap, (parent.last_use, tie,
                                          parent))
                    tie += 1
        if dropped:
            inc("veles_prefix_evictions_total", dropped)
        return freed

    def clear(self) -> None:
        """Release every cached block's page reference (engine stop /
        ledger-balance tests)."""
        with self._lock:
            stack = [self._root]
            pages: List[int] = []
            while stack:
                node = stack.pop()
                kids = list(node.children.values())
                stack.extend(kids)
                if node is not self._root:
                    pages.append(node.page)
            self._root = _PrefixNode((), 0, None)
            self._blocks = 0
        self.pool.free(pages)

    def cached_pages(self) -> List[int]:
        """Every page the index currently references (full blocks by
        construction) — the engine's fragmentation gauge stamps them
        fully occupied."""
        with self._lock:
            out: List[int] = []
            stack = [self._root]
            while stack:
                node = stack.pop()
                stack.extend(node.children.values())
                if node is not self._root:
                    out.append(node.page)
            return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"blocks": self._blocks,
                    "pages": self._blocks}


class _StateNode:
    """One checkpointed ``page_size``-token block of a recurrent
    prompt: the exact tokens (the match key — token equality is the
    authority, same degrade-to-miss contract as :class:`_PrefixNode`)
    and the HOST snapshot of the recurrent state pytree as it stood
    AFTER this block was scanned."""

    __slots__ = ("tokens", "state", "nbytes", "children", "parent",
                 "last_use")

    def __init__(self, tokens: Tuple[int, ...], state, nbytes: int,
                 parent: Optional["_StateNode"]) -> None:
        self.tokens = tokens
        self.state = state
        self.nbytes = int(nbytes)
        self.children: Dict[Tuple[int, ...], "_StateNode"] = {}
        self.parent = parent
        self.last_use = 0


class StateCache:
    """Prefix cache for the O(1)-state lane: a radix tree over
    ``page_size``-token blocks whose payload is a STATE SNAPSHOT, not
    a page.

    A transformer prefix is a range of KV rows, so :class:`PrefixCache`
    shares pages. A recurrent prefix is fully summarized by the state
    vector after its last token, so this tree stores one host-side
    snapshot of the state pytree per block boundary. Admission calls
    :meth:`match` with the prompt: the deepest matched node's snapshot
    is adopted COPY-ON-WRITE — the caller uploads it into its slot's
    state rows and never mutates the host copy — and the slot's scan
    covers only the unmatched suffix. After prefill the slot's own
    block-boundary snapshots are :meth:`insert`-ed so the next
    admission with the same prefix skips the re-scan.

    Snapshots are plain host pytrees (dict of numpy arrays) and own no
    pool pages — eviction is purely the soft ``max_blocks`` budget,
    LRU leaves first (counted as ``veles_o1_state_evictions_total``).
    All mutation happens on the engine's tick thread; the lock exists
    for the /metrics stats reads."""

    def __init__(self, page_size: int,
                 max_blocks: Optional[int] = None) -> None:
        self.page_size = int(page_size)
        self.max_blocks = int(max_blocks or 0)
        self._lock = threading.Lock()
        self._root = _StateNode((), None, 0, None)
        self._clock = 0
        self._blocks = 0
        self._bytes = 0

    def _blocks_of(self, tokens: Sequence[int]) -> List[Tuple[int, ...]]:
        p = self.page_size
        n = len(tokens) // p
        return [tuple(int(t) for t in tokens[i * p:(i + 1) * p])
                for i in range(n)]

    @staticmethod
    def _snapshot_bytes(state) -> int:
        total = 0
        stack = [state]
        while stack:
            node = stack.pop()
            if isinstance(node, dict):
                stack.extend(node.values())
            elif isinstance(node, (list, tuple)):
                stack.extend(node)
            else:
                total += int(getattr(node, "nbytes", 0))
        return total

    def match(self, tokens: Sequence[int], corrupt=None):
        """Walk the tree over ``tokens``' full blocks; returns
        ``(n_tokens_matched, snapshot)`` for the DEEPEST matched node
        (``(0, None)`` on a miss). Unlike the paged cache there is
        nothing per-block to adopt — the last boundary's snapshot
        subsumes all of them.

        ``corrupt`` is the armed ``serve.state_restore`` fault acting
        on the index: every candidate block key is damaged before the
        equality check, so a rotten index DEGRADES to a shorter (or
        empty) match and a longer re-scan — never to a wrong state,
        because token equality is the authority."""
        best = None
        depth = 0
        with self._lock:
            node = self._root
            self._clock += 1
            for block in self._blocks_of(tokens):
                key = block
                if corrupt is not None:
                    raw = bytearray()
                    for t in block:
                        raw += int(t).to_bytes(8, "little", signed=True)
                    raw = corrupt.corrupt(bytes(raw))
                    key = tuple(
                        int.from_bytes(raw[i:i + 8], "little",
                                       signed=True)
                        for i in range(0, len(raw) - len(raw) % 8, 8))
                child = node.children.get(key)
                if child is None or child.tokens != block:
                    break
                child.last_use = self._clock
                best = child.state
                depth += self.page_size
                node = child
        return depth, best

    def insert(self, tokens: Sequence[int], snapshots) -> int:
        """Record ``tokens``' full blocks with their block-boundary
        ``snapshots`` (parallel lists: ``snapshots[i]`` is the state
        after block i's last token — host pytrees the caller no longer
        mutates). Blocks already present are only LRU-touched (first
        writer wins; two identical prefills carry bit-identical states
        anyway, the scan is deterministic). A ``None`` snapshot marks
        a block the caller did NOT re-scan (it was adopted from this
        cache): the existing node is touched, but if eviction dropped
        it meanwhile the walk stops — a node without a real snapshot
        must never exist. Returns NEW blocks cached."""
        blocks = self._blocks_of(tokens)
        added = 0
        with self._lock:
            self._clock += 1
            node = self._root
            for i, block in enumerate(blocks):
                if i >= len(snapshots):
                    break
                child = node.children.get(block)
                if child is None:
                    if snapshots[i] is None:
                        break
                    nbytes = self._snapshot_bytes(snapshots[i])
                    child = _StateNode(block, snapshots[i], nbytes,
                                       node)
                    node.children[block] = child
                    self._blocks += 1
                    self._bytes += nbytes
                    added += 1
                child.last_use = self._clock
                node = child
        if self.max_blocks and self._blocks > self.max_blocks:
            self.evict()
        return added

    def _leaves(self) -> List[_StateNode]:
        out: List[_StateNode] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            kids = list(node.children.values())
            if not kids and node is not self._root:
                out.append(node)
            stack.extend(kids)
        return out

    def evict(self) -> int:
        """Drop least-recently-used LEAF blocks until the soft block
        budget holds (a block with cached children anchors their
        prefix and is never dropped first). Same one-walk heap shape
        as :meth:`PrefixCache.evict`. Counted per dropped block."""
        import heapq
        dropped = 0
        with self._lock:
            if not self.max_blocks:
                return 0
            heap = [(n.last_use, i, n)
                    for i, n in enumerate(self._leaves())]
            heapq.heapify(heap)
            tie = len(heap)
            while heap and self._blocks > self.max_blocks:
                _, _, victim = heapq.heappop(heap)
                parent = victim.parent
                if victim.children or parent is None \
                        or parent.children.get(victim.tokens) \
                        is not victim:
                    continue           # stale heap entry
                parent.children.pop(victim.tokens, None)
                self._blocks -= 1
                self._bytes -= victim.nbytes
                dropped += 1
                if parent is not self._root and not parent.children:
                    heapq.heappush(heap, (parent.last_use, tie,
                                          parent))
                    tie += 1
        if dropped:
            inc("veles_o1_state_evictions_total", dropped)
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._root = _StateNode((), None, 0, None)
            self._blocks = 0
            self._bytes = 0

    def state_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"blocks": self._blocks,
                    "bytes": self._bytes}
