"""Unit: the dataflow node of the framework.

Equivalent of the reference's veles/units.py:59-927 (IUnit/Unit contract:
control links, gates, attribute links, demand, lifecycle) — with one
deliberate architectural change (SURVEY.md §7): in the reference, the unit
graph IS the per-minibatch dispatch engine (every unit's ``run`` enqueues a
GPU kernel from a thread pool, veles/units.py:782-505). On TPU that would
defeat XLA: here the unit graph is the *authoring and orchestration* layer.
Units whose work is on-device declare pure functions that the workflow traces
into one jitted SPMD step; the gate/link machinery below runs in plain Python
*between* steps (epoch logic, decisions, snapshots, plotting).

Gate semantics preserved from the reference (veles/units.py:139-141,280-308,
524-552):
- ``gate_block``   — when True the unit neither runs nor propagates;
- ``gate_skip``    — when True the unit does not run but still propagates;
- ``ignores_gate`` — run as soon as any upstream fires, not all.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Set, Tuple

from .config import root
from .error import BadUnitLink, Bug
from .logger import Logger
from .mutable import Bool, LinkableAttribute


class UnitRegistry(type):
    """Metaclass census of every unit class, for introspection, the CLI
    frontend and the forge (reference: veles/unit_registry.py:51)."""

    units: Set[type] = set()
    #: name → class for units registered with ``MAPPING``
    mapping: Dict[str, type] = {}

    def __init__(cls, name, bases, clsdict):
        super().__init__(name, bases, clsdict)
        if not clsdict.get("hide_from_registry", False):
            UnitRegistry.units.add(cls)
        mapping = clsdict.get("MAPPING")
        if mapping:
            existing = UnitRegistry.mapping.get(mapping)
            if existing is not None and existing.__name__ != name:
                raise Bug("duplicate unit MAPPING %r (%s vs %s)" %
                          (mapping, existing.__name__, name))
            UnitRegistry.mapping[mapping] = cls


class Unit(Logger, metaclass=UnitRegistry):
    """A node in a Workflow graph (reference: veles/units.py:108)."""

    hide_from_registry = True

    #: a unit whose ``run`` only EMITS (plots, reports, saved images,
    #: status pushes) and is never read back by the compute path may
    #: declare True: with the overlap engine on (root.common.overlap.
    #: enabled, docs/overlap.md) the scheduler dispatches its run to
    #: the async side-plane instead of blocking the step loop. Gate
    #: evaluation and downstream propagation stay inline either way —
    #: only the run body moves off-thread, so scheduling decisions are
    #: bit-identical with overlap on or off.
    side_effect_only = False

    def __init__(self, workflow, **kwargs) -> None:
        super().__init__()
        self.name: str = kwargs.pop("name", type(self).__name__)
        self.view_group: str = kwargs.pop("view_group", "PLUMBING")
        self.gate_block = Bool(False)
        self.gate_skip = Bool(False)
        self.ignores_gate = Bool(kwargs.pop("ignores_gate", False))
        #: upstream control edges: unit → fired flag
        self.links_from: Dict["Unit", bool] = {}
        #: downstream control edges
        self.links_to: Set["Unit"] = set()
        self._demanded: Set[str] = set()
        self._initialized = False
        self.timers: Dict[str, float] = {"run": 0.0}
        self.run_count = 0
        self.workflow = workflow
        if workflow is not None:
            workflow.add_ref(self)

    # -- graph wiring -------------------------------------------------------
    def link_from(self, *units: "Unit") -> "Unit":
        """Add control edges ``unit → self``
        (reference: veles/units.py:554)."""
        for u in units:
            if u is self:
                raise BadUnitLink("%s: cannot link to itself" % self.name)
            self.links_from[u] = False
            u.links_to.add(self)
        return self

    def unlink_from(self, *units: "Unit") -> "Unit":
        for u in units:
            self.links_from.pop(u, None)
            u.links_to.discard(self)
        return self

    def unlink_all(self) -> None:
        for u in list(self.links_from):
            self.unlink_from(u)
        for u in list(self.links_to):
            u.unlink_from(self)

    def link_attrs(self, other: "Unit",
                   *mappings: Any, two_way: bool = False) -> "Unit":
        """Alias attributes of ``other`` into self: each mapping is either
        ``"attr"`` or ``("my_attr", "their_attr")``
        (reference: veles/units.py:638)."""
        for m in mappings:
            mine, theirs = (m, m) if isinstance(m, str) else m
            LinkableAttribute.link(self, mine, other, theirs,
                                   two_way=two_way)
        return self

    def demand(self, *attrs: str) -> None:
        """Declare attributes that must be present (non-None) by initialize
        time (reference: veles/units.py:682)."""
        self._demanded.update(attrs)

    # -- lifecycle ----------------------------------------------------------
    @property
    def is_initialized(self) -> bool:
        return self._initialized

    def verify_demands(self) -> List[str]:
        return [a for a in sorted(self._demanded)
                if getattr(self, a, None) is None]

    def initialize(self, **kwargs) -> Optional[bool]:
        """Prepare to run. Return True to request re-queue after the rest of
        the graph initializes (partial init, reference
        veles/workflow.py:331-336)."""
        missing = self.verify_demands()
        if missing:
            self.debug("%s: waiting for demanded attrs %s", self.name,
                       missing)
            return True
        self._initialized = True
        return None

    def run(self) -> None:  # pragma: no cover - abstract
        """One unit of work. Runs between jitted steps, in Python."""

    def stop(self) -> None:
        """Cooperative cancellation hook."""

    # -- gate machinery (reference: veles/units.py:524-552,782-803) ---------
    def open_gate(self, src: "Unit") -> bool:
        """Record that ``src`` fired; True when self may proceed."""
        if src not in self.links_from:
            raise Bug("%s notified by non-upstream %s" % (self.name,
                                                          src.name))
        self.links_from[src] = True
        if bool(self.ignores_gate):
            self._reset_fired()
            return True
        if all(self.links_from.values()):
            self._reset_fired()
            return True
        return False

    def _reset_fired(self) -> None:
        for k in self.links_from:
            self.links_from[k] = False

    def process(self, side_plane=None) -> Iterable["Unit"]:
        """Run (honoring gates) and yield downstream units to notify.
        Called by the Workflow scheduler. When a side plane is given
        and this unit is ``side_effect_only``, the run body executes
        on the unit's own FIFO lane instead of inline — the scheduler
        keeps walking the graph while the I/O happens."""
        if bool(self.gate_block):
            return ()
        if not bool(self.gate_skip):
            if side_plane is not None and self.side_effect_only:
                side_plane.submit("unit." + self.name, self._timed_run)
            else:
                self._timed_run()
        # stable name order: keeps the scheduler deterministic across runs
        return tuple(sorted(self.links_to, key=lambda u: u.name))

    def _timed_run(self) -> None:
        """The instrumented run body process() executes inline or the
        side-plane lane executes async (spans nest per thread, so the
        instrumentation is identical either way)."""
        t0 = time.time()
        if root.common.trace.run:
            self.debug("running %s", self.name)
        from .telemetry.counters import inc
        from .telemetry.spans import span
        inc("veles_unit_runs_total")
        # telemetry span: nesting + per-run dispatch/transfer
        # counter deltas. The root.common.trace.spans switch is
        # honored centrally by the recorder — one knob, every site
        with span("unit.run", unit=self.name,
                  cls=type(self).__name__):
            self.run()
        self.timers["run"] += time.time() - t0
        self.run_count += 1

    def __repr__(self) -> str:
        return "<%s %r>" % (type(self).__name__, self.name)


class TrivialUnit(Unit):
    """A unit that does nothing when run (useful as a join point)."""

    hide_from_registry = True
