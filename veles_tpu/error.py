"""Framework exception types.

Equivalent of the reference's veles/error.py:1-49 (VelesException, Bug,
MasterSlaveCommunicationError), renamed for the TPU-era runtime.
"""


class VelesError(Exception):
    """Base class for all framework errors."""


class Bug(VelesError):
    """Internal invariant violation — indicates a framework bug."""


class BadUnitLink(VelesError):
    """Raised when control/data links form an invalid graph."""


class NoMoreJobs(VelesError):
    """Raised by a data source when the epoch/job stream is exhausted
    (reference: veles/workflow.py:82)."""


class DistributedCommunicationError(VelesError):
    """Coordinator/multi-host communication failure
    (reference: MasterSlaveCommunicationError, veles/error.py)."""
