"""numpy-aware JSON encoding (reference: NumpyJSONEncoder,
veles/json_encoders.py)."""

from __future__ import annotations

import json
from typing import Any

import numpy


class NumpyJSONEncoder(json.JSONEncoder):
    """Serializes numpy scalars/arrays (and sets/bytes) transparently."""

    def default(self, o: Any) -> Any:
        if isinstance(o, numpy.integer):
            return int(o)
        if isinstance(o, numpy.floating):
            return float(o)
        if isinstance(o, numpy.bool_):
            return bool(o)
        if isinstance(o, numpy.ndarray):
            return o.tolist()
        if isinstance(o, (set, frozenset)):
            return sorted(o)
        if isinstance(o, bytes):
            return o.decode(errors="replace")
        return str(o)


def dumps(obj: Any, **kwargs: Any) -> str:
    kwargs.setdefault("cls", NumpyJSONEncoder)
    return json.dumps(obj, **kwargs)
